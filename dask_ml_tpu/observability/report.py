"""Run-report CLI: aggregate a recorded JSONL metrics/trace file into a
per-component summary.

Usage::

    python -m dask_ml_tpu.observability.report metrics.jsonl
    python -m dask_ml_tpu.observability.report metrics.jsonl --json
    python -m dask_ml_tpu.observability.report trace.jsonl --perfetto out.json
    python -m dask_ml_tpu.observability.report --merge a.jsonl b.jsonl ...
    python -m dask_ml_tpu.observability.report trace.jsonl --slowest 20
    python -m dask_ml_tpu.observability.report --watch http://host:9100
    python -m dask_ml_tpu.observability.report --watch URL --interval 5
    python -m dask_ml_tpu.observability.report --watch URL --once
    python -m dask_ml_tpu.observability.report trace.jsonl --incidents DIR
    python -m dask_ml_tpu.observability.report --incidents DIR

Reads the records the subsystem emits — span records (``span`` field),
per-step solver/search records (``component`` field), stream-pass
overlap records (``stream_pass``), counter snapshots (``counters``),
program-registry snapshots (``programs``, from ``log_programs``),
sampled request traces (``req_trace``) + admitted-traffic captures
(``req_capture``, both from ``observability/_requests.py``), and
watchdog stall dumps (``watchdog``) — and prints: time per span (wall +
device-sync + measured MFU where program FLOPs were recorded),
samples/s where a span recorded its row count, each component's
convergence trajectory, streaming overlap totals, the compiled-program
cost table (compiles, compile time, FLOPs, HBM peak), watchdog stalls,
and the run's counter totals. ``--json`` emits the same content as one
machine-readable JSON object; ``--perfetto`` converts the span tree to
Chrome-trace JSON for ``ui.perfetto.dev`` (see ``export.py``). The
point (ISSUE 1/4): a recorded round's JSONL answers "where did this
fit spend its time, FLOPs and HBM" without re-running anything.

``--watch URL`` flips the CLI from post-hoc to LIVE: it polls a live
telemetry server's ``/status`` (whose ``report`` block is already
``report_data``-shaped) and ``/traces`` every ``--interval`` seconds
(default 2) and re-renders the same tables in place — programs,
serving windows, fleet federation, request traces — the top(1) of a
serving process. ``--once`` prints a single frame and exits (CI).

``--incidents DIR`` renders the black-box bundles the incident plane
captured under ``config.incident_dir`` (``observability/incidents.py``)
as an offline table — alone, or after the per-file report tables; with
``--json`` the bundles ride the same object as ``incident_bundles``.
The ``alert`` transition records the rules engine emits and the
``incident`` capture records aggregate into ``alerts``/``incidents``
tables alongside everything above.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

# the metric each component's convergence trajectory is read from, in
# preference order (first key present in its step records wins)
_LOSS_KEYS = ("loss", "inertia", "center_shift2", "primal_residual",
              "score", "opt_residual", "grad_norm")


def load_records(path):
    """Parse a JSONL file, skipping blank/corrupt lines (a crashed run
    may truncate its last line — the report must still read the rest)."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
    return records


def merge_records(record_lists):
    """Fold several processes' record lists into ONE timeline.

    The flight recorder already pid-prefixes span ids, so records from
    a bench child, a serving worker, and a multichip dryrun can share
    one report without id collisions — what they do NOT share is a time
    origin: span records carry absolute ``t_unix``, but step/stream
    records only carry the sink-relative ``time`` whose zero-point is
    per-process (per-logger, even). Per input list this estimates the
    origin as the median of (t_unix - time) over records carrying both
    (the same estimator ``export.py`` uses per component), assigns each
    record an absolute timestamp — records with neither field inherit
    their in-file predecessor's, preserving local order — and merge-
    sorts everything by it. ``final_counters``/``final_programs``'s
    "last snapshot wins" then means last *in wall-clock time*, not last
    file on the command line.
    """
    keyed = []
    seq = 0
    # fallback anchor for a legacy clock-less file (no t_unix anywhere,
    # pre-stamping writers): place it after every clocked record rather
    # than at -inf, where it would steal "first" and its counters
    # snapshot would LOSE "last in wall-clock time" to any mid-run one
    t_max = max(
        (float(r["t_unix"]) for records in record_lists
         for r in records if isinstance(r, dict) and "t_unix" in r),
        default=0.0,
    )
    for records in record_lists:
        deltas = sorted(
            float(r["t_unix"]) - float(r["time"])
            for r in records
            if isinstance(r, dict) and "t_unix" in r and "time" in r
        )
        origin = deltas[len(deltas) // 2] if deltas else None
        last = float("-inf") if origin is not None else t_max
        for r in records:
            if not isinstance(r, dict):
                continue
            if "t_unix" in r:
                t = float(r["t_unix"])
            elif origin is not None and "time" in r:
                t = origin + float(r["time"])
            else:
                t = last  # no clock: ride the neighbor, keep file order
            last = t
            keyed.append((t, seq, r))
            seq += 1
    keyed.sort(key=lambda kv: (kv[0], kv[1]))
    return [r for _, _, r in keyed]


def _fmt_seconds(s):
    return f"{s:.3f}s"


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _fmt_mfu(v):
    if v is None:
        return "-"
    return f"{v:.4f}" if v >= 1e-4 else f"{v:.1e}"


def _fmt_flops(n):
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000 or unit == "P":
            return f"{n:.3g}{unit}F" if unit else f"{n:.0f}F"
        n /= 1000.0


def _table(title, headers, rows):
    if not rows:
        return []
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [title, fmt.format(*headers),
           fmt.format(*("-" * w for w in widths))]
    out.extend(fmt.format(*(str(c) for c in r)) for r in rows)
    out.append("")
    return out


def summarize_spans(records):
    """[(key, count, wall, sync, samples/s or None, program_flops)]
    grouped by (span name, component).

    MFU caveat: ``ctr_program_flops`` deltas come from the ONE
    process-global counter registry (like every ctr_* field since the
    observability core) — tracked programs executing on OTHER threads
    while a span is open attribute their FLOPs to it too. Per-span MFU
    is exact for single-threaded runs and for spans that own their
    thread's compute (fits, serving batches); overlapping concurrent
    tracked work double-attributes across the open spans.

    Wall/sync/rows/flops are aggregated from each group's TOP-LEVEL
    spans only: a nested span of the same group (a retry inside a pass,
    a relabeled inner fit) sits INSIDE its ancestor's wall, re-reports
    rows the ancestor already counted, and its counter deltas are
    already contained in the ancestor's (one global accumulator) — so
    summing every record both double-counted rows/flops and inflated
    the wall denominator. A record whose parent chain reaches another
    record of the SAME group only contributes to the record count."""
    def span_key(r):
        if "span" not in r or r.get("watchdog"):
            return None
        key = r["span"]
        if r.get("component"):
            key = f"{r['component']}.{key}"
        return key

    groups = {}
    key_of = {}
    parent_of = {}
    keyed = [(span_key(r), r) for r in records]
    for key, r in keyed:
        if key is not None and r.get("span_id") is not None:
            key_of[r["span_id"]] = key
            parent_of[r["span_id"]] = r.get("parent_id")
    for key, r in keyed:
        if key is None:
            continue
        g = groups.setdefault(key, {"n": 0, "wall": 0.0, "sync": 0.0,
                                    "rows": 0.0, "flops": 0.0})
        g["n"] += 1
        # top-level-of-group check: walk the parent chain; any ancestor
        # in the same group already contains this record's wall, rows
        # and counter deltas
        nested = False
        pid = r.get("parent_id")
        seen = set()
        while pid is not None and pid not in seen:
            seen.add(pid)
            if key_of.get(pid) == key:
                nested = True
                break
            pid = parent_of.get(pid)
        if not nested:
            g["wall"] += float(r.get("wall_s", 0.0))
            g["sync"] += float(r.get("sync_s", 0.0))
            g["flops"] += float(r.get("ctr_program_flops", 0.0))
            g["rows"] += float(r.get("n_rows", 0.0))
    out = []
    for key in sorted(groups, key=lambda k: -groups[k]["wall"]):
        g = groups[key]
        sps = g["rows"] / g["wall"] if g["rows"] and g["wall"] > 0 else None
        out.append((key, g["n"], g["wall"], g["sync"], sps, g["flops"]))
    return out


def summarize_components(records):
    """Per-component step telemetry: record count, steps, convergence
    trajectory (first → last of the component's loss-like metric)."""
    comps = {}
    for r in records:
        if "span" in r or "component" not in r or r.get("watchdog"):
            continue
        c = comps.setdefault(r["component"], {"n": 0, "steps": set(),
                                              "key": None, "first": None,
                                              "last": None})
        c["n"] += 1
        if r.get("step") is not None:
            c["steps"].add(r["step"])
        if c["key"] is None:
            for k in _LOSS_KEYS:
                if k in r:
                    c["key"] = k
                    break
        k = c["key"]
        if k is not None and k in r:
            if c["first"] is None:
                c["first"] = float(r[k])
            c["last"] = float(r[k])
    out = []
    for name in sorted(comps):
        c = comps[name]
        traj = "-"
        if c["key"] is not None and c["first"] is not None:
            traj = f"{c['key']}: {c['first']:.6g} -> {c['last']:.6g}"
        out.append((name, c["n"], len(c["steps"]), traj))
    return out


def summarize_stream(records):
    """Streaming-pass overlap totals (from BlockStream's per-pass
    records): the double-buffer health check, plus the super-block
    dispatch amortization — a per-block pass costs one dispatch per
    block, a super-block pass one per K blocks, so dispatches/blocks
    shows the measured collapse."""
    passes = [r for r in records if "stream_pass" in r]
    if not passes:
        return None
    tot = {k: sum(float(p.get(k, 0.0)) for p in passes)
           for k in ("host_s", "put_s", "wait_s", "consume_s", "pass_s")}
    tot["n_passes"] = len(passes)
    tot["n_blocks"] = sum(int(p.get("n_blocks", 0)) for p in passes)
    # per-block passes dispatch once per block; super-block passes
    # record their own (smaller) dispatch count
    tot["dispatches"] = sum(
        int(p.get("dispatches", p.get("n_blocks", 0))) for p in passes
    )
    sb = [int(p["superblock_k"]) for p in passes if p.get("superblock_k")]
    tot["superblock_k"] = max(sb) if sb else 1
    # data-parallel width of the sharded superblock flavor (ISSUE 9):
    # 1 = single-device streaming, D = shard_map/psum scans over D chips
    sh = [int(p["sb_shards"]) for p in passes if p.get("sb_shards")]
    tot["sb_shards"] = max(sh) if sh else 1
    # 2-D mesh shape (ISSUE 18): feature-sharded passes tag "DxM"; the
    # widest mesh of the run wins (passes usually share one)
    mm = [int(p.get("sb_model_shards", 1)) for p in passes]
    tot["sb_model_shards"] = max(mm) if mm else 1
    msh = [str(p["mesh"]) for p in passes if p.get("mesh")]
    tot["mesh"] = (max(msh, key=_mesh_size) if msh
                   else f"{tot['sb_shards']}x{tot['sb_model_shards']}")
    return tot


def _mesh_size(s):
    try:
        d, m = str(s).split("x")
        return int(d) * int(m)
    except Exception:
        return 0


def summarize_drift(records):
    """The drift records (``drift.py`` emits one per scored feature /
    canary) as two table-ready lists:

    - ``scores``: train-vs-serve and window-vs-window PSI/KS grouped by
      (pair, model, version, method) — feature count, worst feature,
      max psi/ks, alert count;
    - ``canaries``: version-vs-version hot-swap deltas, one row per
      recorded canary (disagreement + max quantile shift).
    """
    groups = {}
    canaries = []
    for r in records:
        if not r.get("drift"):
            continue
        if r.get("pair") == "canary":
            canaries.append({
                "model": r.get("model"),
                "versions": f"{r.get('version_from')}"
                            f"->{r.get('version_to')}",
                "method": r.get("method"),
                "n_rows": r.get("n_rows"),
                "disagreement": r.get("disagreement"),
                "max_quantile_shift": r.get("max_quantile_shift"),
                "alert": bool(r.get("alert")),
            })
            continue
        key = (r.get("pair"), r.get("model"), r.get("version"),
               r.get("method"))
        g = groups.setdefault(key, {"features": set(), "max_psi": 0.0,
                                    "max_ks": 0.0, "worst": None,
                                    "alerts": 0})
        g["features"].add(r.get("feature"))
        psi = r.get("psi")
        if isinstance(psi, (int, float)) and psi >= g["max_psi"]:
            g["max_psi"] = float(psi)
            g["worst"] = r.get("feature")
        ks = r.get("ks")
        if isinstance(ks, (int, float)):
            g["max_ks"] = max(g["max_ks"], float(ks))
        if r.get("alert"):
            g["alerts"] += 1
    scores = []
    for (pair, model, version, method) in sorted(
            groups, key=lambda k: (str(k[0]), str(k[1]), str(k[2]))):
        g = groups[(pair, model, version, method)]
        scores.append({
            "pair": pair, "model": model, "version": version,
            "method": method, "features": len(g["features"]),
            "worst_feature": g["worst"],
            "max_psi": round(g["max_psi"], 6),
            "max_ks": round(g["max_ks"], 6),
            "alerts": g["alerts"],
        })
    return {"scores": scores, "canaries": canaries}


_TRACE_TAGS = ("replica", "version", "flavor", "rerouted_from",
               "slo_violation", "slo_shed", "fault_injected",
               "canary_scored")


def summarize_traces(records):
    """The request-trace slice of a recorded run: every sampled
    ``req_trace`` record (slowest first) plus the admitted-traffic
    capture summary (``req_capture`` records — the replay substrate).
    Trace records carry absolute ``t_unix``, so a ``--merge`` of several
    processes' files lands them on the shared wall-clock timeline and
    the pid-prefixed trace ids never collide."""
    traces = [r for r in records if r.get("req_trace")]
    traces.sort(key=lambda r: -float(r.get("e2e_s", 0.0)))
    by_outcome = {}
    for r in traces:
        o = r.get("outcome", "?")
        by_outcome[o] = by_outcome.get(o, 0) + 1
    caps = [r for r in records if r.get("req_capture")]
    capture = None
    if caps:
        by_method = {}
        rows = 0
        for c in caps:
            by_method[c.get("method", "?")] = \
                by_method.get(c.get("method", "?"), 0) + 1
            rows += int(c.get("n_rows", 0))
        ts = sorted(float(c["t_unix"]) for c in caps if "t_unix" in c)
        dur = (ts[-1] - ts[0]) if len(ts) > 1 else 0.0
        capture = {
            "requests": len(caps), "rows": rows,
            "duration_s": round(dur, 6),
            "rate_rps": round(len(caps) / dur, 3) if dur > 0 else None,
            "by_method": by_method,
        }
    return {"sampled": len(traces), "by_outcome": by_outcome,
            "traces": traces, "capture": capture}


def _numeric(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def final_counters(records):
    """The run's counter totals: the LAST explicit counters snapshot,
    else the sum of per-span counter deltas. Only NUMERIC fields
    survive — snapshot records can carry stray string/bool fields
    (extras, phase tags) that must not leak into the counters table."""
    snaps = [r for r in records if r.get("counters")]
    if snaps:
        return {k: v for k, v in snaps[-1].items()
                if k not in ("counters", "time", "t_unix", "step",
                             "component")
                and _numeric(v)}
    totals = {}
    for r in records:
        # top-level spans only: a parent span's delta already contains
        # every nested child's (the registry is one global accumulator),
        # so summing all records would double-count
        if r.get("parent_id") is not None:
            continue
        for k, v in r.items():
            if k.startswith("ctr_") and _numeric(v):
                totals[k[4:]] = totals.get(k[4:], 0) + v
    return totals


def final_programs(records):
    """The LAST program-registry snapshot (``log_programs`` record), or
    []."""
    for r in reversed(records):
        if isinstance(r.get("programs"), list):
            return r["programs"]
    return []


def final_plans(records):
    """The LAST execution-plan snapshot (rides ``log_programs`` records
    since ISSUE 15), or []: one row per planned program — plan group,
    shape ladder, the rungs that minted specializations, warmup /
    cache-hit counts."""
    for r in reversed(records):
        if isinstance(r.get("plans"), list):
            return r["plans"]
    return []


def resolved_peak(records):
    """The peak-FLOPs fields riding the last programs record (None when
    the run never recorded them — MFU columns are then skipped)."""
    for r in reversed(records):
        if r.get("peak_flop_per_s_per_chip"):
            return {
                "flop_per_s_per_chip": float(r["peak_flop_per_s_per_chip"]),
                "source": r.get("peak_source"),
                "device_kind": r.get("device_kind"),
                "n_chips": int(r.get("n_chips", 1)),
            }
    return None


def watchdog_stalls(records):
    """[(span, thread, age_s, n_threads_dumped)] per watchdog record."""
    out = []
    for r in records:
        if r.get("watchdog"):
            out.append((r.get("span"), r.get("thread"),
                        r.get("age_s"), len(r.get("stacks", {}))))
    return out


def reliability_summary(records):
    """The chaos-plane slice of the run's counters: injected faults
    (total + per-site), retry/quarantine absorption, checkpoint
    saves/resumes, replica restarts/permanent failures. [] when the run
    recorded none (the usual, fault-free case)."""
    from ..reliability import RELIABILITY_COUNTERS

    ctr = final_counters(records)
    rows = []
    for k in sorted(ctr):
        if k in RELIABILITY_COUNTERS or k.startswith("faults_injected_"):
            rows.append({"counter": k, "total": ctr[k]})
    return rows


def summarize_alerts(records):
    """The run's alert-engine state: the LAST ``alerts`` snapshot block
    (a /status scrape's synthetic record), else rule rows aggregated
    from the JSONL ``alert`` transition records the engine emits —
    last-transition-wins per rule, ``fired`` counting firing
    transitions."""
    for r in reversed(records):
        if isinstance(r.get("alerts"), dict):
            return r["alerts"]
    rules = {}
    for r in records:
        if not r.get("alert") or not r.get("rule"):
            continue
        row = rules.setdefault(r["rule"], {
            "rule": r["rule"], "kind": r.get("kind"),
            "metric": r.get("metric"), "state": "ok",
            "value": None, "since": None, "fired": 0,
        })
        firing = r.get("state") == "firing"
        row["state"] = "firing" if firing else "ok"
        row["value"] = r.get("value")
        row["since"] = r.get("t_unix")
        if firing:
            row["fired"] += 1
    rows = sorted(rules.values(), key=lambda x: x["rule"])
    return {
        "armed": bool(rows),
        "rules": rows,
        "firing": [x["rule"] for x in rows if x["state"] == "firing"],
    }


def summarize_incidents(records):
    """Captured incident bundles: the LAST ``incidents`` snapshot
    record (a /status scrape), else the JSONL ``incident`` capture
    records in order."""
    for r in reversed(records):
        if isinstance(r.get("incidents"), list):
            return r["incidents"]
    return [{"path": r.get("path"), "reason": r.get("reason"),
             "rule": r.get("rule"), "t_unix": r.get("t_unix")}
            for r in records if r.get("incident")]


def summarize_bundles(bundles):
    """Table rows for on-disk bundles (``report --incidents <dir>``):
    the capture identity plus how much context each bundle froze."""
    rows = []
    for b in bundles:
        if b.get("error"):
            rows.append({"t_unix": None, "reason": b["error"],
                         "rule": None, "open_spans": None,
                         "counters": None, "programs": None,
                         "path": b.get("path")})
            continue
        rows.append({
            "t_unix": b.get("t_unix"), "reason": b.get("reason"),
            "rule": b.get("rule"),
            "open_spans": len(b.get("open_spans") or []),
            "counters": len(b.get("counters") or {}),
            "programs": len(b.get("programs") or []),
            "path": b.get("path"),
        })
    return rows


def report_data(records):
    """The full report as one JSON-ready dict (the ``--json`` output;
    ``build_report`` renders the same content as tables)."""
    peak = resolved_peak(records)
    total_peak = (peak["flop_per_s_per_chip"] * peak["n_chips"]
                  if peak else None)
    spans = []
    for key, n, wall, sync, sps, flops in summarize_spans(records):
        row = {"span": key, "count": n, "wall_s": round(wall, 6),
               "sync_s": round(sync, 6),
               "samples_per_sec": round(sps, 1) if sps else None,
               "program_flops": flops or None}
        if flops and total_peak and wall > 0:
            row["mfu"] = round(flops / wall / total_peak, 6)
        spans.append(row)
    comps = [{"component": c, "records": n, "steps": s, "convergence": t}
             for c, n, s, t in summarize_components(records)]
    return {
        "records": len(records),
        "spans": spans,
        "components": comps,
        "streaming": summarize_stream(records),
        "drift": summarize_drift(records),
        "traces": summarize_traces(records),
        "counters": final_counters(records),
        "reliability": reliability_summary(records),
        "programs": final_programs(records),
        "plans": final_plans(records),
        "peak": peak,
        "alerts": summarize_alerts(records),
        "incidents": summarize_incidents(records),
        "watchdog_stalls": [
            {"span": s, "thread": t, "age_s": a, "threads_dumped": n}
            for s, t, a, n in watchdog_stalls(records)
        ],
    }


def _fmt_ms(s):
    if s is None:
        return "-"
    return f"{float(s) * 1e3:.2f}ms"


def _trace_flags(t):
    """Compact tag column for the traces table."""
    flags = []
    if t.get("rerouted_from") is not None:
        flags.append(f"rerouted_from={t['rerouted_from']}")
    for k in ("slo_violation", "slo_shed", "fault_injected",
              "canary_scored"):
        if t.get(k):
            flags.append(k)
    if t.get("replica") is not None:
        flags.append(f"r{t['replica']}")
    if t.get("version") is not None:
        flags.append(f"v{t['version']}")
    return ",".join(flags) or "-"


def build_report(records, path="<records>", slowest=10):
    """The full report as one string (the CLI prints it; tests assert on
    it). ``slowest`` caps the traces table at the N slowest sampled
    traces (``report ... --slowest N``)."""
    return render_report(report_data(records), path=path,
                         slowest=slowest)


def render_report(data, path="<records>", slowest=10):
    """Render a ``report_data``-shaped dict as the report tables — the
    shared back half of :func:`build_report` (post-hoc JSONL) and the
    ``--watch`` live mode (a scraped ``/status`` ``report`` block is the
    same shape, so the live view and the CLI agree by construction)."""
    lines = [f"run report: {path}  ({data.get('records') or 0} "
             f"records)", ""]
    span_rows = []
    for row in data.get("spans") or []:
        span_rows.append((
            row["span"], row["count"], _fmt_seconds(row["wall_s"]),
            _fmt_seconds(row["sync_s"]),
            f"{row['samples_per_sec']:,.0f}"
            if row["samples_per_sec"] else "-",
            _fmt_mfu(row.get("mfu")),
        ))
    lines += _table("spans (time by component)",
                    ("span", "count", "wall", "device_sync", "samples/s",
                     "mfu"),
                    span_rows)
    comp_rows = [(c["component"], c["records"], c["steps"],
                  c["convergence"]) for c in data.get("components") or []]
    lines += _table("per-step telemetry",
                    ("component", "records", "steps", "convergence"),
                    comp_rows)
    st = data.get("streaming")
    if st:
        lines += _table(
            "streaming overlap",
            ("passes", "blocks", "dispatches", "sb_k", "mesh",
             "host", "put", "wait", "consume"),
            [(st["n_passes"], st["n_blocks"], st["dispatches"],
              st["superblock_k"],
              st.get("mesh", f"{st.get('sb_shards', 1)}x1"),
              _fmt_seconds(st["host_s"]),
              _fmt_seconds(st["put_s"]), _fmt_seconds(st["wait_s"]),
              _fmt_seconds(st["consume_s"]))],
        )
    dr = data.get("drift") or {"scores": [], "canaries": []}
    if dr["scores"]:
        lines += _table(
            "drift (train vs serve / window vs window)",
            ("pair", "model", "version", "method", "features",
             "worst", "max_psi", "max_ks", "alerts"),
            [(s["pair"], s["model"], s["version"], s["method"],
              s["features"], s["worst_feature"], s["max_psi"],
              s["max_ks"], s["alerts"]) for s in dr["scores"]],
        )
    if dr["canaries"]:
        lines += _table(
            "canary (version vs version prediction deltas)",
            ("model", "versions", "method", "rows", "disagreement",
             "max_q_shift", "alert"),
            [(c["model"], c["versions"], c["method"], c["n_rows"],
              c["disagreement"], c["max_quantile_shift"],
              "ALERT" if c["alert"] else "-")
             for c in dr["canaries"]],
        )
    tr = data.get("traces") or {}
    if tr.get("sampled"):
        n_show = max(int(slowest), 1)
        shown = tr["traces"][:n_show]
        rows = []
        for t in shown:
            d = t.get("durations") or {}
            rows.append((
                t.get("trace_id"), t.get("method"), t.get("n_rows"),
                t.get("outcome"), _fmt_ms(t.get("e2e_s")),
                _fmt_ms(d.get("queue_wait")), _fmt_ms(d.get("pack")),
                _fmt_ms(d.get("execute")), _fmt_ms(d.get("demux")),
                _trace_flags(t),
            ))
        outcomes = ", ".join(f"{k}={v}" for k, v in
                             sorted(tr["by_outcome"].items()))
        lines += _table(
            f"traces ({len(shown)} slowest of {tr['sampled']} sampled; "
            f"outcomes: {outcomes})",
            ("trace", "method", "rows", "outcome", "e2e", "queue",
             "pack", "exec", "demux", "tags"),
            rows,
        )
    cap = tr.get("capture")
    if cap:
        lines += _table(
            "traffic capture (admitted request mix — replay substrate)",
            ("requests", "rows", "duration", "rate", "by_method"),
            [(cap["requests"], cap["rows"],
              _fmt_seconds(cap["duration_s"]),
              f"{cap['rate_rps']:.1f}/s" if cap["rate_rps"] else "-",
              ", ".join(f"{k}:{v}" for k, v in
                        sorted(cap["by_method"].items())))],
        )
    progs = data.get("programs") or []
    if progs:
        peak = data.get("peak")
        total_peak = (peak["flop_per_s_per_chip"] * peak["n_chips"]
                      if peak else None)
        # per-program exec_s is host-side DISPATCH time: honest on the
        # synchronous CPU backend, but under async dispatch (TPU/GPU)
        # the call returns at enqueue — an MFU built on it would be
        # inflated nonsense, so it renders only for cpu runs; the
        # per-span MFU above (wall + explicit sync barriers) is the
        # measured number everywhere
        sync_exec = bool(peak and "cpu" in
                         str(peak.get("device_kind") or "").lower())
        # plan/ladder:rung attribution column (ISSUE 15) — only when
        # any row carries it, so pre-plans records render unchanged
        has_plan = any(p.get("plan") or p.get("ladder_rung")
                       for p in progs)
        # mesh column (ISSUE 18): sharded super-block programs render
        # the "DxM" shape they were built over
        has_mesh = any(p.get("mesh") for p in progs)
        rows = []
        for p in progs:
            flops = p.get("flops_per_call")
            hbm = p.get("hbm_peak_bytes")
            exec_s = p.get("exec_s") or 0.0
            # warm-call flops only: exec_s excludes compiling calls'
            # wall, so the matching numerator must too (older records
            # without the field fall back to the full total)
            ftot = p.get("flops_exec",
                         p.get("flops_total") or 0.0) or 0.0
            mfu = (_fmt_mfu(ftot / exec_s / total_peak)
                   if sync_exec and total_peak and exec_s > 0 and ftot
                   else "-")
            row = (
                p.get("program"), p.get("compiles", 0),
                _fmt_seconds(p.get("compile_s") or 0.0),
                p.get("calls", 0),
                _fmt_flops(flops) if flops else "-",
                _fmt_bytes(hbm) if hbm else "-",
                mfu,
            )
            if has_plan:
                row += (p.get("ladder_rung") or p.get("plan") or "-",)
            if has_mesh:
                row += (p.get("mesh") or "-",)
            rows.append(row)
        title = "programs (XLA cost/memory per compiled entry point)"
        if peak:
            title += (f"  [peak {peak['flop_per_s_per_chip']:.3g} "
                      f"FLOP/s/chip x{peak['n_chips']}, "
                      f"{peak['source']}]")
        headers = ("program", "compiles", "compile_s", "calls",
                   "flops/call", "hbm_peak", "mfu")
        if has_plan:
            headers += ("plan",)
        if has_mesh:
            headers += ("mesh",)
        lines += _table(title, headers, rows)
    plans = data.get("plans") or []
    if plans:
        lines += _table(
            "plans (execution plans: ladder rungs / warmups)",
            ("program", "plan", "ladder", "rungs", "warmups",
             "warm_hits"),
            [(p.get("program"), p.get("plan"), p.get("ladder"),
              p.get("rungs"), p.get("warmups"), p.get("warm_hits"))
             for p in plans],
        )
    al = data.get("alerts") or {}
    if al.get("rules"):
        lines += _table(
            "alerts (rules engine)",
            ("rule", "kind", "state", "value", "fired"),
            [(a.get("rule"), a.get("kind"), a.get("state"),
              a.get("value") if a.get("value") is not None else "-",
              a.get("fired", 0)) for a in al["rules"]],
        )
    inc = data.get("incidents") or []
    if inc:
        lines += _table(
            "incidents (black-box bundles)",
            ("time", "reason", "rule", "path"),
            [(time.strftime("%H:%M:%S",
                            time.localtime(c["t_unix"]))
              if c.get("t_unix") else "-",
              c.get("reason"), c.get("rule") or "-", c.get("path"))
             for c in inc],
        )
    stalls = data.get("watchdog_stalls") or []
    if stalls:
        lines += _table(
            "watchdog stalls",
            ("span", "thread", "age_s", "threads_dumped"),
            [(s["span"], s["thread"], s["age_s"], s["threads_dumped"])
             for s in stalls],
        )
    rel = data.get("reliability") or []
    if rel:
        lines += _table(
            "reliability (injected faults / retries / resumes / "
            "restarts)",
            ("counter", "total"),
            [(r["counter"], r["total"]) for r in rel],
        )
    ctr = data.get("counters") or {}
    if ctr:
        rows = []
        for k in sorted(ctr):
            v = ctr[k]
            shown = _fmt_bytes(v) if k.endswith("bytes") else (
                _fmt_seconds(v) if k.endswith("secs") else v)
            rows.append((k, shown))
        lines += _table("counters", ("counter", "total"), rows)
    if not span_rows and not comp_rows and not st and not ctr \
            and not progs and not stalls and not dr["scores"] \
            and not dr["canaries"] and not tr.get("sampled") and not cap:
        lines.append("no observability records found "
                     "(set config.metrics_path or config.trace_dir)")
    return "\n".join(lines).rstrip() + "\n"


def _render_bundle_table(bundle_rows, incidents_dir):
    """The offline-bundles table as one printable string."""
    lines = _table(
        f"incident bundles ({incidents_dir})",
        ("time", "reason", "rule", "open_spans", "counters",
         "programs", "path"),
        [(time.strftime("%H:%M:%S", time.localtime(b["t_unix"]))
          if b.get("t_unix") else "-",
          b.get("reason"), b.get("rule") or "-",
          b.get("open_spans") if b.get("open_spans") is not None
          else "-",
          b.get("counters") if b.get("counters") is not None else "-",
          b.get("programs") if b.get("programs") is not None else "-",
          b.get("path")) for b in bundle_rows],
    ) or [f"incident bundles ({incidents_dir}): none found", ""]
    return "\n".join(lines).rstrip() + "\n"


# -- live watch mode (report --watch URL) ------------------------------------

def _fetch_json(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _watch_frame(url, slowest=10, timeout=5.0):
    """One rendered frame of the live view: a /status header plus
    serving + fleet tables, then the shared report tables over the
    scraped ``report`` block — with the traces table re-pointed at the
    ``/traces`` document (the recent-span ring behind ``report`` never
    holds req_trace records; the trace plane keeps its own ring)."""
    doc = _fetch_json(f"{url}/status", timeout=timeout)
    try:
        tdoc = _fetch_json(f"{url}/traces", timeout=timeout)
    except Exception:
        tdoc = None
    lines = [
        f"live: {url}  pid={doc.get('pid')}  "
        f"uptime={float(doc.get('uptime_s') or 0.0):.1f}s  "
        f"open_spans={len(doc.get('open_spans') or [])}  "
        f"({time.strftime('%H:%M:%S')})",
        "",
    ]
    # firing alerts belong in the header: an operator watching a live
    # process must see "FIRING" before any table
    firing = (doc.get("alerts") or {}).get("firing") or []
    if firing:
        lines[0] += f"  FIRING={','.join(firing)}"
    srv_rows = [
        (s.get("fleet") or s.get("model") or "-",
         s.get("healthy_replicas", s.get("replicas", "-")),
         s.get("queue_rows", "-"), s.get("version", "-"))
        for s in doc.get("serving") or []
    ]
    lines += _table("serving",
                    ("fleet", "healthy", "queue_rows", "version"),
                    srv_rows)
    fl = doc.get("fleet")
    if fl:
        slo = fl.get("slo") or {}
        lines += _table(
            "fleet federation",
            ("federation", "processes", "requests", "violations",
             "burn_rate", "alerts", "scrape"),
            [(fl.get("federation"), fl.get("n_scraped"),
              slo.get("requests"), slo.get("violations"),
              slo.get("burn_rate"), len(slo.get("alerts") or []),
              _fmt_ms(fl.get("scrape_seconds")))],
        )
    data = dict(doc.get("report") or {})
    if tdoc and tdoc.get("traces"):
        data["traces"] = summarize_traces(tdoc["traces"])
    lines.append(render_report(data, path=url, slowest=slowest))
    return "\n".join(lines)


def watch(url, interval=2.0, once=False, slowest=10):
    """Poll a live telemetry server and re-render the report in place —
    the top(1) of a serving process. ``once`` renders a single frame
    with no screen clear and returns (CI / scripting mode)."""
    url = str(url).rstrip("/")
    while True:
        ok = True
        try:
            frame = _watch_frame(url, slowest=slowest)
        except Exception as e:
            ok = False
            frame = f"live: {url}  (unreachable: {e})"
        if once:
            sys.stdout.write(frame.rstrip() + "\n")
            return 0 if ok else 1
        # ANSI clear + home: re-render in place, no curses dependency
        sys.stdout.write("\x1b[2J\x1b[H" + frame.rstrip() + "\n")
        sys.stdout.flush()
        time.sleep(max(float(interval), 0.1))


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    as_json = False
    merge = False
    perfetto_out = None
    slowest = 10
    watch_url = None
    interval = 2.0
    once = False
    incidents_dir = None
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--json":
            as_json = True
        elif a == "--merge":
            merge = True
        elif a == "--watch":
            if i + 1 >= len(argv):
                print("error: --watch needs a live telemetry URL",
                      file=sys.stderr)
                return 2
            i += 1
            watch_url = argv[i]
        elif a == "--interval":
            if i + 1 >= len(argv):
                print("error: --interval needs seconds",
                      file=sys.stderr)
                return 2
            i += 1
            try:
                interval = float(argv[i])
            except ValueError:
                print(f"error: --interval needs a number, got "
                      f"{argv[i]!r}", file=sys.stderr)
                return 2
        elif a == "--once":
            once = True
        elif a == "--incidents":
            if i + 1 >= len(argv):
                print("error: --incidents needs a bundle directory",
                      file=sys.stderr)
                return 2
            i += 1
            incidents_dir = argv[i]
        elif a == "--perfetto":
            if i + 1 >= len(argv):
                print("error: --perfetto needs an output path",
                      file=sys.stderr)
                return 2
            i += 1
            perfetto_out = argv[i]
        elif a == "--slowest":
            if i + 1 >= len(argv):
                print("error: --slowest needs a count", file=sys.stderr)
                return 2
            i += 1
            try:
                slowest = int(argv[i])
            except ValueError:
                print(f"error: --slowest needs an integer, got "
                      f"{argv[i]!r}", file=sys.stderr)
                return 2
        else:
            paths.append(a)
        i += 1
    if watch_url is not None:
        try:
            return watch(watch_url, interval=interval, once=once,
                         slowest=slowest)
        except KeyboardInterrupt:
            return 0
    # offline incident bundles (report [trace.jsonl] --incidents DIR):
    # rendered after the per-file reports, or alone with no inputs
    bundle_rows = None
    if incidents_dir is not None:
        from .incidents import load_bundles

        bundle_rows = summarize_bundles(load_bundles(incidents_dir))
    if not paths:
        if bundle_rows is None:
            print("error: no input JSONL files", file=sys.stderr)
            return 2
        if as_json:
            sys.stdout.write(json.dumps(
                {"incident_bundles": bundle_rows}) + "\n")
        else:
            sys.stdout.write(_render_bundle_table(bundle_rows,
                                                  incidents_dir))
        return 0
    if perfetto_out is not None and len(paths) > 1 and not merge:
        # one output path per invocation: silently overwriting it per
        # input would keep only the last file's trace (--merge folds
        # the inputs into ONE trace, which is the multi-file story)
        print("error: --perfetto takes exactly one input JSONL "
              f"(got {len(paths)}); run once per file or pass --merge",
              file=sys.stderr)
        return 2
    rc = 0
    if merge:
        # one merged timeline: every input contributes to a single
        # report/trace instead of one report per file
        lists = []
        for path in paths:
            try:
                lists.append(load_records(path))
            except OSError as e:
                print(f"error: cannot read {path}: {e}", file=sys.stderr)
                rc = 1
        if not lists:
            return rc or 1
        merged = merge_records(lists)
        label = " + ".join(paths)
        if perfetto_out is not None:
            from .export import write_chrome_trace

            try:
                trace = write_chrome_trace(merged, perfetto_out)
            except OSError as e:
                print(f"error: cannot write {perfetto_out}: {e}",
                      file=sys.stderr)
                return 1
            print(f"wrote {len(trace['traceEvents'])} trace events "
                  f"-> {perfetto_out}  (open in ui.perfetto.dev)",
                  file=sys.stderr)
        if as_json:
            data = report_data(merged)
            data["path"] = label
            data["merged_files"] = len(lists)
            if bundle_rows is not None:
                data["incident_bundles"] = bundle_rows
            sys.stdout.write(json.dumps(data) + "\n")
        elif perfetto_out is None:
            sys.stdout.write(build_report(merged, path=label,
                                          slowest=slowest))
            if bundle_rows is not None:
                sys.stdout.write(_render_bundle_table(bundle_rows,
                                                      incidents_dir))
        return rc
    for path in paths:
        try:
            records = load_records(path)
        except OSError as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            rc = 1
            continue
        if perfetto_out is not None:
            from .export import write_chrome_trace

            try:
                trace = write_chrome_trace(records, perfetto_out)
            except OSError as e:
                print(f"error: cannot write {perfetto_out}: {e}",
                      file=sys.stderr)
                rc = 1
                continue
            # stderr: --json promises machine-readable stdout, and the
            # flags combine
            print(f"wrote {len(trace['traceEvents'])} trace events "
                  f"-> {perfetto_out}  (open in ui.perfetto.dev)",
                  file=sys.stderr)
        if as_json:
            data = report_data(records)
            data["path"] = path
            if bundle_rows is not None:
                data["incident_bundles"] = bundle_rows
            sys.stdout.write(json.dumps(data) + "\n")
        elif perfetto_out is None:
            sys.stdout.write(build_report(records, path=path,
                                          slowest=slowest))
    if bundle_rows is not None and not as_json and perfetto_out is None:
        sys.stdout.write(_render_bundle_table(bundle_rows,
                                              incidents_dir))
    return rc


if __name__ == "__main__":
    sys.exit(main())
