"""Multiclass one-vs-rest LogisticRegression (beyond the reference's
binary-only dask-glm logistic family): the C per-class solves run as one
vmapped XLA program for smooth solvers; predict/proba follow sklearn's
OvR contract."""

import numpy as np
import pytest
from sklearn.datasets import make_classification as sk_make

from dask_ml_tpu.linear_model import LogisticRegression


@pytest.fixture(scope="module")
def data3():
    X, y = sk_make(n_samples=600, n_features=10, n_informative=6,
                   n_classes=3, random_state=0)
    return X.astype(np.float32), y.astype(np.float32)


def test_ovr_attributes_and_accuracy(data3):
    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=200).fit(X, y)
    assert clf.coef_.shape == (3, X.shape[1])
    assert clf.intercept_.shape == (3,)
    np.testing.assert_array_equal(clf.classes_, [0.0, 1.0, 2.0])

    from sklearn.linear_model import LogisticRegression as SkLR

    ref = SkLR(max_iter=500).fit(X, y)
    ours_acc = (clf.predict(X) == y).mean()
    ref_acc = ref.score(X, y)
    assert ours_acc > ref_acc - 0.05  # OvR vs multinomial: close, not equal


def test_ovr_predict_proba_contract(data3):
    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (len(X), 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert (proba >= 0).all()
    # argmax of proba equals predict
    np.testing.assert_array_equal(
        clf.classes_[np.argmax(proba, axis=1)], clf.predict(X)
    )
    eta = clf.decision_function(X)
    assert eta.shape == (len(X), 3)


@pytest.mark.parametrize("solver", ["newton", "admm"])
def test_ovr_loop_solvers(data3, solver):
    X, y = data3
    clf = LogisticRegression(solver=solver, max_iter=30).fit(X, y)
    assert clf.coef_.shape == (3, X.shape[1])
    assert (clf.predict(X) == y).mean() > 0.6


@pytest.mark.slow
def test_ovr_in_grid_search(data3):
    from dask_ml_tpu.model_selection import GridSearchCV

    X, y = data3
    s = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=60),
        {"C": [0.1, 1.0]}, cv=2,
    ).fit(X, y)
    assert s.best_score_ > 0.6
    assert s.predict(X).shape == (len(X),)


def test_ovr_sharded_input(data3):
    from dask_ml_tpu.parallel import as_sharded

    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=100).fit(
        as_sharded(X), as_sharded(y)
    )
    host = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
    np.testing.assert_allclose(clf.coef_, host.coef_, atol=1e-3)


def test_single_class_still_raises(data3):
    X, _ = data3
    with pytest.raises(ValueError, match="class"):
        LogisticRegression(max_iter=10).fit(
            X, np.zeros(len(X), np.float32)
        )


def test_multinomial_multi_class_rejected(data3):
    X, y = data3
    with pytest.raises(ValueError, match="multi_class"):
        LogisticRegression(multi_class="multinomial", max_iter=10).fit(X, y)


@pytest.mark.slow
def test_ovr_streamed_predict_and_fit(tmp_path, data3):
    """Multiclass predict AND fit stream block-wise over memmaps like
    the binary path (VERDICT r3 missing #2): the streamed OvR fit
    matches the in-core vmapped OvR solve."""
    from dask_ml_tpu import config

    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=60).fit(X, y)
    path = tmp_path / "X.f32"
    X.tofile(path)
    Xm = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)
    with config.set(stream_block_rows=128):
        eta = clf.decision_function(Xm)
        pred = clf.predict(Xm)
    assert eta.shape == (len(X), 3)
    np.testing.assert_array_equal(pred, clf.predict(X))
    with config.set(stream_block_rows=128):
        st = LogisticRegression(solver="lbfgs", max_iter=80,
                                tol=1e-7).fit(Xm, y)
    assert st.solver_info_["streamed"] is True
    assert st.solver_info_["n_blocks"] > 1
    assert st.solver_info_["n_classes"] == 3
    ref = LogisticRegression(solver="lbfgs", max_iter=80, tol=1e-7).fit(X, y)
    assert st.coef_.shape == ref.coef_.shape == (3, X.shape[1])
    np.testing.assert_allclose(st.coef_, ref.coef_, rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(st.intercept_, ref.intercept_, rtol=5e-2,
                               atol=5e-3)
    assert np.mean(st.predict(X) == ref.predict(X)) > 0.99


@pytest.mark.parametrize("solver,penalty", [
    ("newton", "l2"),
    ("admm", "l1"),
    ("proximal_grad", "elastic_net"),
])
def test_ovr_streamed_all_solvers(tmp_path, data3, solver, penalty):
    """Every streamed solver family handles multiclass: one data pass
    per epoch shared across the C one-vs-rest problems."""
    from dask_ml_tpu import config

    X, y = data3
    kw = dict(solver=solver, penalty=penalty, C=1.0, max_iter=120, tol=1e-7)
    ref = LogisticRegression(**kw).fit(X, y)
    with config.set(stream_block_rows=128):
        st = LogisticRegression(**kw).fit(X.copy(), y)
    assert st.solver_info_["streamed"] is True
    assert st.solver_info_["n_classes"] == 3
    assert np.mean(st.predict(X) == ref.predict(X)) > 0.98


def test_warm_start_binary_after_multiclass(data3):
    """A stale (C, d) coef_ must not leak into a later binary solve."""
    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=30, warm_start=True)
    clf.fit(X, y)
    assert clf.coef_.shape[0] == 3
    yb = (y > 0).astype(np.float32)
    clf.fit(X, yb)
    assert clf.coef_.shape == (1, X.shape[1])
    assert clf.score(X, yb) > 0.5


def test_solver_kwargs_checkpoint_takes_loop_path(tmp_path, data3):
    """checkpoint kwargs are honored for multiclass (per-class loop
    rather than the vmapped program that cannot checkpoint)."""
    X, y = data3
    p = str(tmp_path / "ck")
    clf = LogisticRegression(
        solver="lbfgs", max_iter=12,
        solver_kwargs={"checkpoint_path": p, "checkpoint_every": 4},
    ).fit(X, y)
    assert clf.coef_.shape == (3, X.shape[1])


def test_predict_log_proba(data3):
    """sklearn API: log of predict_proba, -inf allowed on exact zeros
    (shared base.log_proba implementation, same as GaussianNB)."""
    X, y = data3
    clf = LogisticRegression(solver="lbfgs", max_iter=60).fit(X, y)
    lp = clf.predict_log_proba(X)
    assert lp.shape == (len(X), 3)
    np.testing.assert_allclose(np.exp(lp), clf.predict_proba(X), atol=1e-7)
    yb = (y > 0).astype(np.float32)
    clfb = LogisticRegression(solver="lbfgs", max_iter=60).fit(X, yb)
    np.testing.assert_allclose(np.exp(clfb.predict_log_proba(X)),
                               clfb.predict_proba(X), atol=1e-7)
