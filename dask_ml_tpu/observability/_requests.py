"""Per-request lifecycle tracing (the serving request trace plane).

The observability plane sees batches, programs, and processes; this
module sees the REQUEST — the unit a serving fleet is actually debugged
by. Every admitted request can carry a :class:`RequestTrace` recording
stage timestamps (admit → queue-pop → coalesce/pack → dispatch →
execute-done → demux → complete) plus outcome tags (bucket, flavor,
replica, version, reroutes, SLO sheds/violations, injected faults,
canary scoring). A **tail sampler** keeps the full stage breakdown only
for interesting traces — errors, timeouts, sheds, SLO violations,
reroutes, fault-injected batches, and the rolling slowest
``config.obs_trace_sample`` fraction of ordinary completions — while
EVERY completion folds its stage durations into per-stage exemplar
histograms (each bucket remembers one recent trace id, so a scraped
p99 links to a concrete trace).

Zero-overhead contract, same as every prior plane:
``obs_trace_sample=0`` means no trace object is ever allocated on the
serving hot path (``ModelServer`` captures the gate ONCE at
construction as ``self._trace_on``), the serving jaxprs stay
byte-identical, and nothing here ever imports jax or enters a trace.
Trace ids carry the pid in their high bits (the ``_spans._ids``
convention) so multi-process trace files merge and lane correctly in
the report CLI and the Perfetto export.

The plane is also ROADMAP 4(c)'s traffic-capture substrate: with a
trace sink configured (``trace_dir``/``metrics_path``), every admitted
request appends one ``req_capture`` JSONL record (method, rows, admit
wall clock) and every SAMPLED trace one ``req_trace`` record — the
exact format :func:`load_capture`/:func:`replay` round-trip for
traffic replay.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from bisect import bisect_left
from collections import deque

from ..config import get_config
from ._hist import DEFAULT_BOUNDS, Histogram
from ._spans import _T0, _trace_sink

__all__ = [
    "STAGES",
    "RequestTrace",
    "load_capture",
    "new_trace",
    "replay",
    "tagging",
    "trace_context",
    "traces_data",
    "traces_reset",
    "tracing_enabled",
]

# pid-prefixed ids, the _spans._ids convention: two processes appending
# into one shared trace.jsonl must not collide ids, and the report's
# merge/Perfetto laning reads the process back out of id >> 24.
_trace_ids = itertools.count(((os.getpid() & 0xFFFFFF) << 24) | 1)

# lifecycle stages in order. Consecutive stamps telescope: the sum of
# present stage-to-stage durations equals complete - admit exactly.
STAGES = ("admit", "queue_pop", "pack", "dispatch", "execute_done",
          "demux", "complete")

# named stage-pair durations; the middle three carry the /metrics-facing
# histogram families (queue wait broken OUT of the end-to-end
# serving_latency_seconds family, which stays end-to-end).
_DUR_DEFS = (
    ("queue_wait", "admit", "queue_pop"),
    ("pack", "queue_pop", "pack"),
    ("dispatch", "pack", "dispatch"),
    ("execute", "dispatch", "execute_done"),
    ("demux", "execute_done", "demux"),
    ("resolve", "demux", "complete"),
)
_LIVE_HIST = {
    "queue_wait": "serving_queue_wait_seconds",
    "pack": "serving_pack_seconds",
    "demux": "serving_demux_seconds",
}

# tags that make a trace unconditionally interesting to the tail
# sampler (beyond a non-"ok" outcome)
_ALWAYS_KEEP_TAGS = ("rerouted_from", "rerouted_from_process",
                     "fault_injected", "slo_violation", "slo_shed")

_lock = threading.Lock()
_kept: deque | None = None          # sampled trace records, newest last
_hists: dict[str, "_ExemplarHist"] = {}
_counts = {"started": 0, "completed": 0, "sampled": 0, "captured": 0}
_RING = 256                          # rolling e2e window for slowest-p
_ring: list = []
_ring_i = 0
_ring_n = 0                          # completions folded in (ever)
_thresh: float | None = None

_tls = threading.local()
_live = None                         # .live module, bound on first use
                                     # (top-level import would be a cycle)


def tracing_enabled() -> bool:
    """One config read: is the request trace plane on? ``ModelServer``
    captures this once at construction; the fleet door (which has no
    construction-time hot path) reads it per submit."""
    return float(get_config().obs_trace_sample) > 0.0


@contextlib.contextmanager
def tagging(**tags):
    """Thread-local pending tags: traces created inside the block start
    with ``tags`` pre-applied. The fleet's failover loop wraps its
    retry submit in ``tagging(rerouted_from=<corpse id>)`` so the
    surviving replica's trace records where the request came from."""
    stack = getattr(_tls, "tags", None)
    if stack is None:
        stack = _tls.tags = []
    stack.append(tags)
    try:
        yield
    finally:
        stack.pop()


def _pending_tags() -> dict:
    stack = getattr(_tls, "tags", None)
    if not stack:
        return {}
    out = {}
    for t in stack:
        out.update(t)
    return out


@contextlib.contextmanager
def trace_context(trace_id):
    """Thread-local pending trace id: traces created inside the block
    CONTINUE ``trace_id`` instead of minting a fresh one. This is the
    cross-process continuation primitive — the federation receive side
    wraps its fleet submit in the router's X-Trace-Context id, so the
    remote stages land on the SAME pid-prefixed trace the router
    started (collision-free: the id was minted exactly once, at the
    router, and no other trace in the receiving process can carry its
    pid prefix)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = int(trace_id)
    try:
        yield
    finally:
        _tls.ctx = prev


def _pending_ctx():
    return getattr(_tls, "ctx", None)


class _ExemplarHist:
    """A :class:`Histogram` whose buckets each remember the most recent
    trace id that landed there — the exemplar a scraped quantile links
    back to a concrete sampled (or folded) request. Exposed through the
    JSON ``/traces`` surface only: the Prometheus text exposition stays
    grammar-clean (no OpenMetrics exemplar syntax)."""

    __slots__ = ("hist", "exemplars")

    def __init__(self, bounds=None):
        self.hist = Histogram(bounds)
        self.exemplars = [None] * (len(self.hist.bounds) + 1)

    def observe(self, value: float, trace_id: int) -> None:
        self.hist.observe(value)
        self.exemplars[bisect_left(self.hist.bounds, float(value))] = \
            int(trace_id)

    def snapshot(self) -> dict:
        snap = self.hist.snapshot()
        snap["bounds"] = list(snap["bounds"])
        snap["exemplars"] = list(self.exemplars)
        return snap


class RequestTrace:
    """One request's lifecycle: stage stamps (``time.perf_counter``),
    outcome tags, and the thread names the Perfetto export lanes flow
    events across. Never touches jax; everything is host-side."""

    __slots__ = ("trace_id", "method", "n_rows", "t_unix", "stages",
                 "tags", "threads", "_finished")

    def __init__(self, method, n_rows, t_admit=None):
        ctx = _pending_ctx()
        self.trace_id = next(_trace_ids) if ctx is None else int(ctx)
        self.method = str(method)
        self.n_rows = int(n_rows)
        self.t_unix = time.time()
        self.stages = {
            "admit": time.perf_counter() if t_admit is None else t_admit,
        }
        self.tags = _pending_tags()
        self.threads = {"admit": threading.current_thread().name}
        self._finished = False

    def stamp(self, stage: str, t=None) -> None:
        self.stages[stage] = time.perf_counter() if t is None else t
        if stage == "queue_pop" and "worker" not in self.threads:
            self.threads["worker"] = threading.current_thread().name

    def tag(self, **kw) -> None:
        self.tags.update(kw)

    def finish(self, outcome: str = "ok") -> None:
        """Terminal stamp + tail-sampler decision + histogram folds +
        capture-sink write. Idempotent: a request failed after a partial
        demux finishes once, with the first outcome."""
        _finish(self, outcome)


def new_trace(method, n_rows, t_admit=None) -> RequestTrace:
    """Allocate a trace for one admitted request, pick up any pending
    thread-local tags, and append its ``req_capture`` traffic record to
    the trace sink (when one is configured). Call sites gate on
    ``tracing_enabled()`` / a captured ``_trace_on`` — this function is
    never reached when the plane is off."""
    tr = RequestTrace(method, n_rows, t_admit=t_admit)
    with _lock:
        _counts["started"] += 1
    _capture(tr)
    return tr


def _capture(tr: RequestTrace) -> None:
    sink = _trace_sink()
    if sink is None:
        return
    try:
        sink.log(
            req_capture=True, trace_id=tr.trace_id, pid=os.getpid(),
            method=tr.method, n_rows=tr.n_rows,
            t_unix=round(tr.t_unix, 6),
        )
    except Exception:
        return  # telemetry must never fail the request it observes
    with _lock:
        _counts["captured"] += 1


def _slow_threshold(e2e: float, p: float) -> float:
    """Rolling (1 - p) quantile over the last ``_RING`` end-to-end
    latencies, recomputed every 32 completions (a 256-element sort per
    request would be measurable; a cached threshold is one compare).
    The cadence counts COMPLETIONS (``_ring_n``), not ring occupancy —
    once the ring is full its length never changes, so a length-based
    cadence would degenerate into a sort per request."""
    global _thresh, _ring_i, _ring_n
    with _lock:
        if len(_ring) < _RING:
            _ring.append(e2e)
        else:
            _ring[_ring_i] = e2e
            _ring_i = (_ring_i + 1) % _RING
        _ring_n += 1
        n = len(_ring)
        if _thresh is None or n < 32 or _ring_n % 32 == 0:
            s = sorted(_ring)
            k = min(n - 1, max(0, int((1.0 - min(p, 1.0)) * n)))
            _thresh = s[k]
        return _thresh


def _finish(tr: RequestTrace, outcome: str) -> None:
    if tr._finished:
        return
    tr._finished = True
    st = tr.stages
    if "complete" not in st:
        st["complete"] = time.perf_counter()
    t0 = st["admit"]
    e2e = st["complete"] - t0

    cfg = get_config()
    p = float(cfg.obs_trace_sample)

    # fold stage-pair durations into the exemplar histograms, and
    # mirror the three /metrics families into the live registry with
    # the same {method, bucket} labels serving_latency_seconds carries
    durs = {}
    for name, a, b in _DUR_DEFS:
        ta, tb = st.get(a), st.get(b)
        if ta is None or tb is None:
            continue
        durs[name] = tb - ta
    with _lock:
        _counts["completed"] += 1
        for name, v in durs.items():
            h = _hists.get(name)
            if h is None:
                h = _hists[name] = _ExemplarHist()
            h.observe(v, tr.trace_id)
    bucket = tr.tags.get("bucket")
    if bucket is not None:
        global _live
        if _live is None:
            from . import live as _live_mod
            _live = _live_mod
        if _live.live_publishing():
            labels = (("method", tr.method), ("bucket", str(int(bucket))))
            for name, fam in _LIVE_HIST.items():
                if name in durs:
                    hist = _live.histogram(fam, labels=labels)
                    if hist is not None:
                        hist.observe(durs[name])

    # tail sampler: errors / sheds / SLO trouble / reroutes / injected
    # faults are ALWAYS kept; ordinary completions only when they land
    # in the rolling slowest-p fraction (p >= 1 keeps everything, so the
    # quantile ring is skipped entirely)
    interesting = outcome != "ok" or any(
        tr.tags.get(k) for k in _ALWAYS_KEEP_TAGS
    )
    if not interesting and p > 0:
        interesting = p >= 1.0 or e2e >= _slow_threshold(e2e, p)
    if not interesting:
        return

    rec = {
        "req_trace": True,
        "trace_id": tr.trace_id,
        "pid": os.getpid(),
        "method": tr.method,
        "n_rows": tr.n_rows,
        "t_unix": round(tr.t_unix, 6),
        "e2e_s": round(e2e, 6),
        "outcome": outcome,
        "stages": {s: round(st[s] - t0, 6) for s in STAGES if s in st},
        "durations": {k: round(v, 6) for k, v in durs.items()},
        "threads": dict(tr.threads),
    }
    for k, v in tr.tags.items():
        rec.setdefault(k, v)
    global _kept
    with _lock:
        if _kept is None:
            _kept = deque(maxlen=max(int(cfg.obs_trace_keep), 1))
        _kept.append(rec)
        _counts["sampled"] += 1
    sink = _trace_sink()
    if sink is not None:
        try:
            # "time" pinned to the ADMIT instant (sink default would be
            # the completion write time) so the merged timeline and the
            # Perfetto flow events start where the request actually did
            sink.log(time=round(tr.t_unix - _T0, 6), **rec)
        except Exception:
            pass


def traces_data() -> dict:
    """The ``/traces`` JSON document: sampler counters, the retained
    sampled traces (oldest first), and the per-stage exemplar
    histograms."""
    with _lock:
        kept = [dict(r) for r in _kept] if _kept is not None else []
        counts = dict(_counts)
        hists = {name: h.snapshot() for name, h in sorted(_hists.items())}
    return {"counts": counts, "traces": kept,
            "stage_histograms": hists}


def traces_reset() -> None:
    """Forget every kept trace, histogram, and sampler state (test
    isolation; also re-latches ``obs_trace_keep`` on next sample)."""
    global _kept, _thresh, _ring_i, _ring_n
    with _lock:
        _kept = None
        _hists.clear()
        _ring.clear()
        _ring_i = 0
        _ring_n = 0
        _thresh = None
        for k in _counts:
            _counts[k] = 0


# -- traffic capture replay (ROADMAP 4c substrate) ---------------------------

def load_capture(path) -> list:
    """The admitted-traffic records (``req_capture``) out of a trace
    JSONL file, sorted by admit wall clock. Corrupt lines are skipped —
    same contract as the report CLI's loader."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if isinstance(r, dict) and r.get("req_capture"):
                records.append(r)
    records.sort(key=lambda r: r.get("t_unix", 0.0))
    return records


def replay(records, submit, speed: float = 1.0) -> dict:
    """Re-issue a captured traffic mix: calls ``submit(method, n_rows)``
    for each record at the recorded inter-arrival spacing (scaled by
    ``1/speed``; ``speed=10`` replays 10x faster). Returns the replayed
    mix summary — the stub ROADMAP 4(c)'s full replay harness will grow
    from, and the round-trip witness that a capture file reproduces the
    recorded (method, rows, rate) mix."""
    by_method: dict[str, int] = {}
    rows = 0
    if not records:
        return {"requests": 0, "rows": 0, "duration_s": 0.0,
                "rate_rps": 0.0, "by_method": by_method}
    t_first = records[0].get("t_unix", 0.0)
    start = time.perf_counter()
    for r in records:
        delay = (r.get("t_unix", t_first) - t_first) / max(speed, 1e-9) \
            - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        submit(r["method"], int(r["n_rows"]))
        by_method[r["method"]] = by_method.get(r["method"], 0) + 1
        rows += int(r["n_rows"])
    dur = time.perf_counter() - start
    return {
        "requests": len(records),
        "rows": rows,
        "duration_s": round(dur, 6),
        "rate_rps": round(len(records) / dur, 3) if dur > 0 else 0.0,
        "by_method": by_method,
    }
