"""PartitionedFrame — the scale-out frame substrate (SURVEY.md §1 L2:
the reference's dd.DataFrame role). Global-category correctness across
partitions is the load-bearing property: a category seen in only ONE
partition must appear in every partition's dtype."""

import numpy as np
import pandas as pd
import pytest

from dask_ml_tpu.parallel import PartitionedFrame, from_pandas


@pytest.fixture()
def df():
    rng = np.random.RandomState(0)
    n = 200
    return pd.DataFrame({
        "a": rng.randn(n),
        "b": rng.randint(0, 5, n).astype(np.int64),
        "c": np.where(rng.rand(n) < 0.5, "x", "y"),
    })


def test_roundtrip_and_metadata(df):
    pf = from_pandas(df, npartitions=4)
    assert pf.npartitions == 4
    assert len(pf) == len(df)
    assert list(pf.columns) == ["a", "b", "c"]
    pd.testing.assert_frame_equal(pf.compute(), df)


def test_map_partitions_and_getitem(df):
    pf = from_pandas(df, npartitions=4)
    doubled = pf.map_partitions(lambda p: p.assign(a=p.a * 2))
    np.testing.assert_allclose(doubled.compute()["a"], df["a"] * 2)
    sub = pf[["a", "b"]]
    assert list(sub.columns) == ["a", "b"]
    lens = pf.map_partitions(len)
    assert sum(lens) == len(df)


def test_global_categories_cross_partition(df):
    # "z" exists ONLY in the last partition
    df = df.copy()
    df.iloc[-1, df.columns.get_loc("c")] = "z"
    pf = from_pandas(df, npartitions=4)
    from dask_ml_tpu.preprocessing import Categorizer

    cat = Categorizer().fit(pf)
    out = cat.transform(pf)
    for p in out.partitions:
        assert set(p["c"].cat.categories) == {"x", "y", "z"}
    # parity with the single-frame pandas path
    single = Categorizer().fit(df)
    assert set(single.categories_["c"].categories) == \
        set(cat.categories_["c"].categories)


def test_dummy_and_ordinal_over_partitions(df):
    from dask_ml_tpu.preprocessing import (
        Categorizer, DummyEncoder, OrdinalEncoder,
    )

    pf = from_pandas(df, npartitions=4)
    cat_pf = Categorizer().fit(pf).transform(pf)
    # DummyEncoder: partitioned result equals pandas result
    enc = DummyEncoder().fit(cat_pf)
    out = enc.transform(cat_pf)
    ref_df = Categorizer().fit(df).transform(df)
    ref = DummyEncoder().fit(ref_df).transform(ref_df)
    pd.testing.assert_frame_equal(out.compute(), ref)
    # OrdinalEncoder: codes agree with pandas path
    out2 = OrdinalEncoder().fit(cat_pf).transform(cat_pf)
    ref2 = OrdinalEncoder().fit(ref_df).transform(ref_df)
    pd.testing.assert_frame_equal(out2.compute(), ref2)


def test_to_sharded_bridge_end_to_end(df):
    """frame → categorize → dummy-encode → device array → GLM fit: the
    full frame-to-TPU pipeline."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import ShardedArray, as_sharded
    from dask_ml_tpu.preprocessing import Categorizer, DummyEncoder

    pf = from_pandas(df, npartitions=4)
    enc = DummyEncoder()
    cat_pf = Categorizer().fit(pf).transform(pf)
    feats = enc.fit(cat_pf).transform(cat_pf)
    Xs = feats.to_sharded()
    assert isinstance(Xs, ShardedArray)
    assert Xs.shape == (len(df), len(enc.transformed_columns_))
    y = (df["a"] > 0).astype(np.float32).to_numpy()
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(
        Xs, as_sharded(y)
    )
    assert clf.score(Xs, as_sharded(y)) > 0.9


def test_train_test_split_frames(df):
    from dask_ml_tpu.model_selection import train_test_split

    pf = from_pandas(df, npartitions=4)
    y = from_pandas(df[["b"]], npartitions=4)
    tr, te, ytr, yte = train_test_split(pf, y, test_size=0.25,
                                        random_state=0)
    assert isinstance(tr, PartitionedFrame)
    assert len(tr) + len(te) == len(df)
    assert len(ytr) == len(tr) and len(yte) == len(te)
    # blockwise: every partition contributed to both sides
    assert all(len(p) for p in tr.partitions)
    assert all(len(p) for p in te.partitions)
    # disjoint rows (index-based)
    assert not set(tr.compute().index) & set(te.compute().index)

    # global (non-blockwise) split also works
    tr2, te2 = train_test_split(pf, test_size=0.25, blockwise=False,
                                random_state=0)
    assert len(tr2) + len(te2) == len(df)

    with pytest.raises(ValueError, match="identical partition"):
        train_test_split(pf, from_pandas(df, npartitions=3))


def test_mismatched_partitions_rejected(df):
    with pytest.raises(ValueError, match="mismatched columns"):
        PartitionedFrame([df[["a"]], df[["b"]]])


def test_scalers_frame_in_frame_out(df):
    """Scalers consume frames and return the SAME frame type with the
    original columns/index/partition boundaries (the reference's dd
    frame-in/frame-out scaler contract,
    ref: dask_ml/preprocessing/data.py::StandardScaler dd path)."""
    import sklearn.preprocessing as skp

    from dask_ml_tpu.preprocessing import (
        MinMaxScaler, QuantileTransformer, RobustScaler, StandardScaler,
    )

    num = df[["a", "b"]].astype(np.float64)
    pf = from_pandas(num, npartitions=4)
    cases = [
        (StandardScaler(), skp.StandardScaler()),
        (MinMaxScaler(), skp.MinMaxScaler()),
        (RobustScaler(), skp.RobustScaler()),
        (QuantileTransformer(n_quantiles=50),
         skp.QuantileTransformer(n_quantiles=50)),
    ]
    for ours, ref in cases:
        out = ours.fit(pf).transform(pf)
        assert isinstance(out, PartitionedFrame)
        assert [len(p) for p in out.partitions] == \
            [len(p) for p in pf.partitions]
        got = out.compute()
        assert list(got.columns) == ["a", "b"]
        assert got.index.equals(num.index)
        want = ref.fit_transform(num)
        np.testing.assert_allclose(got.to_numpy(), want,
                                   rtol=2e-2, atol=2e-2)
        # frame fit records the column names
        np.testing.assert_array_equal(
            ours.feature_names_in_, np.asarray(["a", "b"], dtype=object)
        )
        # pandas in → pandas out
        assert isinstance(ours.fit(num).transform(num), pd.DataFrame)
        # inverse round-trips back to the original values
        back = ours.inverse_transform(out)
        np.testing.assert_allclose(
            back.compute().to_numpy(), num.to_numpy(), rtol=1e-2, atol=5e-2
        )


def test_scalers_reject_unencoded_categoricals(df):
    from dask_ml_tpu.preprocessing import StandardScaler

    pf = from_pandas(df, npartitions=3)  # column "c" holds strings
    with pytest.raises(ValueError, match="encode"):
        StandardScaler().fit(pf)


def test_polynomial_features_preserve_dataframe(df):
    from dask_ml_tpu.parallel import ShardedArray
    from dask_ml_tpu.preprocessing import PolynomialFeatures

    pf = from_pandas(df[["a", "b"]], npartitions=3)
    out = PolynomialFeatures(degree=2, preserve_dataframe=True) \
        .fit(pf).transform(pf)
    assert isinstance(out, PartitionedFrame)
    assert list(out.columns)[:3] == ["1", "a", "b"]
    assert out.compute().shape == (len(df), 6)
    # default preserve_dataframe=False returns a device array (the
    # reference's default for frame input)
    out2 = PolynomialFeatures(degree=2).fit(pf).transform(pf)
    assert isinstance(out2, ShardedArray)


def test_column_transformer_partitioned_frames(df):
    """ColumnTransformer over PartitionedFrame: frame-in → frame-out with
    partition boundaries preserved, scaled + passthrough columns."""
    from dask_ml_tpu.compose import ColumnTransformer
    from dask_ml_tpu.preprocessing import StandardScaler

    num = df[["a", "b"]].astype(np.float64)
    pf = from_pandas(num, npartitions=4)
    ct = ColumnTransformer(
        [("scale", StandardScaler(), ["a"])], remainder="passthrough"
    )
    out = ct.fit_transform(pf)
    assert isinstance(out, PartitionedFrame)
    assert list(out.columns) == ["a", "b"]
    got = out.compute()
    np.testing.assert_allclose(got["b"], num["b"])
    assert abs(got["a"].mean()) < 1e-5
    pd.testing.assert_frame_equal(ct.transform(pf).compute(), got)
    # pandas input now yields a pandas frame as well
    outp = ct.fit_transform(num)
    assert isinstance(outp, pd.DataFrame)
    np.testing.assert_allclose(outp.to_numpy(), got.to_numpy(),
                               rtol=1e-6, atol=1e-6)


def test_scaler_transform_validates_feature_names(df):
    from dask_ml_tpu.preprocessing import StandardScaler

    num = df[["a", "b"]].astype(np.float64)
    scaler = StandardScaler().fit(from_pandas(num, npartitions=3))
    flipped = from_pandas(num[["b", "a"]], npartitions=3)
    with pytest.raises(ValueError, match="feature names"):
        scaler.transform(flipped)


def test_quantile_transformer_constant_column():
    """sklearn maps a constant column to 0 (lower bound applied last)."""
    import sklearn.preprocessing as skp

    from dask_ml_tpu.preprocessing import QuantileTransformer

    rng = np.random.RandomState(0)
    Z = np.c_[np.full(300, 7.0), rng.randn(300)]
    got = QuantileTransformer(n_quantiles=40).fit(Z).transform(Z).to_numpy()
    want = skp.QuantileTransformer(n_quantiles=40).fit_transform(Z)
    np.testing.assert_allclose(got, want, atol=1e-6)
