"""Deterministic estimator tokens for work de-duplication.

Reference: ``dask_ml/model_selection/_normalize.py`` (SURVEY.md §2a,
§3.4): dask's ``tokenize`` gives identical graph keys to identical
(estimator, params) subtrees so shared pipeline prefixes are fit once. We
need the same property without a task graph: a stable string token keyed
on (class, sorted params), used by the search controller's prefix memo —
the de-dup is explicit (a dict) instead of graph-key coincidence.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _token_piece(v):
    if isinstance(v, np.ndarray):
        return f"ndarray:{v.shape}:{v.dtype}:{hashlib.sha1(np.ascontiguousarray(v).tobytes()).hexdigest()[:16]}"
    if isinstance(v, (list, tuple)):
        return f"{type(v).__name__}({','.join(_token_piece(i) for i in v)})"
    if isinstance(v, dict):
        inner = ",".join(
            f"{k}={_token_piece(v[k])}" for k in sorted(v, key=str)
        )
        return f"dict({inner})"
    if hasattr(v, "get_params"):
        return estimator_token(v)
    return f"{type(v).__name__}:{v!r}"


def estimator_token(est) -> str:
    """Stable token for an (unfitted) estimator's identity + params."""
    params = est.get_params(deep=False)
    inner = ",".join(f"{k}={_token_piece(params[k])}" for k in sorted(params))
    raw = f"{type(est).__module__}.{type(est).__qualname__}({inner})"
    return hashlib.sha1(raw.encode()).hexdigest()
