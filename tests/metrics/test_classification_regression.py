"""Classification/regression metric + scorer parity vs sklearn
(ref: dask_ml/metrics/{classification,regression,scorer}.py)."""

import numpy as np
import pytest
import sklearn.metrics as skm

from dask_ml_tpu import metrics as dm


@pytest.fixture(scope="module")
def preds():
    rng = np.random.RandomState(0)
    y_true = rng.randint(0, 2, size=400).astype(np.float64)
    y_pred = np.where(rng.uniform(size=400) < 0.8, y_true,
                      1 - y_true)
    proba = np.clip(
        y_true * 0.7 + rng.uniform(size=400) * 0.3, 1e-6, 1 - 1e-6
    )
    w = rng.uniform(0.5, 2.0, size=400)
    return y_true, y_pred, proba, w


def test_accuracy(preds):
    y, p, _, w = preds
    assert np.isclose(float(dm.accuracy_score(y, p)), skm.accuracy_score(y, p))
    assert np.isclose(
        float(dm.accuracy_score(y, p, sample_weight=w)),
        skm.accuracy_score(y, p, sample_weight=w),
    )
    assert np.isclose(
        float(dm.accuracy_score(y, p, normalize=False)),
        skm.accuracy_score(y, p, normalize=False),
    )


def test_log_loss(preds):
    y, _, proba, w = preds
    assert np.isclose(float(dm.log_loss(y, proba)), skm.log_loss(y, proba),
                      rtol=1e-5)
    assert np.isclose(
        float(dm.log_loss(y, proba, sample_weight=w)),
        skm.log_loss(y, proba, sample_weight=w), rtol=1e-5,
    )
    # 2-column probability input
    P = np.stack([1 - proba, proba], axis=1)
    assert np.isclose(float(dm.log_loss(y, P)), skm.log_loss(y, P), rtol=1e-5)


def test_regression_metrics():
    rng = np.random.RandomState(1)
    y = rng.uniform(1, 10, size=300)
    p = y + rng.normal(scale=0.5, size=300)
    w = rng.uniform(0.5, 2.0, size=300)
    pairs = [
        (dm.mean_squared_error, skm.mean_squared_error),
        (dm.mean_absolute_error, skm.mean_absolute_error),
        (dm.r2_score, skm.r2_score),
        (dm.mean_squared_log_error, skm.mean_squared_log_error),
    ]
    for ours, ref in pairs:
        assert np.isclose(float(ours(y, p)), ref(y, p), rtol=1e-5), ours
        assert np.isclose(
            float(ours(y, p, sample_weight=w)), ref(y, p, sample_weight=w),
            rtol=1e-5,
        ), ours


def test_mse_squared_false():
    rng = np.random.RandomState(2)
    y = rng.uniform(size=100)
    p = rng.uniform(size=100)
    assert np.isclose(
        float(dm.mean_squared_error(y, p, squared=False)),
        np.sqrt(skm.mean_squared_error(y, p)), rtol=1e-5,
    )


def test_scorer_registry():
    from dask_ml_tpu.metrics.scorer import SCORERS, check_scoring, get_scorer

    assert "accuracy" in SCORERS and "r2" in SCORERS
    assert "neg_mean_squared_error" in SCORERS
    with pytest.raises((ValueError, KeyError)):
        get_scorer("not_a_scorer")

    from sklearn.linear_model import SGDClassifier

    est = SGDClassifier()
    scorer = check_scoring(est, "accuracy")
    X = np.random.RandomState(0).randn(50, 3)
    y = (X[:, 0] > 0).astype(int)
    est.fit(X, y)
    s = scorer(est, X, y)
    assert 0.0 <= float(s) <= 1.0


def test_scorer_greater_is_better_sign():
    """neg_* scorers must return negated losses so search maximizes."""
    from dask_ml_tpu.metrics.scorer import get_scorer

    from sklearn.linear_model import LinearRegression

    rng = np.random.RandomState(0)
    X = rng.randn(80, 3)
    y = X @ np.array([1.0, -2.0, 0.5]) + rng.normal(scale=0.1, size=80)
    est = LinearRegression().fit(X, y)
    val = get_scorer("neg_mean_squared_error")(est, X, y)
    assert float(val) <= 0.0


def test_log_loss_multiclass_matches_sklearn():
    import sklearn.metrics as skm

    from dask_ml_tpu.metrics import log_loss

    rng = np.random.RandomState(0)
    y = rng.randint(0, 4, 300).astype(np.float64)
    p = rng.dirichlet(np.ones(4), 300)
    assert abs(float(log_loss(y, p)) - skm.log_loss(y, p)) < 1e-6
    # non-contiguous labels map by sorted order, as sklearn does
    y2 = np.choose(y.astype(int), [10.0, 20.0, 30.0, 40.0])
    assert abs(float(log_loss(y2, p)) - skm.log_loss(y2, p)) < 1e-6


def test_log_loss_binary_noncanonical_labels():
    import sklearn.metrics as skm

    from dask_ml_tpu.metrics import log_loss

    rng = np.random.RandomState(1)
    y = np.where(rng.rand(200) > 0.5, 20.0, 10.0)
    p = rng.rand(200)
    assert abs(float(log_loss(y, p)) - skm.log_loss(y, p)) < 1e-6


def test_log_loss_missing_class_requires_labels():
    import pytest

    from dask_ml_tpu.metrics import log_loss

    rng = np.random.RandomState(2)
    p = rng.dirichlet(np.ones(4), 100)
    y = rng.randint(0, 3, 100).astype(np.float64)  # class 3 never occurs
    with pytest.raises(ValueError, match="labels"):
        log_loss(y, p)
    # explicit labels resolve the mapping
    import sklearn.metrics as skm

    got = float(log_loss(y, p, labels=[0.0, 1.0, 2.0, 3.0]))
    want = skm.log_loss(y, p, labels=[0.0, 1.0, 2.0, 3.0])
    assert abs(got - want) < 1e-6


def test_log_loss_single_class_and_out_of_label_raise():
    import pytest

    from dask_ml_tpu.metrics import log_loss

    # all-one-class binary without labels: ambiguous mapping must raise
    with pytest.raises(ValueError, match="single class"):
        log_loss(np.zeros(5), np.full(5, 0.1))
    # with labels the mapping is pinned and matches sklearn
    import sklearn.metrics as skm

    got = float(log_loss(np.zeros(5), np.full(5, 0.1), labels=[0.0, 1.0]))
    want = skm.log_loss(np.zeros(5), np.full(5, 0.1), labels=[0, 1])
    assert abs(got - want) < 1e-6
    # y values outside the label set raise instead of scoring a neighbor
    p4 = np.full((4, 4), 0.25)
    with pytest.raises(ValueError, match="not in labels"):
        log_loss(np.array([0.0, 1.0, 2.0, 5.0]), p4,
                 labels=[0.0, 1.0, 2.0, 3.0])


def test_neg_log_loss_scorer_fold_missing_class():
    """The scorer forwards estimator.classes_, so a fold missing a class
    still scores (the bare metric would raise)."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.metrics.scorer import get_scorer

    rng = np.random.RandomState(0)
    X = rng.randn(300, 6).astype(np.float32)
    y = rng.randint(0, 3, 300).astype(np.float32)
    clf = LogisticRegression(solver="lbfgs", max_iter=60).fit(X, y)
    scorer = get_scorer("neg_log_loss")
    sub = y < 2  # evaluation slice missing class 2
    s = scorer(clf, X[sub], y[sub])
    assert np.isfinite(s) and s <= 0


def test_extended_regression_metrics_match_sklearn():
    from dask_ml_tpu.metrics import (explained_variance_score, max_error,
                                     median_absolute_error)
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(3)
    for n in (101, 200):  # odd and even valid counts
        t = rng.randn(n).astype(np.float64)
        p = t + 0.3 * rng.randn(n)
        w = rng.rand(n) + 0.05
        np.testing.assert_allclose(
            explained_variance_score(t, p),
            skm.explained_variance_score(t, p), rtol=1e-6)
        np.testing.assert_allclose(
            explained_variance_score(t, p, sample_weight=w),
            skm.explained_variance_score(t, p, sample_weight=w),
            rtol=1e-5)
        np.testing.assert_allclose(
            max_error(t, p), skm.max_error(t, p), rtol=1e-6)
        np.testing.assert_allclose(
            median_absolute_error(t, p),
            skm.median_absolute_error(t, p), rtol=1e-5)
        np.testing.assert_allclose(
            median_absolute_error(t, p, sample_weight=w),
            skm.median_absolute_error(t, p, sample_weight=w), rtol=1e-5)
        # sharded (padded) inputs agree with the host result
        np.testing.assert_allclose(
            median_absolute_error(as_sharded(np.float32(t)),
                                  as_sharded(np.float32(p))),
            skm.median_absolute_error(np.float32(t), np.float32(p)),
            rtol=1e-5)
    # zero-weight rows contribute nothing, even with extreme errors
    t2 = np.array([0.0, 0.0, 0.0, 0.0, 100.0])
    p2 = np.array([1.0, 2.0, 3.0, 4.0, 0.0])
    w2 = np.array([1.0, 1.0, 1.0, 1.0, 0.0])
    np.testing.assert_allclose(
        median_absolute_error(t2, p2, sample_weight=w2),
        skm.median_absolute_error(t2, p2, sample_weight=w2), rtol=1e-9)


def test_extended_scorer_strings_device_resident():
    from dask_ml_tpu.datasets import make_regression
    from dask_ml_tpu.linear_model import LinearRegression
    from dask_ml_tpu.metrics.scorer import SCORERS, get_scorer

    X, y = make_regression(n_samples=2000, n_features=8, random_state=0)
    est = LinearRegression(solver="lbfgs", max_iter=50).fit(X, y)
    for name in ("neg_root_mean_squared_error",
                 "neg_mean_squared_log_error", "neg_median_absolute_error",
                 "explained_variance", "max_error"):
        assert name in SCORERS
        if name == "neg_mean_squared_log_error":
            continue  # needs nonnegative targets; registry check enough
        s = get_scorer(name)(est, X, y)
        assert np.isfinite(s)
    # rmse/medae/max_error are negated; explained_variance is not
    assert get_scorer("neg_root_mean_squared_error")(est, X, y) <= 0
    assert get_scorer("explained_variance")(est, X, y) > 0.9


def test_constant_target_force_finite():
    from dask_ml_tpu.metrics import explained_variance_score, r2_score

    t = np.ones(6)
    assert explained_variance_score(t, np.arange(6.0)) == \
        skm.explained_variance_score(t, np.arange(6.0)) == 0.0
    assert explained_variance_score(t, t) == \
        skm.explained_variance_score(t, t) == 1.0
    assert r2_score(t, np.arange(6.0)) == \
        skm.r2_score(t, np.arange(6.0)) == 0.0
    assert r2_score(t, t) == skm.r2_score(t, t) == 1.0


def test_undefined_metric_warning_class():
    """The degenerate curve paths warn with an
    UndefinedMetricWarning-compatible class (ADVICE r5): a UserWarning
    subclass under sklearn's name, so sklearn-ported filters catch it."""
    from sklearn.exceptions import (
        UndefinedMetricWarning as SkUndefinedMetricWarning,
    )

    from dask_ml_tpu.metrics import UndefinedMetricWarning

    assert issubclass(UndefinedMetricWarning, UserWarning)
    # sklearn-ported filters target sklearn's class — ours must BE one
    assert issubclass(UndefinedMetricWarning, SkUndefinedMetricWarning)
    y = np.zeros(8)
    s = np.linspace(0, 1, 8)
    with pytest.warns(UndefinedMetricWarning):
        dm.roc_curve(y, s)
    with pytest.warns(UndefinedMetricWarning):
        dm.precision_recall_curve(y, s)
    with pytest.warns(UndefinedMetricWarning):
        assert dm.average_precision_score(y, s) == 0.0
    # sklearn-style filtering by the SPECIFIC class works
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning raises ...
        warnings.simplefilter("ignore", UndefinedMetricWarning)  # ... but ours
        dm.roc_curve(y, s)


def test_binary_metrics_reject_duplicate_labels():
    """labels=[v, v] passes the length check but would silently map every
    row positive (ADVICE r5) — must raise instead."""
    y = np.array([0.0, 1.0, 1.0, 0.0])
    s = np.array([0.1, 0.8, 0.7, 0.3])
    for fn in (dm.roc_auc_score, dm.roc_curve,
               dm.precision_recall_curve, dm.average_precision_score):
        with pytest.raises(ValueError, match="distinct"):
            fn(y, s, labels=[1.0, 1.0])
