"""Device-native SGD estimators with ``partial_fit``.

The reference has no GLM partial_fit — its ``Incremental`` wrapper streams
blocks through *sklearn's* SGDClassifier (SURVEY.md §3.6), keeping the hot
loop on host CPU. These estimators keep the model AND the update on
device: each ``partial_fit`` is one jitted gradient(+prox) step on a
streamed block — the TPU-resident streaming-partial_fit path of
BASELINE.md configs[3]. Same sklearn contract, so they compose with
``Incremental``, ``IncrementalSearchCV`` and Hyperband.

Update rule: full-block gradient steps (minibatch GD), not per-sample SGD
— per-sample loops don't map to the MXU; a block IS the minibatch.
Penalties follow sklearn's SGD semantics: l2 inside the objective, l1 as
a proximal soft-threshold after the step, elasticnet as the l1_ratio mix.

Batched trials: N models with the same (class, loss, classes) but
different hyperparameters advance in ONE jitted step via ``jax.vmap``
over a stacked (N, d+1) weight matrix — the TPU replacement for the
reference's N concurrent model futures (``dask_ml/model_selection/
_incremental.py::_fit``, SURVEY.md §3.5): instead of N workers each
running one sklearn partial_fit, one XLA program advances the whole
cohort with the data block read from HBM once.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin, to_host
from ..metrics import accuracy_score, r2_score
from ..observability import track_program
from ..plans import tracked as plan_tracked, warmups as plan_warmups
from ..parallel.sharded import ShardedArray, as_sharded
from ..utils.validation import check_is_fitted

_LOSSES = ("log_loss", "hinge", "squared_error")
_PENALTIES = ("l2", "l1", "elasticnet", None, "none")


def _sgd_data_loss(w, y, X, mask, n_valid, iflag, loss, mxu=None):
    """The minibatch data term — THE single definition shared by
    ``_sgd_update_one`` (which adds the l2 penalty inside its
    objective) and the grad-accum micro kernel (which normalizes by the
    accumulation GROUP's global valid-row count, so summing micro
    (value, grad) pairs over the group IS the group objective's
    value_and_grad; at A=1 single-process the traced expression is
    identical to the sequential step's)."""
    # iflag=0 zeroes the intercept's contribution to eta, so grad[-1]
    # is already 0 and the intercept stays frozen at its init (0).
    # The matvec runs at X's dtype with f32 accumulation — a bf16
    # block (config.dtype="bfloat16" epoch grids) rides the MXU at
    # bf16 rate; for f32 X this is exactly `X @ w[:-1]`
    Xd = X if mxu is None else X.astype(mxu)
    eta = jnp.matmul(Xd, w[:-1].astype(Xd.dtype),
                     preferred_element_type=jnp.float32) \
        + w[-1] * iflag
    if loss == "log_loss":
        per = jax.nn.softplus(eta) - y * eta
    elif loss == "hinge":
        margins = (2.0 * y - 1.0) * eta
        per = jnp.maximum(0.0, 1.0 - margins)
    else:  # squared_error
        per = 0.5 * (eta - y) ** 2
    return jnp.sum(per * mask) / jnp.maximum(n_valid, 1.0)


def _sgd_update_one(w, y, X, mask, n_valid, lr, alpha, l2w, l1w, iflag,
                    loss, mxu=None):
    """One minibatch-GD(+prox) update of one weight vector — the SINGLE
    definition of the objective and update shared by the model-batched
    and class-batched kernels (a divergence between them would silently
    split binary and multiclass semantics). ``mxu`` (static dtype, e.g.
    bf16 under config.dtype="auto" on TPU) casts ONLY the eta matvec's
    operands; with None the trace is unchanged."""

    def objective(w):
        data_loss = _sgd_data_loss(w, y, X, mask, n_valid, iflag, loss,
                                   mxu=mxu)
        reg = 0.5 * alpha * l2w * jnp.sum(w[:-1] ** 2)
        return data_loss + reg

    val, grad = jax.value_and_grad(objective)(w)
    w = w - lr * grad
    # proximal soft-threshold for the l1 part (intercept unpenalized)
    thr = lr * alpha * l1w
    coef = jnp.sign(w[:-1]) * jnp.maximum(jnp.abs(w[:-1]) - thr, 0.0)
    return w.at[:-1].set(coef), val


def _sgd_many_update(W, loss_sums, grads, nv, lr, alpha, l2w, l1w,
                     iflag):
    """The vectorized `_sgd_update_one` epilogue on RAW kernel sums for
    an (N, d+1) weight stack — the ONE definition shared by the fused
    multiclass step, the fused sharded multiclass step, and the fused
    cohort scan (each copy independently remembering the thr broadcast
    and the iflag fold is how flavors drift apart). Per-row
    lr/alpha/penalty/iflag operands may be scalars (multiclass: one
    setting for all C rows) or (N,) vectors (cohort: per-model);
    broadcasting a scalar to a column changes no float op. Returns
    (W2, per-row losses)."""
    def col(a):
        return jnp.reshape(
            jnp.broadcast_to(jnp.asarray(a, jnp.float32),
                             (W.shape[0],)), (-1, 1)
        )

    lrc, ac, l2c, l1c, ifc = (col(a) for a in
                              (lr, alpha, l2w, l1w, iflag))
    l2term = ac * l2c
    losses = loss_sums / nv \
        + 0.5 * l2term[:, 0] * jnp.sum(W[:, :-1] ** 2, axis=1)
    g = grads / nv
    g = g.at[:, :-1].add(l2term * W[:, :-1])
    g = g.at[:, -1].mul(ifc[:, 0])
    W2 = W - lrc * g
    thr = lrc * ac * l1c
    coef = jnp.sign(W2[:, :-1]) * jnp.maximum(
        jnp.abs(W2[:, :-1]) - thr, 0.0
    )
    return W2.at[:, :-1].set(coef), losses


@track_program("sgd.step_many")
@partial(jax.jit, static_argnames=("loss", "mxu"))
def _sgd_step_many(X, y, mask, n_valid, W, lrs, alphas, l2_ws, l1_ws,
                   int_flags, loss, mxu=None):
    """Advance N models one step in one program. W: (N, d+1) stacked
    weights (last column = intercept). X/y/mask are SHARED across models
    — the block is read once; lr/alpha/penalty weights/intercept flag
    are per-model dynamic scalars (no static recompile per setting)."""

    def one(w, lr, alpha, l2w, l1w, iflag):
        return _sgd_update_one(w, y, X, mask, n_valid, lr, alpha, l2w,
                               l1w, iflag, loss, mxu=mxu)

    return jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0))(
        W, lrs, alphas, l2_ws, l1_ws, int_flags
    )


@track_program("sgd.step_multi")
@partial(jax.jit, static_argnames=("loss", "mxu"))
def _sgd_step_multi(X, y_codes, mask, n_valid, W, lr, alpha, l2w, l1w,
                    iflag, loss, mxu=None):
    """Advance the C one-vs-rest problems of ONE multiclass model in one
    program. W: (C, d+1); ``y_codes`` holds class INDICES 0..C-1 (mapped
    at encode time — float32 equality on raw labels would collapse
    ID-like classes past 2**24), and each class's 0/1 target derives
    in-kernel — no (C, n) target matrix ever materializes."""

    def one(w, c):
        y = (y_codes == c).astype(jnp.float32)
        return _sgd_update_one(w, y, X, mask, n_valid, lr, alpha, l2w,
                               l1w, iflag, loss, mxu=mxu)

    return jax.vmap(one)(W, jnp.arange(W.shape[0], dtype=jnp.float32))


@track_program("sgd.grad_accum_micro")
@partial(jax.jit, static_argnames=("loss", "n_out", "mxu"))
def _sgd_accum_micro(W, Xb, yb, mask, nv_group, iflag, loss, n_out,
                     mxu=None):
    """value_and_grad of one micro-block's SHARE of an accumulation
    group's data objective (config.stream_grad_accum): the data term
    normalized by the group's GLOBAL valid-row count ``nv_group``
    INSIDE autodiff, so summing these (value, grad) pairs over the
    group's micro-blocks — and across processes — yields exactly the
    group objective's value_and_grad. At A=1 single-process the traced
    expression is the sequential step's own data term (the SINGLE
    ``_sgd_data_loss`` definition), which is what makes A=1 parity
    exact rather than merely close."""
    if n_out is not None:
        def one(w, c):
            y = (yb == c).astype(jnp.float32)
            return jax.value_and_grad(
                lambda ww: _sgd_data_loss(ww, y, Xb, mask, nv_group,
                                          iflag, loss, mxu=mxu)
            )(w)

        vals, grads = jax.vmap(one)(
            W, jnp.arange(n_out, dtype=jnp.float32)
        )
        return vals.sum(), grads
    return jax.value_and_grad(
        lambda w: _sgd_data_loss(w, yb, Xb, mask, nv_group, iflag,
                                 loss, mxu=mxu)
    )(W)


@track_program("sgd.grad_accum_apply")
@jax.jit
def _sgd_accum_apply(W, grad, lr, alpha, l2w, l1w):
    """The shared grad-accum epilogue: fold in the l2 penalty's
    gradient — via the SAME autodiff expression the sequential
    objective differentiates, so A=1 single-process updates stay
    bit-identical — then the lr step and the l1 proximal
    soft-threshold, exactly ``_sgd_update_one``'s tail."""
    reg_g = jax.grad(
        lambda w: 0.5 * alpha * l2w * jnp.sum(w[..., :-1] ** 2)
    )(W)
    g = grad + reg_g
    W2 = W - lr * g
    thr = lr * alpha * l1w
    coef = jnp.sign(W2[..., :-1]) * jnp.maximum(
        jnp.abs(W2[..., :-1]) - thr, 0.0
    )
    return W2.at[..., :-1].set(coef)


@plan_tracked("superblock.sgd_scan")
@partial(jax.jit, static_argnames=("loss", "n_out", "mxu"),
         donate_argnums=(0,))
def _sgd_sb_scan(W, Xs, ys, counts, lrs, alpha, l2w, l1w, iflag, loss,
                 n_out, mxu=None):
    """K streamed-block minibatch steps as ONE scan program over a
    super-block stack (ISSUE 3): ``Xs (K, S, d)`` / ``ys (K, S)`` /
    ``counts (K,)`` valid-row counts; the weight carry ``W`` is DONATED
    so XLA advances it in place across the pass's dispatches. ``lrs``
    carries the host-precomputed lr clock values (identical to the
    per-block loop's ``_step_args`` sequence). All-padding slots
    (``counts == 0``, the ragged final super-block) leave W untouched —
    a masked-empty update would still apply the l2/prox terms.

    ``Xs``/``ys`` may instead be K-tuples of per-block arrays (the CPU
    layout, ``streaming.superblock_unrolled``): the chain unrolls at
    trace time into the same single program, minus XLA:CPU's per-step
    block-sized slice copy of a stacked operand."""
    unrolled = isinstance(Xs, (tuple, list))
    S = Xs[0].shape[0] if unrolled else Xs.shape[1]
    r = jnp.arange(S)

    def step(W, Xb, yb, c, lr):
        mask = (r < c).astype(jnp.float32)
        nv = c.astype(jnp.float32)
        if n_out is not None:
            def one(w, cc):
                yy = (yb == cc).astype(jnp.float32)
                return _sgd_update_one(w, yy, Xb, mask, nv, lr, alpha,
                                       l2w, l1w, iflag, loss, mxu=mxu)

            W2, losses = jax.vmap(one)(
                W, jnp.arange(n_out, dtype=jnp.float32)
            )
            loss_v = losses.sum()
        else:
            W2, loss_v = _sgd_update_one(W, yb, Xb, mask, nv, lr, alpha,
                                         l2w, l1w, iflag, loss, mxu=mxu)
        return jnp.where(c > 0, W2, W), loss_v

    if unrolled:
        losses = []
        for j in range(len(Xs)):
            W, loss_v = step(W, Xs[j], ys[j], counts[j], lrs[j])
            losses.append(loss_v)
        return W, jnp.stack(losses)

    def scan_step(W, inp):
        Xb, yb, c, lr = inp
        return step(W, Xb, yb, c, lr)

    return jax.lax.scan(scan_step, W, (Xs, ys, counts, lrs))


@plan_tracked("pallas.sgd_step")
@partial(jax.jit, static_argnames=("loss", "n_out", "mxu", "interpret"),
         donate_argnums=(0,))
def _sgd_sb_scan_pallas(W, Xs, ys, counts, lrs, alpha, l2w, l1w, iflag,
                        loss, n_out=None, mxu=None, interpret=False):
    """Pallas flavor of :func:`_sgd_sb_scan` (ISSUE 8 tentpole): each
    block step is ONE fused VMEM pass — the ``fused_sgd_block_grad``
    kernel (flat weights) or ``fused_sgd_many_block_grad`` (the C
    one-vs-rest rows of a multiclass model, ISSUE 12: one (tile, C)
    MXU matmul serves all classes) returns the objective and gradient
    sums from a single X read where the XLA step reads X twice
    (forward matvec + autodiff backward) — followed by the identical
    O(d) lr/l2/prox epilogue in XLA. Selected by ``_SGDBase._sb_step``
    with ``config.pallas_stream`` on (real TPU, or interpret mode via
    ``pallas_stream_interpret``) and block shapes satisfying
    ``sgd_stream_tile`` / ``sgd_many_stream_tile``; numerically within
    float tolerance of the XLA flavor (tests/test_precision.py)."""
    from ..ops.pallas_fused import (fused_sgd_block_grad,
                                    fused_sgd_many_block_grad)

    unrolled = isinstance(Xs, (tuple, list))

    def step(W, Xb, yb, c, lr):
        nv = jnp.maximum(c.astype(jnp.float32), 1.0)
        if n_out is not None:
            loss_sums, grads = fused_sgd_many_block_grad(
                Xb, c, yb, W, iflag, loss, codes=True, mxu=mxu,
                interpret=interpret,
            )
            W2, losses = _sgd_many_update(W, loss_sums, grads, nv, lr,
                                          alpha, l2w, l1w, iflag)
            return jnp.where(c > 0, W2, W), losses.sum()
        loss_sum, grad = fused_sgd_block_grad(
            Xb, c, yb, W, iflag, loss, mxu=mxu, interpret=interpret
        )
        # the exact `_sgd_update_one` epilogue on the kernel's raw sums
        loss_v = loss_sum / nv + 0.5 * alpha * l2w * jnp.sum(W[:-1] ** 2)
        g = grad / nv
        g = g.at[:-1].add(alpha * l2w * W[:-1])
        g = g.at[-1].mul(iflag)
        W2 = W - lr * g
        thr = lr * alpha * l1w
        coef = jnp.sign(W2[:-1]) * jnp.maximum(
            jnp.abs(W2[:-1]) - thr, 0.0
        )
        W2 = W2.at[:-1].set(coef)
        return jnp.where(c > 0, W2, W), loss_v

    if unrolled:
        losses = []
        for j in range(len(Xs)):
            W, loss_v = step(W, Xs[j], ys[j], counts[j], lrs[j])
            losses.append(loss_v)
        return W, jnp.stack(losses)

    def scan_step(W, inp):
        Xb, yb, c, lr = inp
        return step(W, Xb, yb, c, lr)

    return jax.lax.scan(scan_step, W, (Xs, ys, counts, lrs))


def _sgd_sparse_pointwise(eta, y, loss):
    """The per-row loss switch on a precomputed eta — the sparse twin
    of the expression inside ``_sgd_data_loss`` (kept textually
    separate so the dense kernels' traced jaxprs stay byte-identical)."""
    if loss == "log_loss":
        return jax.nn.softplus(eta) - y * eta
    if loss == "hinge":
        margins = (2.0 * y - 1.0) * eta
        return jnp.maximum(0.0, 1.0 - margins)
    return 0.5 * (eta - y) ** 2  # squared_error


def _sgd_update_one_sparse(w, y, data, cols, rows, S, mask, n_valid, lr,
                           alpha, l2w, l1w, iflag, loss):
    """``_sgd_update_one`` over one bucketed-nnz sparse block: the eta
    matvec and its autodiff backward run at nnz cost (take →
    scatter-add); objective normalization, l2 term and the l1 proximal
    epilogue are the dense step's exactly."""
    from ..ops.sparse_kernels import sparse_eta

    def objective(w):
        eta = sparse_eta(data, cols, rows, w[:-1], S) + w[-1] * iflag
        data_loss = jnp.sum(_sgd_sparse_pointwise(eta, y, loss) * mask) \
            / jnp.maximum(n_valid, 1.0)
        return data_loss + 0.5 * alpha * l2w * jnp.sum(w[:-1] ** 2)

    val, grad = jax.value_and_grad(objective)(w)
    w = w - lr * grad
    thr = lr * alpha * l1w
    coef = jnp.sign(w[:-1]) * jnp.maximum(jnp.abs(w[:-1]) - thr, 0.0)
    return w.at[:-1].set(coef), val


import functools as _ft_sharded


@_ft_sharded.lru_cache(maxsize=32)
def _sgd_sb_scan_sparse(loss, n_out, S, mesh=None):
    """Sparse flavor of :func:`_sgd_sb_scan` (ISSUE 13): K streamed
    minibatch steps over bucketed-nnz COO stacks in ONE donated-carry
    scan dispatch — same lr clock, same padding-slot pass-through, same
    zero-compiles-after-pass-1 contract (the stream plan pads every
    super-block of a fit to one nnz capacity). ``mesh`` selects the
    shard_map data-parallel flavor: each shard's raw (loss, grad) sums
    come from its own nnz segment/slab and psum ONCE per block step
    before the identical lr/l2/prox epilogue — the dense sharded scan's
    exact collective shape, tracked as
    ``superblock.sparse.sgd_scan.psum``."""
    from ..ops.sparse_kernels import sparse_eta

    S = int(S)

    if mesh is None:
        @partial(jax.jit, donate_argnums=(0,))
        def run(W, data, cols, rows, ys, counts, lrs, alpha, l2w, l1w,
                iflag):
            r = jnp.arange(S)

            def step(W, db, cb, rb, yb, c, lr):
                mask = (r < c).astype(jnp.float32)
                nv = c.astype(jnp.float32)
                if n_out is not None:
                    def one(w, cc):
                        yy = (yb == cc).astype(jnp.float32)
                        return _sgd_update_one_sparse(
                            w, yy, db, cb, rb, S, mask, nv, lr, alpha,
                            l2w, l1w, iflag, loss,
                        )

                    W2, losses = jax.vmap(one)(
                        W, jnp.arange(n_out, dtype=jnp.float32)
                    )
                    loss_v = losses.sum()
                else:
                    W2, loss_v = _sgd_update_one_sparse(
                        W, yb, db, cb, rb, S, mask, nv, lr, alpha, l2w,
                        l1w, iflag, loss,
                    )
                return jnp.where(c > 0, W2, W), loss_v

            def scan_step(W, inp):
                db, cb, rb, yb, c, lr = inp
                return step(W, db, cb, rb, yb, c, lr)

            return jax.lax.scan(scan_step, W,
                                (data, cols, rows, ys, counts, lrs))

        return plan_tracked("superblock.sparse.sgd_scan", run)

    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS

    def body(W, data, cols, rows, ys, shard_counts, counts, lrs, alpha,
             l2w, l1w, iflag):
        r = jnp.arange(S)               # LOCAL slab height
        cts_local = shard_counts[0]

        def step(W, db, cb, rb, yb, c_loc, c_glob, lr):
            mask = (r < c_loc).astype(jnp.float32)
            nv = jnp.maximum(c_glob.astype(jnp.float32), 1.0)

            def one(w, y):
                def local_sums(w):
                    eta = sparse_eta(db, cb, rb, w[:-1], S) \
                        + w[-1] * iflag
                    return jnp.sum(
                        _sgd_sparse_pointwise(eta, y, loss) * mask
                    )

                v, g = jax.value_and_grad(local_sums)(w)
                loss_sum, grad = jax.lax.psum((v, g), DATA_AXIS)
                loss_v = loss_sum / nv \
                    + 0.5 * alpha * l2w * jnp.sum(w[:-1] ** 2)
                g = grad / nv
                g = g.at[:-1].add(alpha * l2w * w[:-1])
                w2 = w - lr * g
                thr = lr * alpha * l1w
                coef = jnp.sign(w2[:-1]) * jnp.maximum(
                    jnp.abs(w2[:-1]) - thr, 0.0
                )
                return w2.at[:-1].set(coef), loss_v

            if n_out is not None:
                def one_class(w, cc):
                    return one(w, (yb == cc).astype(jnp.float32))

                W2, losses = jax.vmap(one_class)(
                    W, jnp.arange(n_out, dtype=jnp.float32)
                )
                loss_v = losses.sum()
            else:
                W2, loss_v = one(W, yb)
            return jnp.where(c_glob > 0, W2, W), loss_v

        def scan_step(W, inp):
            db, cb, rb, yb, cl, cg, lr = inp
            return step(W, db, cb, rb, yb, cl, cg, lr)

        return jax.lax.scan(
            scan_step, W,
            (data, cols, rows, ys, cts_local, counts, lrs),
        )

    @partial(jax.jit, donate_argnums=(0,))
    def run(W, data, cols, rows, ys, shard_counts, counts, lrs, alpha,
            l2w, l1w, iflag):
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(DATA_AXIS, None), P(), P(), P(), P(), P(),
                      P()),
            out_specs=(P(), P()),
        )
        return f(W, data, cols, rows, ys, shard_counts, counts, lrs,
                 alpha, l2w, l1w, iflag)

    return plan_tracked("superblock.sparse.sgd_scan.psum", run)


@plan_tracked("superblock.sparse.grad_accum_micro")
@partial(jax.jit, static_argnames=("loss", "n_out", "S"))
def _sgd_accum_micro_sparse(W, data, cols, rows, yb, mask, nv_group,
                            iflag, loss, n_out, S):
    """Sparse twin of :func:`_sgd_accum_micro` (the grad-accum flavor's
    per-micro-block value_and_grad, normalized by the GROUP's global
    valid-row count inside autodiff) over one bucketed-nnz block."""
    from ..ops.sparse_kernels import sparse_eta

    def data_loss(w, y):
        eta = sparse_eta(data, cols, rows, w[:-1], int(S)) \
            + w[-1] * iflag
        return jnp.sum(_sgd_sparse_pointwise(eta, y, loss) * mask) \
            / jnp.maximum(nv_group, 1.0)

    if n_out is not None:
        def one(w, c):
            y = (yb == c).astype(jnp.float32)
            return jax.value_and_grad(lambda ww: data_loss(ww, y))(w)

        vals, grads = jax.vmap(one)(
            W, jnp.arange(n_out, dtype=jnp.float32)
        )
        return vals.sum(), grads
    return jax.value_and_grad(lambda w: data_loss(w, yb))(W)


@_ft_sharded.lru_cache(maxsize=32)
def _sgd_sb_scan_sharded(mesh, loss, n_out, mxu=None, fused=False,
                         interpret=False):
    """Data-parallel flavor of :func:`_sgd_sb_scan` (ISSUE 9): the K
    block steps run under ``shard_map`` over the stream mesh's "data"
    axis with a REPLICATED weight carry. SGD's update is sequential in
    the blocks, so unlike the additive GLM/KMeans reducers it cannot
    defer merging to one pass-end collective: each block step computes
    its shard's raw (loss-sum, gradient-sum) from purely local rows and
    pays ONE ``lax.psum`` over "data" before the identical lr/l2/prox
    epilogue applies the GLOBAL gradient — the classic data-parallel
    minibatch step, K psums per super-block dispatch. Counts split per
    shard (``shard_counts``, local masks) with the global ``counts``
    riding replicated for the normalizer and the padding-slot
    pass-through; parity with the single-device scan is float-roundoff
    only (per-shard partial sums reassociate the same additions).

    ``fused=True`` (ISSUE 12): each shard's raw sums come from the
    fused Pallas kernel running INSIDE the shard_map on its own slab
    (tile selection sees the per-shard S/D height) — the per-step psum
    and epilogue are unchanged, so the dispatch shape (K psums per
    super-block) is identical and tracked as ``pallas.sgd_step.psum``.

    Cached per (mesh, loss, n_out, mxu, fused, interpret) so every pass
    of a fit reuses ONE jitted, donated-carry callable."""
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS, data_shard_spec as spec_of

    if fused:
        from ..ops.pallas_fused import (fused_sgd_block_grad,
                                        fused_sgd_many_block_grad)

    def body(W, Xs, ys, shard_counts, counts, lrs, alpha, l2w, l1w,
             iflag):
        unrolled = isinstance(Xs, (tuple, list))
        S = Xs[0].shape[0] if unrolled else Xs.shape[1]
        r = jnp.arange(S)
        cts_local = shard_counts[0]

        def step(W, Xb, yb, c_loc, c_glob, lr):
            mask = (r < c_loc).astype(jnp.float32)
            nv = jnp.maximum(c_glob.astype(jnp.float32), 1.0)

            if fused and n_out is not None:
                # fused multiclass: one VMEM pass over this shard's
                # slab serves all C one-vs-rest rows; psum the raw
                # sums, then the identical vectorized epilogue
                vs, gs = fused_sgd_many_block_grad(
                    Xb, c_loc, yb, W, iflag, loss, codes=True,
                    mxu=mxu, interpret=interpret,
                )
                vs, gs = jax.lax.psum((vs, gs), DATA_AXIS)
                W2, losses = _sgd_many_update(W, vs, gs, nv, lr,
                                              alpha, l2w, l1w, iflag)
                return jnp.where(c_glob > 0, W2, W), losses.sum()

            def one(w, y):
                def local_sums(w):
                    # the raw UNNORMALIZED data term over this shard's
                    # rows — same eta/loss math as _sgd_update_one
                    # (iflag rides inside eta, so grad[-1] is already 0
                    # with the intercept off)
                    Xd = Xb if mxu is None else Xb.astype(mxu)
                    eta = jnp.matmul(Xd, w[:-1].astype(Xd.dtype),
                                     preferred_element_type=jnp.float32
                                     ) + w[-1] * iflag
                    if loss == "log_loss":
                        per = jax.nn.softplus(eta) - y * eta
                    elif loss == "hinge":
                        margins = (2.0 * y - 1.0) * eta
                        per = jnp.maximum(0.0, 1.0 - margins)
                    else:  # squared_error
                        per = 0.5 * (eta - y) ** 2
                    return jnp.sum(per * mask)

                if fused:
                    # ONE VMEM pass over this shard's slab for the
                    # same raw sums the autodiff path computes twice
                    v, g = fused_sgd_block_grad(
                        Xb, c_loc, yb, w, iflag, loss, mxu=mxu,
                        interpret=interpret,
                    )
                    # the kernel's raw intercept sum is iflag-free;
                    # fold it here exactly like the XLA epilogue does
                    g = g.at[-1].mul(iflag)
                else:
                    v, g = jax.value_and_grad(local_sums)(w)
                # the data-parallel gradient psum INSIDE the scan: the
                # next block step needs the GLOBAL update
                loss_sum, grad = jax.lax.psum((v, g), DATA_AXIS)
                loss_v = loss_sum / nv \
                    + 0.5 * alpha * l2w * jnp.sum(w[:-1] ** 2)
                g = grad / nv
                g = g.at[:-1].add(alpha * l2w * w[:-1])
                w2 = w - lr * g
                thr = lr * alpha * l1w
                coef = jnp.sign(w2[:-1]) * jnp.maximum(
                    jnp.abs(w2[:-1]) - thr, 0.0
                )
                return w2.at[:-1].set(coef), loss_v

            if n_out is not None:
                def one_class(w, cc):
                    return one(w, (yb == cc).astype(jnp.float32))

                W2, losses = jax.vmap(one_class)(
                    W, jnp.arange(n_out, dtype=jnp.float32)
                )
                loss_v = losses.sum()
            else:
                W2, loss_v = one(W, yb)
            return jnp.where(c_glob > 0, W2, W), loss_v

        if unrolled:
            losses = []
            for j in range(len(Xs)):
                W, loss_v = step(W, Xs[j], ys[j], cts_local[j],
                                 counts[j], lrs[j])
                losses.append(loss_v)
            return W, jnp.stack(losses)

        def scan_step(W, inp):
            Xb, yb, cl, cg, lr = inp
            return step(W, Xb, yb, cl, cg, lr)

        return jax.lax.scan(scan_step, W,
                            (Xs, ys, cts_local, counts, lrs))

    @partial(jax.jit, donate_argnums=(0,))
    def run(W, Xs, ys, shard_counts, counts, lrs, alpha, l2w, l1w,
            iflag):
        unrolled = isinstance(Xs, (tuple, list))
        if unrolled:
            xs_spec = tuple(spec_of(a, 0) for a in Xs)
            ys_spec = tuple(spec_of(a, 0) for a in ys)
        else:
            xs_spec = spec_of(Xs, 1)
            ys_spec = spec_of(ys, 1)
        f = shard_map(
            body, mesh,
            in_specs=(P(), xs_spec, ys_spec, P(DATA_AXIS, None), P(),
                      P(), P(), P(), P(), P()),
            out_specs=(P(), P()),
            check_vma=False if fused else None,
        )
        return f(W, Xs, ys, shard_counts, counts, lrs, alpha, l2w,
                 l1w, iflag)

    name = "pallas.sgd_step.psum" if fused else "superblock.sgd_scan.psum"
    return plan_tracked(name, run)


@plan_tracked("sgd.fused_epoch")
@partial(jax.jit, static_argnames=("loss", "schedule", "n_out"))
def _sgd_epoch(Xr, yr, order, W, t0, eta0, power_t, alpha, l2w, l1w,
               iflag, n_rows, loss, schedule, n_out):
    """One FULL epoch as one program: ``lax.scan`` over the block grid
    ``Xr (B, S, d)`` / ``yr (B, S)`` — block b is dataset rows
    [b*S, (b+1)*S), axis 1 row-sharded so every step uses the whole
    mesh. Replaces one dispatch per block with one per epoch — on a
    tunneled runtime the per-launch round trip dominates the math at
    streaming block sizes. ``order`` holds the (possibly shuffled)
    block indices; the lr clock advances per block exactly as the
    per-block loop does."""
    S = Xr.shape[1]

    def lr_at(t):
        t = jnp.maximum(t, 1.0)
        if schedule == "constant":
            return jnp.float32(eta0)
        if schedule == "invscaling":
            return eta0 / t ** power_t
        return 1.0 / (alpha * (1e3 + t))  # "optimal"

    def step(carry, b):
        W, t = carry
        Xb = jnp.take(Xr, b, axis=0)          # (S, d), axis 0 sharded
        yb = jnp.take(yr, b, axis=0)
        # grid row r of block b is dataset row b*S + r; pad rows (the
        # tail the grid rounds up to) fail the bound and mask out
        row_ids = b * S + jnp.arange(S)
        mask = (row_ids < n_rows).astype(jnp.float32)
        n_valid = jnp.sum(mask)
        t = t + 1.0
        lr = lr_at(t)
        if n_out is not None:
            def one(w, c):
                yy = (yb == c).astype(jnp.float32)
                return _sgd_update_one(w, yy, Xb, mask, n_valid, lr,
                                       alpha, l2w, l1w, iflag, loss)

            W2, _ = jax.vmap(one)(
                W, jnp.arange(n_out, dtype=jnp.float32)
            )
        else:
            W2, _ = _sgd_update_one(W, yb, Xb, mask, n_valid, lr, alpha,
                                    l2w, l1w, iflag, loss)
        return (W2, t), jnp.float32(0.0)

    (W, t), _ = jax.lax.scan(step, (W, jnp.float32(t0)), order)
    return W, t


@plan_tracked("sgd.cohort_scan", ladder="cohort-slots")
@partial(jax.jit, static_argnames=("loss", "mxu"))
def _sgd_cohort_scan(Xr, yr, NV, order, W, LRS, alphas, l2ws, l1ws,
                     iflags, loss, mxu=None):
    """Advance N cohort models through S block steps in ONE program:
    ``lax.scan`` over ``order`` (indices into the DEDUPLICATED block
    stack Xr (B, bs, d) — a rung asking for several epochs revisits
    blocks without duplicating them in HBM) with the models vmapped
    inside each step — the adaptive-search hot path's S separate
    ``_batched_partial_fit`` dispatches collapse to one. ``LRS`` (S, N)
    carries each model's host-precomputed lr schedule values; per-step
    validity is the scalar prefix count ``NV[b]`` (take_rows blocks
    have trailing padding)."""
    bs = Xr.shape[1]
    r = jnp.arange(bs)

    def step(W, inp):
        b, lrs = inp
        Xb = jnp.take(Xr, b, axis=0)
        yb = jnp.take(yr, b, axis=0)
        nv = jnp.take(NV, b)
        m = (r < nv).astype(jnp.float32)
        n_valid = nv.astype(jnp.float32)

        def one(w, lr, a, l2w, l1w, ifl):
            return _sgd_update_one(w, yb, Xb, m, n_valid, lr, a, l2w,
                                   l1w, ifl, loss, mxu=mxu)

        W2, losses = jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0))(
            W, lrs, alphas, l2ws, l1ws, iflags
        )
        return W2, losses

    W, losses = jax.lax.scan(step, W, (order, LRS))
    return W, losses[-1]


@plan_tracked("pallas.sgd_cohort", ladder="cohort-slots")
@partial(jax.jit, static_argnames=("loss", "mxu", "interpret"))
def _sgd_cohort_scan_pallas(Xr, yr, NV, order, W, LRS, alphas, l2ws,
                            l1ws, iflags, loss, mxu=None,
                            interpret=False):
    """Pallas flavor of :func:`_sgd_cohort_scan` (ISSUE 12): each block
    step is ONE fused VMEM pass serving the WHOLE cohort — the
    ``fused_sgd_many_block_grad`` kernel's (tile, N) MXU matmul against
    the stacked coef rows replaces N vmapped forward+backward X reads —
    followed by the identical per-model lr/l2/prox epilogue on the raw
    sums. Same prefix-count masking and lr clocks as the XLA scan;
    selected by ``_batched_fused_calls`` when the stacked block height
    satisfies ``sgd_many_stream_tile``."""
    from ..ops.pallas_fused import fused_sgd_many_block_grad

    def step(W, inp):
        b, lrs = inp
        Xb = jnp.take(Xr, b, axis=0)
        yb = jnp.take(yr, b, axis=0)
        nv = jnp.take(NV, b)
        nvf = jnp.maximum(nv.astype(jnp.float32), 1.0)
        loss_sums, grads = fused_sgd_many_block_grad(
            Xb, nv, yb, W, iflags, loss, codes=False, mxu=mxu,
            interpret=interpret,
        )
        return _sgd_many_update(W, loss_sums, grads, nvf, lrs, alphas,
                                l2ws, l1ws, iflags)

    W, losses = jax.lax.scan(step, W, (order, LRS))
    return W, losses[-1]


# -- streamed cohort superblock scans (ISSUE 14 tentpole) ---------------
# The adaptive-search cohort as a CLIENT of the streamed superblock
# plane: one BlockStream pass advances EVERY surviving candidate — each
# super-block is ONE dispatch whose donated carry holds the stacked
# (n_slots, d+1) cohort weights, so the round's data is read from
# host/HBM once regardless of candidate count. Three mechanisms ride
# the scan:
#   - ``ACT (K, width)``: per-model STEP activity — heterogeneous
#     rounds ({model_id: n_calls} with differing counts) run in the
#     SAME scan, a model advancing only on its own window of block
#     steps (the per-model ``iflags`` mechanism of the fused kernels
#     generalized to the XLA scan);
#   - ``idx (width,)``: the slot-rung gather — each dispatch pulls the
#     union of its ACTIVE slots out of the full (n_slots, d+1) donated
#     carry into the smallest compiled rung width (a geometric ladder,
#     all rungs warmed in round 1), so compute scales with the LIVE
#     bracket while bracket halving still reuses compiled scans via
#     padded slots instead of recompiling at each surviving N;
#   - padding block slots (``counts == 0``, the ragged final
#     super-block) pass through exactly like the single-model scans,
#     and padding SLOT columns (``ACT`` all-zero) pass their rows back
#     unchanged through the ``.at[idx].set`` scatter.


# the slot-width ladder a search's cohort dispatches draw from: the
# plans subsystem's SlotRungLadder (ISSUE 15 — powers of two below the
# candidate count, then the full count, near-duplicate top power
# dropped). Every rung compiles during round 1 (warmup dispatches
# recorded in the process-wide plans WarmupRegistry, which replaced the
# old module-level _COHORT_WARMED set), so a shrinking bracket later
# picks its rung at zero new compiles — and a second search over the
# same shapes skips the warmup executions entirely.
from ..plans.ladders import SlotRungLadder as _SlotRungLadder  # noqa: E402

_COHORT_LADDER = _SlotRungLadder()


def _cohort_rungs(n_slots):
    return _COHORT_LADDER.rungs_for(n_slots)


def _cohort_rung_of(n_active, n_slots):
    return _COHORT_LADDER.rung_for(n_active, n_slots)


def _cohort_gather(W, idx):
    return jnp.take(W, idx, axis=0)


def _cohort_scatter(W, idx, Wc):
    return W.at[idx].set(Wc)


@plan_tracked("superblock.sgd_cohort", ladder="cohort-slots")
@partial(jax.jit, static_argnames=("loss", "mxu"), donate_argnums=(0,))
def _sgd_cohort_sb_scan(W, idx, Xs, ys, counts, LRS, ACT, alphas,
                        l2ws, l1ws, iflags, loss, mxu=None):
    """K streamed block steps of a search-cohort rung in ONE scan
    program: ``W (n_slots, d+1)`` donated full carry, ``idx (width,)``
    the dispatch's slot gather, ``Xs/ys/counts`` the super-block
    operands of :func:`_sgd_sb_scan`, ``LRS``/``ACT`` ``(K, width)``
    per-model lr clock values / step-activity masks. Each step runs
    the SINGLE ``_sgd_update_one`` definition vmapped over the rung —
    identical updates and lr clocks to the device-resident
    ``_sgd_cohort_scan`` over the same minibatches — and an inactive
    (masked or padding) slot passes its weights through untouched."""
    unrolled = isinstance(Xs, (tuple, list))
    S = Xs[0].shape[0] if unrolled else Xs.shape[1]
    r = jnp.arange(S)
    Wc = _cohort_gather(W, idx)

    def step(Wc, Xb, yb, c, lrs, act):
        mask = (r < c).astype(jnp.float32)
        nv = c.astype(jnp.float32)

        def one(w, lr, a, l2w, l1w, ifl):
            return _sgd_update_one(w, yb, Xb, mask, nv, lr, a, l2w,
                                   l1w, ifl, loss, mxu=mxu)

        W2, losses = jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0))(
            Wc, lrs, alphas, l2ws, l1ws, iflags
        )
        keep = (act > 0) & (c > 0)
        return jnp.where(keep[:, None], W2, Wc), losses

    if unrolled:
        losses = []
        for j in range(len(Xs)):
            Wc, lv = step(Wc, Xs[j], ys[j], counts[j], LRS[j], ACT[j])
            losses.append(lv)
        return _cohort_scatter(W, idx, Wc), jnp.stack(losses)

    def scan_step(Wc, inp):
        Xb, yb, c, lrs, act = inp
        return step(Wc, Xb, yb, c, lrs, act)

    Wc, losses = jax.lax.scan(scan_step, Wc, (Xs, ys, counts, LRS, ACT))
    return _cohort_scatter(W, idx, Wc), losses


@plan_tracked("pallas.sgd_cohort", ladder="cohort-slots")
@partial(jax.jit, static_argnames=("loss", "mxu", "interpret"),
         donate_argnums=(0,))
def _sgd_cohort_sb_scan_pallas(W, idx, Xs, ys, counts, LRS, ACT,
                               alphas, l2ws, l1ws, iflags, loss,
                               mxu=None, interpret=False):
    """Fused flavor of :func:`_sgd_cohort_sb_scan`: each block step is
    ONE ``fused_sgd_many_block_grad`` VMEM pass serving the whole rung
    — the same kernel the device-resident fused cohort scan uses —
    followed by the shared ``_sgd_many_update`` epilogue and the
    step/slot pass-through mask."""
    from ..ops.pallas_fused import fused_sgd_many_block_grad

    unrolled = isinstance(Xs, (tuple, list))
    Wc = _cohort_gather(W, idx)

    def step(Wc, Xb, yb, c, lrs, act):
        nv = jnp.maximum(c.astype(jnp.float32), 1.0)
        loss_sums, grads = fused_sgd_many_block_grad(
            Xb, c, yb, Wc, iflags, loss, codes=False, mxu=mxu,
            interpret=interpret,
        )
        W2, losses = _sgd_many_update(Wc, loss_sums, grads, nv, lrs,
                                      alphas, l2ws, l1ws, iflags)
        keep = (act > 0) & (c > 0)
        return jnp.where(keep[:, None], W2, Wc), losses

    if unrolled:
        losses = []
        for j in range(len(Xs)):
            Wc, lv = step(Wc, Xs[j], ys[j], counts[j], LRS[j], ACT[j])
            losses.append(lv)
        return _cohort_scatter(W, idx, Wc), jnp.stack(losses)

    def scan_step(Wc, inp):
        Xb, yb, c, lrs, act = inp
        return step(Wc, Xb, yb, c, lrs, act)

    Wc, losses = jax.lax.scan(scan_step, Wc, (Xs, ys, counts, LRS, ACT))
    return _cohort_scatter(W, idx, Wc), losses


@_ft_sharded.lru_cache(maxsize=32)
def _sgd_cohort_sb_scan_sharded(mesh, loss, mxu=None, fused=False,
                                interpret=False):
    """Data-parallel flavor of :func:`_sgd_cohort_sb_scan`: the cohort
    scan runs INSIDE ``shard_map`` over the stream mesh's "data" axis
    with the slot stack replicated — each block step computes every
    slot's raw (loss-sum, gradient-sum) from purely local rows and pays
    exactly ONE ``lax.psum`` over "data" (the stacked analog of the
    single-model sharded scan's collective shape) before the shared
    ``_sgd_many_update`` epilogue applies the GLOBAL update. With
    ``fused=True`` the local raw sums come from the
    ``fused_sgd_many_block_grad`` Pallas kernel on each device's own
    slab — the ``.psum`` twin of the fused cohort scan (ISSUE 14 after
    the PR-12 pattern), tracked as ``pallas.sgd_cohort.psum``."""
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS, data_shard_spec as spec_of

    if fused:
        from ..ops.pallas_fused import fused_sgd_many_block_grad

    def body(Wc, Xs, ys, shard_counts, counts, LRS, ACT, alphas, l2ws,
             l1ws, iflags):
        unrolled = isinstance(Xs, (tuple, list))
        S = Xs[0].shape[0] if unrolled else Xs.shape[1]
        r = jnp.arange(S)
        cts_local = shard_counts[0]

        def step(W, Xb, yb, c_loc, c_glob, lrs, act):
            mask = (r < c_loc).astype(jnp.float32)
            nv = jnp.maximum(c_glob.astype(jnp.float32), 1.0)
            if fused:
                vs, gs = fused_sgd_many_block_grad(
                    Xb, c_loc, yb, W, iflags, loss, codes=False,
                    mxu=mxu, interpret=interpret,
                )
            else:
                def local_sums(w, ifl):
                    # the raw UNNORMALIZED data term over this shard's
                    # rows — `_sgd_data_loss`'s eta/loss math with the
                    # normalizer deferred past the psum
                    Xd = Xb if mxu is None else Xb.astype(mxu)
                    eta = jnp.matmul(
                        Xd, w[:-1].astype(Xd.dtype),
                        preferred_element_type=jnp.float32,
                    ) + w[-1] * ifl
                    if loss == "log_loss":
                        per = jax.nn.softplus(eta) - yb * eta
                    elif loss == "hinge":
                        margins = (2.0 * yb - 1.0) * eta
                        per = jnp.maximum(0.0, 1.0 - margins)
                    else:  # squared_error
                        per = 0.5 * (eta - yb) ** 2
                    return jnp.sum(per * mask)

                vs, gs = jax.vmap(
                    lambda w, ifl: jax.value_and_grad(
                        lambda ww: local_sums(ww, ifl)
                    )(w)
                )(W, iflags)
            vs, gs = jax.lax.psum((vs, gs), DATA_AXIS)
            W2, losses = _sgd_many_update(W, vs, gs, nv, lrs, alphas,
                                          l2ws, l1ws, iflags)
            keep = (act > 0) & (c_glob > 0)
            return jnp.where(keep[:, None], W2, W), losses

        if unrolled:
            losses = []
            for j in range(len(Xs)):
                Wc, lv = step(Wc, Xs[j], ys[j], cts_local[j],
                              counts[j], LRS[j], ACT[j])
                losses.append(lv)
            return Wc, jnp.stack(losses)

        def scan_step(Wc, inp):
            Xb, yb, cl, cg, lrs, act = inp
            return step(Wc, Xb, yb, cl, cg, lrs, act)

        return jax.lax.scan(scan_step, Wc,
                            (Xs, ys, cts_local, counts, LRS, ACT))

    @partial(jax.jit, donate_argnums=(0,))
    def run(W, idx, Xs, ys, shard_counts, counts, LRS, ACT, alphas,
            l2ws, l1ws, iflags):
        unrolled = isinstance(Xs, (tuple, list))
        if unrolled:
            xs_spec = tuple(spec_of(a, 0) for a in Xs)
            ys_spec = tuple(spec_of(a, 0) for a in ys)
        else:
            xs_spec = spec_of(Xs, 1)
            ys_spec = spec_of(ys, 1)
        f = shard_map(
            body, mesh,
            in_specs=(P(), xs_spec, ys_spec, P(DATA_AXIS, None), P(),
                      P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P()),
            check_vma=False if fused else None,
        )
        # the rung gather/scatter runs OUTSIDE the shard_map on the
        # replicated full carry — the compact stack crosses in as P()
        Wc, losses = f(_cohort_gather(W, idx), Xs, ys, shard_counts,
                       counts, LRS, ACT, alphas, l2ws, l1ws, iflags)
        return _cohort_scatter(W, idx, Wc), losses

    name = "pallas.sgd_cohort.psum" if fused \
        else "superblock.sgd_cohort.psum"
    return plan_tracked(name, run, ladder="cohort-slots")


@_ft_sharded.lru_cache(maxsize=32)
def _sgd_cohort_sb_scan_sparse(loss, S, mesh=None):
    """Sparse flavor of :func:`_sgd_cohort_sb_scan` (the search path's
    densify finally ends — ROADMAP 4b): K cohort block steps over
    bucketed-nnz COO stacks in ONE donated-carry dispatch, the
    eta/gradient built from the ``ops/sparse_kernels`` take/segment_sum
    primitives at nnz cost. Same step/slot masks and padding-slot
    semantics as the dense cohort scan; ``mesh`` selects the shard_map
    twin — per-shard raw sums, ONE psum per block step, the shared
    ``_sgd_many_update`` epilogue — tracked as
    ``superblock.sparse.sgd_cohort.psum``."""
    from ..ops.sparse_kernels import sparse_eta

    S = int(S)

    if mesh is None:
        @partial(jax.jit, donate_argnums=(0,))
        def run(W, idx, data, cols, rows, ys, counts, LRS, ACT,
                alphas, l2ws, l1ws, iflags):
            r = jnp.arange(S)

            def step(Wc, db, cb, rb, yb, c, lrs, act):
                mask = (r < c).astype(jnp.float32)
                nv = c.astype(jnp.float32)

                def one(w, lr, a, l2w, l1w, ifl):
                    return _sgd_update_one_sparse(
                        w, yb, db, cb, rb, S, mask, nv, lr, a, l2w,
                        l1w, ifl, loss,
                    )

                W2, losses = jax.vmap(one, in_axes=(0,) * 6)(
                    Wc, lrs, alphas, l2ws, l1ws, iflags
                )
                keep = (act > 0) & (c > 0)
                return jnp.where(keep[:, None], W2, Wc), losses

            def scan_step(Wc, inp):
                db, cb, rb, yb, c, lrs, act = inp
                return step(Wc, db, cb, rb, yb, c, lrs, act)

            Wc, losses = jax.lax.scan(
                scan_step, _cohort_gather(W, idx),
                (data, cols, rows, ys, counts, LRS, ACT),
            )
            return _cohort_scatter(W, idx, Wc), losses

        return plan_tracked("superblock.sparse.sgd_cohort", run,
                            ladder="cohort-slots")

    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS

    def body(Wc, data, cols, rows, ys, shard_counts, counts, LRS, ACT,
             alphas, l2ws, l1ws, iflags):
        r = jnp.arange(S)               # LOCAL slab height
        cts_local = shard_counts[0]

        def step(Wc, db, cb, rb, yb, c_loc, c_glob, lrs, act):
            mask = (r < c_loc).astype(jnp.float32)
            nv = jnp.maximum(c_glob.astype(jnp.float32), 1.0)

            def local_sums(w, ifl):
                eta = sparse_eta(db, cb, rb, w[:-1], S) + w[-1] * ifl
                return jnp.sum(
                    _sgd_sparse_pointwise(eta, yb, loss) * mask
                )

            vs, gs = jax.vmap(
                lambda w, ifl: jax.value_and_grad(
                    lambda ww: local_sums(ww, ifl)
                )(w)
            )(Wc, iflags)
            vs, gs = jax.lax.psum((vs, gs), DATA_AXIS)
            W2, losses = _sgd_many_update(Wc, vs, gs, nv, lrs, alphas,
                                          l2ws, l1ws, iflags)
            keep = (act > 0) & (c_glob > 0)
            return jnp.where(keep[:, None], W2, Wc), losses

        def scan_step(Wc, inp):
            db, cb, rb, yb, cl, cg, lrs, act = inp
            return step(Wc, db, cb, rb, yb, cl, cg, lrs, act)

        return jax.lax.scan(
            scan_step, Wc,
            (data, cols, rows, ys, cts_local, counts, LRS, ACT),
        )

    @partial(jax.jit, donate_argnums=(0,))
    def run(W, idx, data, cols, rows, ys, shard_counts, counts, LRS,
            ACT, alphas, l2ws, l1ws, iflags):
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(DATA_AXIS, None), P(), P(), P(), P(), P(),
                      P(), P()),
            out_specs=(P(), P()),
        )
        Wc, losses = f(_cohort_gather(W, idx), data, cols, rows, ys,
                       shard_counts, counts, LRS, ACT, alphas, l2ws,
                       l1ws, iflags)
        return _cohort_scatter(W, idx, Wc), losses

    return plan_tracked("superblock.sparse.sgd_cohort.psum", run,
                        ladder="cohort-slots")


@partial(jax.jit, static_argnames=("n_rows",))
def _batched_eta_sparse(data, cols, rows, W, n_rows):
    """(n_rows, N) decision values of N stacked models over ONE packed
    sparse slab — the streamed-validation scoring dispatch for sparse
    holdouts (one ``sparse_eta_multi`` pass serves the whole cohort)."""
    from ..ops.sparse_kernels import sparse_eta_multi

    eta = sparse_eta_multi(data, cols, rows, W[:, :-1], n_rows)
    return eta + W[:, -1][None, :]


def _stack_cohort_weights(models, n_slots):
    """The cohort's (n_slots, d+1) host weight stack: live models in
    their slot rows, padding slots zero. Built on HOST so the stack's
    device shape never depends on the surviving candidate count — the
    one device_put per dispatch/score is what keeps shrinking brackets
    at zero recompiles."""
    d1 = int(np.asarray(models[0]._w).shape[-1])
    Wh = np.zeros((max(int(n_slots), len(models)), d1), np.float32)
    for i, m in enumerate(models):
        Wh[i] = np.asarray(m._w, np.float32)
    return Wh


import functools as _functools


def fused_blocks(X) -> tuple[int, int]:
    """(n_blocks B, rows-per-block S) of the fused-epoch grid for a
    ShardedArray: CONTIGUOUS blocks of S = padded/D rows rounded up to a
    multiple of D (so the grid's row axis shards evenly), B = however
    many cover the padded rows. The Incremental wrapper's per-block
    fallback loop uses the same partition so both paths train identical
    minibatches.

    Layout note: a STRIDED partition ({r ≡ b mod B}, grid (S, B, d)
    axis-0-sharded) would make the grid build collective-free, but each
    scan step then reads d-length runs strided B·d apart — measured ~4x
    slower per epoch than contiguous reads; the contiguous grid pays one
    all-to-all at build and streams contiguously ever after, which wins
    on CPU and maps better to TPU HBM burst reads."""
    from ..parallel.mesh import data_shards
    from ..parallel.streaming import grid_partition

    return grid_partition(X.padded_shape[0], max(data_shards(X.mesh), 1))


@_functools.lru_cache(maxsize=32)
def _grid_builders(mesh, B, S, dtype=None):
    """Cached jitted block-grid programs per (mesh, grid shape): pad the
    (n_pad, d) row-sharded array to B*S rows and reshape to (B, S, d)
    with axis 1 sharded (every scan step uses the whole mesh). One
    contiguous pad+reshape+reshard — the gather this replaced was ~6x
    slower on the same data and dominated the whole fused fit. Cached
    because a fresh ``jax.jit(lambda)`` per fit would retrace every
    epoch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    sh3 = NamedSharding(mesh, P(None, DATA_AXIS, None))
    sh2 = NamedSharding(mesh, P(None, DATA_AXIS))
    fX = jax.jit(
        lambda a: jnp.pad(
            a, ((0, B * S - a.shape[0]), (0, 0))
        ).reshape(B, S, a.shape[1]).astype(dtype or a.dtype),
        out_shardings=sh3,
    )
    fy = jax.jit(
        lambda a: jnp.pad(a, (0, B * S - a.shape[0])).reshape(B, S),
        out_shardings=sh2,
    )
    return fX, fy


@jax.jit
def _batched_eta(X, W):
    """(n, N) decision values for N stacked models on one shared X."""
    return X @ W[:, :-1].T + W[:, -1][None, :]


@jax.jit
def _batched_accuracy(X, y01, mask, n_valid, W):
    eta = _batched_eta(X, W)
    correct = (eta > 0).astype(jnp.float32) == y01[:, None]
    return jnp.sum(correct * mask[:, None], axis=0) / jnp.maximum(n_valid, 1.0)


@jax.jit
def _batched_r2(X, y, mask, n_valid, W):
    eta = _batched_eta(X, W)
    n = jnp.maximum(n_valid, 1.0)
    y_mean = jnp.sum(y * mask) / n
    ss_tot = jnp.sum(((y - y_mean) * mask) ** 2)
    ss_res = jnp.sum((((eta - y[:, None]) * mask[:, None]) ** 2), axis=0)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)


class _SGDBase(BaseEstimator):
    loss_default = "squared_error"

    def __init__(self, loss=None, penalty="l2", alpha=1e-4, l1_ratio=0.15,
                 eta0=0.01, learning_rate="invscaling", power_t=0.25,
                 max_iter=5, tol=1e-3, shuffle=True, random_state=None,
                 warm_start=False, fit_intercept=True, fit_dtype=None):
        self.loss = loss
        # per-estimator precision override: None follows config.dtype
        # ("auto" = bf16 on TPU, f32 elsewhere); "float32" opts this
        # estimator out of the bf16 default, "bfloat16" forces it on.
        # The resolved choice lands on `fit_dtype_` after fit.
        self.fit_dtype = fit_dtype
        self.penalty = penalty
        self.alpha = alpha
        self.l1_ratio = l1_ratio
        self.eta0 = eta0
        self.learning_rate = learning_rate
        self.power_t = power_t
        self.max_iter = max_iter
        self.tol = tol
        self.shuffle = shuffle
        self.random_state = random_state
        self.warm_start = warm_start
        self.fit_intercept = fit_intercept

    def _loss(self):
        loss = self.loss or self.loss_default
        if loss not in _LOSSES:
            raise ValueError(f"loss must be one of {_LOSSES}, got {loss!r}")
        return loss

    def _penalty_weights(self):
        """(l2_weight, l1_weight) implementing sklearn SGD semantics."""
        p = self.penalty
        if p == "l2":
            return 1.0, 0.0
        if p == "l1":
            return 0.0, 1.0
        if p == "elasticnet":
            return 1.0 - self.l1_ratio, self.l1_ratio
        if p is None or p == "none":
            return 0.0, 0.0
        raise ValueError(f"penalty must be one of {_PENALTIES}, got {p!r}")

    def _lr(self):
        t = max(self._t, 1)
        if self.learning_rate == "constant":
            return self.eta0
        if self.learning_rate == "invscaling":
            return self.eta0 / (t ** self.power_t)
        if self.learning_rate == "optimal":
            return 1.0 / (self.alpha * (1e3 + t))
        raise ValueError(f"Unknown learning_rate {self.learning_rate!r}")

    def _n_out(self):
        """Number of one-vs-rest rows for a multiclass classifier, else
        None (binary / regression use a flat weight vector)."""
        classes = getattr(self, "classes_", None)
        return len(classes) if classes is not None and len(classes) > 2 \
            else None

    def _ensure_state(self, d):
        if not hasattr(self, "_w") or self._w is None:
            C = self._n_out()
            shape = (C, d + 1) if C is not None else (d + 1,)
            self._w = jnp.zeros(shape, jnp.float32)
            self._t = 0
        self._penalty_weights()  # validate penalty eagerly
        # resolved fit compute dtype, on record (an auto policy that
        # fell back to f32 off-TPU must be visible, not silent)
        from ..config import fit_dtype_info

        info = fit_dtype_info(self.fit_dtype)
        self.fit_dtype_ = info["fit_dtype"]
        self.fit_dtype_source_ = info["fit_dtype_source"]

    def _step_args(self):
        """Per-model dynamic scalars for the (batched) step. The model's
        step clock advances here."""
        self._t += 1
        l2w, l1w = self._penalty_weights()
        return (
            np.float32(self._lr()), np.float32(self.alpha),
            np.float32(l2w), np.float32(l1w),
            np.float32(1.0 if self.fit_intercept else 0.0),
        )

    def _block(self, X, y):
        X = as_sharded(X, dtype=np.float32)
        y = as_sharded(self._encode_y(y), mesh=X.mesh, dtype=np.float32)
        return X, y

    def partial_fit(self, X, y, classes=None, **kwargs):
        if classes is not None:
            self._set_classes(np.asarray(classes))
        X, y = self._block(X, y)
        self._ensure_state(X.shape[1])
        self._one_step(X.data, y.data, X.row_mask(jnp.float32), X.n_rows)
        self._publish(X.shape[1])
        return self

    def _fused_epoch(self, X, y, order, n_blocks=None, classes=None):
        """One full streaming epoch in ONE program (the Incremental
        wrapper's fast path for device data): the dataset is padded and
        reshaped once into its (B, S, d) contiguous block grid (axis 1
        row-sharded; one all-to-all — see ``fused_blocks`` for why this
        beats a collective-free strided layout) and ``_sgd_epoch`` scans
        the blocks in ``order``. Semantically identical to ``order``
        partial_fit calls over the same contiguous blocks (same update,
        same lr clock, same masking), minus one dispatch round trip per
        block. NOTE the grid is a second device copy of the dataset for
        the epoch's duration — the wrapper falls back to the block loop
        when HBM headroom is insufficient."""
        if classes is not None:
            self._set_classes(np.asarray(classes))
        if isinstance(self, ClassifierMixin) and \
                getattr(self, "classes_", None) is None:
            raise ValueError(
                "classes must be passed on the first call to partial_fit."
            )
        X = as_sharded(X, dtype=np.float32)
        y_enc = as_sharded(self._encode_y(y), mesh=X.mesh,
                           dtype=np.float32)
        mesh = X.mesh
        d = X.data.shape[1]
        B, S = fused_blocks(X)
        if n_blocks is not None and n_blocks != B:
            # ``order`` indexes the caller's block partition; a
            # mismatched one would silently train wrong minibatches
            raise ValueError(
                f"_fused_epoch grid has {B} blocks of {S} rows; caller "
                f"partitioned into {n_blocks}"
            )
        order = np.asarray(order, np.int32)
        if order.size and (order.min() < 0 or order.max() >= B):
            raise ValueError(
                f"order indexes blocks 0..{B - 1}; got "
                f"[{order.min()}, {order.max()}]"
            )
        self._ensure_state(d)
        self._lr()  # validate the schedule name eagerly, like the loop
        from ..config import mxu_dtype

        # bf16 epoch grid: halves the grid's HBM (it's a second copy of
        # X) and the scan's matvecs ride the MXU at bf16 rate with f32
        # accumulation; weights/targets/updates stay f32. Weight parity
        # vs f32 ~1e-2 relative (input rounding on the design matrix)
        fX, fy = _grid_builders(mesh, B, S, mxu_dtype(self.fit_dtype))
        Xr = fX(X.data)
        yr = fy(y_enc.data)
        l2w, l1w = self._penalty_weights()
        W, _t = _sgd_epoch(
            Xr, yr, jnp.asarray(order), self._w,
            np.float32(self._t), np.float32(self.eta0),
            np.float32(self.power_t), np.float32(self.alpha),
            np.float32(l2w), np.float32(l1w),
            np.float32(1.0 if self.fit_intercept else 0.0),
            np.int32(X.n_rows), loss=self._loss(),
            schedule=self.learning_rate, n_out=self._n_out(),
        )
        self._w = W
        self._t += int(len(order))
        self._publish(d)
        return self

    # -- batched-trial protocol (consumed by model_selection._incremental) --
    def _batch_prepare(self, fit_params):
        """Apply first-call side effects (classes) before grouping."""
        classes = (fit_params or {}).get("classes")
        if classes is not None:
            self._set_classes(np.asarray(classes))

    def _batch_key(self):
        """Models sharing a key can advance in one vmapped step. None
        disables batching. Hyperparameters (lr schedule, alpha, penalty)
        are DYNAMIC per-model scalars, so only structure is in the key."""
        try:
            loss = self._loss()
            self._penalty_weights()
            from ..config import fit_dtype_info

            # the batched step is ONE program for the cohort, so only
            # models resolving to the SAME compute dtype may share it
            dtype = fit_dtype_info(self.fit_dtype)["fit_dtype"]
        except ValueError:
            return None  # invalid params: surface the error on the solo path
        classes = getattr(self, "classes_", None)
        return (type(self).__name__, loss, dtype,
                tuple(np.asarray(classes).tolist()) if classes is not None
                else None)

    @classmethod
    def _batched_partial_fit(cls, models, X, y):
        """One shared data block, one jitted step, N models advanced.

        X/y may be host arrays or ShardedArray; they are canonicalized
        once for the whole cohort (the reference pays this once per model
        per worker)."""
        Xs = as_sharded(X, dtype=np.float32)
        ys = as_sharded(models[0]._encode_y(y), mesh=Xs.mesh,
                        dtype=np.float32)
        d = Xs.shape[1]
        for m in models:
            m._ensure_state(d)
        mask = Xs.row_mask(jnp.float32)
        args = np.asarray([m._step_args() for m in models], np.float32)
        W = jnp.stack([m._w for m in models])
        from ..config import mxu_dtype

        W, losses = _sgd_step_many(
            Xs.data, ys.data, mask, jnp.float32(Xs.n_rows), W,
            jnp.asarray(args[:, 0]), jnp.asarray(args[:, 1]),
            jnp.asarray(args[:, 2]), jnp.asarray(args[:, 3]),
            jnp.asarray(args[:, 4]), models[0]._loss(),
            mxu=mxu_dtype(models[0].fit_dtype),  # cohort shares (keyed)
        )
        for i, m in enumerate(models):
            m._w = W[i]
            m._last_loss = losses[i]
        return models

    @classmethod
    def _batch_publish(cls, models, d):
        """Materialize coef_/intercept_ once per round (one D2H sync for
        the cohort, not one per model per step)."""
        for m in models:
            m._publish(d)

    def _lr_schedule(self, n_calls):
        """The next ``n_calls`` lr values this model's clock would
        produce — EXACTLY ``_step_args``'s increment-then-``_lr``
        sequence, precomputed on host so a fused multi-call program can
        carry them as one (S,) operand."""
        out = []
        t0 = self._t
        for i in range(n_calls):
            self._t = t0 + i + 1
            out.append(self._lr())
        self._t = t0
        return np.asarray(out, np.float32)

    @classmethod
    def _batched_fused_calls(cls, models, blocks, order=None):
        """Advance the cohort through a sequence of block steps in ONE
        scan program (``_sgd_cohort_scan``) — equivalent to that many
        ``_batched_partial_fit`` calls (same updates, same per-model lr
        clocks) minus the per-call dispatch round trips. ``blocks`` are
        the DISTINCT blocks and ``order`` (default: each once, in
        sequence) indexes the steps into them — a multi-epoch rung
        revisits blocks without duplicating them on device. Blocks may
        be ragged (the last data block is shorter): they stack padded
        to the widest with per-block valid-row counts."""
        if order is None:
            order = list(range(len(blocks)))
        S = len(order)
        enc = models[0]
        Xs_list, ys_list, nvs = [], [], []
        for Xb, yb in blocks:
            Xs = as_sharded(Xb, dtype=np.float32)
            ys = as_sharded(enc._encode_y(yb), mesh=Xs.mesh,
                            dtype=np.float32)
            Xs_list.append(Xs)
            ys_list.append(ys)
            nvs.append(Xs.n_rows)
        d = Xs_list[0].shape[1]
        for m in models:
            m._ensure_state(d)
        bs_max = max(x.data.shape[0] for x in Xs_list)

        def padded(a):
            pad = bs_max - a.shape[0]
            if pad:
                a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
            return a

        Xr = jnp.stack([padded(x.data) for x in Xs_list])
        yr = jnp.stack([padded(y.data) for y in ys_list])
        NV = jnp.asarray(nvs, jnp.int32)
        LRS = jnp.asarray(np.stack(
            [m._lr_schedule(S) for m in models], axis=1
        ))                                   # (S, N)
        args = np.asarray(
            [(m.alpha,) + m._penalty_weights()
             + (1.0 if m.fit_intercept else 0.0,) for m in models],
            np.float32,
        )
        W = jnp.stack([m._w for m in models])
        from ..config import mxu_dtype
        from ..ops.pallas_fused import (sgd_many_stream_tile,
                                        stream_kernel_mode)

        # fused cohort flavor (ISSUE 12): one VMEM pass per block step
        # serves every model in the cohort (the last XLA-only SGD hot
        # path) when the stacked block height fits the kernel grid —
        # cohort weights are flat by construction (_batch_key refuses
        # multiclass), so the kernel's (N, d+1) stack always applies
        use_k, interp = stream_kernel_mode()
        fused = bool(use_k and sgd_many_stream_tile(
            int(bs_max), int(d), len(models)) is not None)
        runner = (partial(_sgd_cohort_scan_pallas, interpret=interp)
                  if fused else _sgd_cohort_scan)
        W, losses = runner(
            Xr, yr, NV, jnp.asarray(np.asarray(order, np.int32)), W,
            LRS, jnp.asarray(args[:, 0]), jnp.asarray(args[:, 1]),
            jnp.asarray(args[:, 2]), jnp.asarray(args[:, 3]),
            enc._loss(), mxu=mxu_dtype(enc.fit_dtype),
        )
        for i, m in enumerate(models):
            m._w = W[i]
            m._last_loss = losses[i]
            m._t += S
        return models

    # -- streamed-cohort protocol (ISSUE 14 tentpole; consumed by
    # model_selection._incremental's _StreamCohortPlane) ----------------
    @classmethod
    def _cohort_sb_flavor(cls, sb, n_slots, fit_dtype):
        """(fused, mxu, interpret, reason) for the streamed cohort
        scan: :meth:`_sb_scan_flavor`'s gate with the multi-weight tile
        — the fused kernel's (tile, n_slots) MXU matmul must fit VMEM
        for the PADDED slot stack, since that is what every dispatch
        actually carries."""
        from ..config import mxu_dtype
        from ..ops.pallas_fused import (sgd_many_stream_tile,
                                        stream_kernel_mode,
                                        stream_mode_reason,
                                        stream_tile_reason)

        mxu = mxu_dtype(fit_dtype)
        reason = stream_mode_reason()
        if reason is not None:
            return False, mxu, False, reason
        _, interp = stream_kernel_mode()
        Xs = sb.arrays[0]
        S, d = Xs[0].shape if isinstance(Xs, (tuple, list)) \
            else Xs.shape[1:]
        D = sb.shard_counts.shape[0] if sb.shard_counts is not None \
            else 1
        S_local = int(S) // max(int(D), 1)
        tile = sgd_many_stream_tile(S_local, int(d), int(n_slots))
        reason = stream_tile_reason(S_local, tile)
        if reason is not None:
            return False, mxu, False, reason
        return True, mxu, interp, None

    @classmethod
    def _streamed_cohort_round(cls, models, stream, order, act,
                               n_slots, warm=False):
        """Advance a (possibly heterogeneous) adaptive-search cohort
        through ONE streamed super-block pass — the ISSUE 14 tentpole.

        ``order`` is the round's block-step timeline (``order[s]`` is
        the block every active model trains on at step ``s``) and
        ``act`` the ``(len(order), len(models))`` step-activity matrix:
        model ``i`` advances exactly on its own window of steps, with
        the SAME updates and lr clock a per-model ``partial_fit`` loop
        over those blocks would produce. Each super-block is one
        dispatch with the stacked carry donated; the data is read once
        per round regardless of candidate count.

        Slot rungs: the full carry holds ``n_slots`` rows (the
        search's candidate count), but each dispatch GATHERS the union
        of its active slots into the smallest rung of the
        ``_cohort_rungs`` ladder — compute scales with the live
        bracket, not the padded stack — and scatters the rows back.
        ``warm=True`` (the search's first streamed round) dispatches
        every OTHER rung once against the first super-block with an
        all-zero activity mask (a semantic no-op), so bracket halving
        later in the search picks any rung at zero new XLA compiles.

        Flavor selection mirrors the single-model ``_sb_step``: sparse
        slabs take the ``superblock.sparse.sgd_cohort[.psum]``
        programs, a >1-shard stream mesh the ``.psum`` twins, and the
        fused Pallas body (``pallas.sgd_cohort[.psum]``) engages under
        the same tile/mode gates. Returns an engagement/dispatch info
        dict for the search's telemetry."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..observability import record_superblock_donation
        from ..parallel.sparse_stream import SparseSlab

        enc = models[0]
        N = len(models)
        n_slots = max(int(n_slots), N)
        d = int(stream.arrays[0].shape[1])
        for m in models:
            m._ensure_state(d)
        order = np.asarray(order, np.int64)
        act = np.asarray(act, np.float32)
        S_total = len(order)
        LRS = np.ones((S_total, n_slots), np.float32)
        ACT = np.zeros((S_total, n_slots), np.float32)
        ACT[:, :N] = act
        for i, m in enumerate(models):
            steps = np.flatnonzero(act[:, i] > 0)
            LRS[steps, i] = m._lr_schedule(len(steps))
        args = np.zeros((n_slots, 4), np.float32)
        for i, m in enumerate(models):
            l2w, l1w = m._penalty_weights()
            args[i] = (m.alpha, l2w, l1w,
                       1.0 if m.fit_intercept else 0.0)
        # the carry commits REPLICATED on the stream's mesh once per
        # round (single-device meshes included — the scan operands live
        # there), so every dispatch hits one executable and donation
        # aliases in place
        rep = NamedSharding(stream.mesh, P())
        W = jax.device_put(_stack_cohort_weights(models, n_slots), rep)
        loss_name = enc._loss()
        sharded = stream.sb_sharded()
        info = {"streamed": True, "n_steps": int(S_total),
                "shards": int(stream.sb_data_shards()),
                "sparse": bool(stream.sb_sparse()),
                "fused": False, "fused_reason": None,
                "dispatches": 0, "warm_dispatches": 0}
        w_bytes = int(n_slots * (d + 1)) * 4
        state = {"flavor": None}

        def dispatch(W, sb, idx, lr_k, act_k):
            pars = tuple(jnp.asarray(args[idx, j]) for j in range(4))
            idx_d = jnp.asarray(idx)
            lr_d, act_d = jnp.asarray(lr_k), jnp.asarray(act_k)
            slab = sb.arrays[0]
            if isinstance(slab, SparseSlab):
                info["fused_reason"] = "sparse-stream"
                if sharded:
                    run = _sgd_cohort_sb_scan_sparse(
                        loss_name, slab.n_rows, mesh=stream.mesh
                    )
                    return run(W, idx_d, slab.data, slab.cols,
                               slab.rows, sb.arrays[1],
                               sb.shard_counts, sb.counts, lr_d,
                               act_d, *pars)
                run = _sgd_cohort_sb_scan_sparse(loss_name,
                                                 slab.n_rows)
                return run(W, idx_d, slab.data, slab.cols, slab.rows,
                           sb.arrays[1], sb.counts, lr_d, act_d,
                           *pars)
            if state["flavor"] is None:
                # gate once at the TOP rung (max VMEM footprint): if
                # the fused tile fits the full slot stack it fits
                # every smaller rung
                state["flavor"] = cls._cohort_sb_flavor(
                    sb, n_slots, enc.fit_dtype
                )
                info["fused"] = state["flavor"][0]
                info["fused_reason"] = state["flavor"][3]
            fused, mxu, interp, _ = state["flavor"]
            if sharded:
                run = _sgd_cohort_sb_scan_sharded(
                    stream.mesh, loss_name, mxu, fused=fused,
                    interpret=interp,
                )
                return run(W, idx_d, sb.arrays[0], sb.arrays[1],
                           sb.shard_counts, sb.counts, lr_d, act_d,
                           *pars)
            if fused:
                return _sgd_cohort_sb_scan_pallas(
                    W, idx_d, sb.arrays[0], sb.arrays[1], sb.counts,
                    lr_d, act_d, *pars, loss_name, mxu=mxu,
                    interpret=interp,
                )
            return _sgd_cohort_sb_scan(
                W, idx_d, sb.arrays[0], sb.arrays[1], sb.counts,
                lr_d, act_d, *pars, loss_name, mxu=mxu,
            )

        all_slots = np.arange(n_slots)
        pos = 0
        losses = np.zeros((S_total, N), np.float32)
        losses_parts = []
        for sb in stream.superblocks(order=order):
            K = int(sb.counts.shape[0])
            take = sb.n_blocks
            cols = np.flatnonzero(act[pos:pos + take, :].any(axis=0))
            width = _cohort_rung_of(max(len(cols), 1), n_slots)
            spare = np.setdiff1d(all_slots, cols)[: width - len(cols)]
            idx = np.concatenate([cols, spare]).astype(np.int32)
            if warm and info["dispatches"] == 0:
                # round-1 rung warmup: every OTHER ladder width runs
                # once over this super-block with an all-zero activity
                # mask (weights pass through bit-identically), so the
                # whole ladder is compiled before bracket shrinks ask
                # for a narrower rung. Once per PROCESS per shape via
                # the plans WarmupRegistry (ISSUE 15): a later search
                # over the same shapes finds the programs already
                # compiled and skips the executions — and the plans
                # table names the rungs that minted them
                slab0 = sb.arrays[0]
                if not isinstance(slab0, SparseSlab) \
                        and state["flavor"] is None:
                    state["flavor"] = cls._cohort_sb_flavor(
                        sb, n_slots, enc.fit_dtype
                    )
                    info["fused"] = state["flavor"][0]
                    info["fused_reason"] = state["flavor"][3]
                fl = state["flavor"] or (False, None, False, None)
                wkey = (cls.__name__, loss_name, stream.mesh, sharded,
                        n_slots, d, K, int(stream.block_rows),
                        slab0.cap if isinstance(slab0, SparseSlab)
                        else None, fl[0], str(fl[1]), fl[2])
                # attribute warm rungs to the flavor that actually
                # dispatches (sparse / fused / psum variants have their
                # own program rows) — a surprise recompile must name
                # the program that minted it, not a sibling
                if isinstance(slab0, SparseSlab):
                    cohort_prog = "superblock.sparse.sgd_cohort"
                elif fl[0]:
                    cohort_prog = "pallas.sgd_cohort"
                else:
                    cohort_prog = "superblock.sgd_cohort"
                if sharded:
                    cohort_prog += ".psum"
                for rw in _cohort_rungs(n_slots):
                    if rw == width \
                            or plan_warmups.warmed(("cohort", wkey, rw)):
                        continue
                    W, _ = dispatch(
                        W, sb, np.arange(rw, dtype=np.int32),
                        np.ones((K, rw), np.float32),
                        np.zeros((K, rw), np.float32),
                    )
                    plan_warmups.note(("cohort", wkey, rw),
                                      program=cohort_prog,
                                      ladder="cohort-slots", rung=rw,
                                      ran=True)
                    info["warm_dispatches"] += 1
                # the REAL dispatch below compiles this round's own
                # width — register it too, or a later same-shape
                # search starting at a different width would re-run
                # its warm no-op for a program that already exists
                plan_warmups.note(("cohort", wkey, width),
                                  program=cohort_prog,
                                  ladder="cohort-slots", rung=width)
            lr_k = np.ones((K, width), np.float32)
            act_k = np.zeros((K, width), np.float32)
            lr_k[:take] = LRS[pos:pos + take][:, idx]
            act_k[:take] = ACT[pos:pos + take][:, idx]
            W, lv = dispatch(W, sb, idx, lr_k, act_k)
            record_superblock_donation(w_bytes)
            info["dispatches"] += 1
            # loss pulls DEFER to pass end: a per-dispatch np.asarray
            # would synchronize the host on every scan, stalling the
            # staging/compute overlap
            losses_parts.append((pos, take, idx, lv))
            pos += take
        # ONE stable-shape D2H pull per round: weights land back as
        # host rows (a per-model device slice would mint a fresh tiny
        # program per surviving N — exactly the recompile leak the
        # padded stack exists to avoid)
        rows = np.asarray(W, np.float32)
        for p, take, idx, lv in losses_parts:
            lvh = np.asarray(lv, np.float32)[:take]
            live = idx < N
            if live.any():
                losses[p:p + take, idx[live]] = lvh[:, live]
        for i, m in enumerate(models):
            steps = np.flatnonzero(act[:, i] > 0)
            m._w = rows[i].copy()
            m._t += len(steps)
            if len(steps):
                m._last_loss = float(losses[steps[-1], i])
        cls._batch_publish(models, d)
        return info

    @classmethod
    def _cohort_holdout(cls, X_test, y_test, model):
        """Stage the search's validation split ONCE — every round then
        scores the whole surviving cohort against it in one batched
        dispatch. Dense splits stage as device arrays; sparse splits as
        one packed COO triple (nnz cost, no densify)."""
        from ..parallel.streaming import (_is_sparse_source,
                                          as_row_sliceable)

        y_enc = np.asarray(model._encode_y(np.asarray(y_test)),
                           np.float32)
        if _is_sparse_source(X_test):
            from ..parallel.sparse_stream import coo_rows

            src = as_row_sliceable(X_test)
            n = int(src.shape[0])
            data, cols, rows = coo_rows(src, 0, n)
            return {"kind": "sparse", "data": jnp.asarray(data),
                    "cols": jnp.asarray(cols),
                    "rows": jnp.asarray(rows), "n": n, "y": y_enc}
        Xs = as_sharded(np.asarray(X_test), dtype=np.float32)
        ys = as_sharded(y_enc, mesh=Xs.mesh, dtype=np.float32)
        return {"kind": "dense", "X": Xs, "y": ys}

    def _one_step(self, Xb, yb, mask, n_valid):
        from ..config import mxu_dtype

        mxu = mxu_dtype(self.fit_dtype)
        lr, alpha, l2w, l1w, iflag = self._step_args()
        if self._n_out() is not None:
            # multiclass: C one-vs-rest rows advance in one program; yb
            # holds class codes, per-class targets derive in-kernel
            W, losses = _sgd_step_multi(
                Xb, yb, mask, jnp.float32(n_valid), self._w,
                jnp.float32(lr), jnp.float32(alpha), jnp.float32(l2w),
                jnp.float32(l1w), jnp.float32(iflag), self._loss(),
                mxu=mxu,
            )
            self._w = W
            self._last_loss = losses.sum()
            return
        W, losses = _sgd_step_many(
            Xb, yb, mask, jnp.float32(n_valid), self._w[None],
            jnp.asarray([lr]), jnp.asarray([alpha]), jnp.asarray([l2w]),
            jnp.asarray([l1w]), jnp.asarray([iflag]), self._loss(),
            mxu=mxu,
        )
        self._w = W[0]
        self._last_loss = losses[0]

    def _sb_scan_flavor(self, sb):
        """(fused, mxu, interpret, reason) for one super-block: whether
        the Pallas fused-step scan (``pallas.sgd_step`` single-device /
        ``pallas.sgd_step.psum`` inside the shard_map flavor — one VMEM
        pass per block) should carry it, when opted in (real TPU, or
        interpret mode via ``config.pallas_stream_interpret``) and the
        PER-SHARD slab height (S/D rows — what each kernel instance
        actually sees) fits the 128-row grid; else the XLA scan, with
        ``reason`` naming the gate that refused (None when fused
        engaged). ``mxu`` is the resolved compute dtype
        (config.dtype="auto" → bf16 on TPU only); both flavors honor
        it, and with everything off/at-default the XLA program traces
        byte-identically to the pre-feature one."""
        from ..config import mxu_dtype
        from ..ops.pallas_fused import (sgd_many_stream_tile,
                                        sgd_stream_tile,
                                        stream_kernel_mode,
                                        stream_mode_reason,
                                        stream_tile_reason)

        mxu = mxu_dtype(self.fit_dtype)
        reason = stream_mode_reason()
        if reason is not None:
            return False, mxu, False, reason
        _, interp = stream_kernel_mode()
        Xs = sb.arrays[0]
        S, d = Xs[0].shape if isinstance(Xs, (tuple, list)) \
            else Xs.shape[1:]
        D = sb.shard_counts.shape[0] if sb.shard_counts is not None \
            else 1
        S_local = int(S) // max(int(D), 1)
        n_out = self._n_out()
        tile = (sgd_many_stream_tile(S_local, int(d), n_out)
                if n_out is not None
                else sgd_stream_tile(S_local, int(d)))
        reason = stream_tile_reason(S_local, tile)
        if reason is not None:
            return False, mxu, False, reason
        return True, mxu, interp, None

    def _sb_step(self, sb):
        """Advance through one SuperBlock — K minibatch steps, ONE
        dispatch, donated weight carry. The lr clock advances exactly as
        K ``_step_args`` calls would (``_lr_schedule`` precomputes the
        same host values); padding slots get a placeholder lr their
        pass-through step never reads."""
        from ..observability import record_superblock_donation

        k = int(sb.counts.shape[0])
        lrs = np.ones(k, np.float32)
        lrs[:sb.n_blocks] = self._lr_schedule(sb.n_blocks)
        l2w, l1w = self._penalty_weights()
        w_bytes = int(np.prod(self._w.shape)) * 4
        from ..parallel.sparse_stream import SparseSlab

        if isinstance(sb.arrays[0], SparseSlab):
            return self._sb_step_sparse(sb, lrs, l2w, l1w, w_bytes)
        fused, mxu, interp, reason = self._sb_scan_flavor(sb)
        # on record for solver_info_ (the fused-engagement audit trail
        # tpu_smoke asserts on)
        self._fused_stream = fused
        self._fused_stream_reason = reason
        if sb.shard_counts is not None:
            # data-parallel flavor (ISSUE 9): blocks staged batch-
            # sharded over the stream mesh; the scan runs under
            # shard_map with the weight carry replicated and one
            # gradient psum per block step — the per-shard raw sums
            # coming from the fused Pallas body when the flavor gate
            # passes (ISSUE 12). The carry is committed replicated ONCE
            # so every dispatch of the fit hits the same executable
            # (and donation aliases in place)
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = sb.shard_counts.sharding.mesh
            rep = NamedSharding(mesh, P())
            if getattr(self._w, "sharding", None) != rep:
                self._w = jax.device_put(self._w, rep)
            run = _sgd_sb_scan_sharded(mesh, self._loss(),
                                       self._n_out(), mxu,
                                       fused=fused, interpret=interp)
            W, losses = run(
                self._w, sb.arrays[0], sb.arrays[1], sb.shard_counts,
                sb.counts, jnp.asarray(lrs), jnp.float32(self.alpha),
                jnp.float32(l2w), jnp.float32(l1w),
                jnp.float32(1.0 if self.fit_intercept else 0.0),
            )
            record_superblock_donation(w_bytes)
            self._w = W
            self._t += sb.n_blocks
            self._last_loss = losses[sb.n_blocks - 1]
            return
        if fused:
            W, losses = _sgd_sb_scan_pallas(
                self._w, sb.arrays[0], sb.arrays[1], sb.counts,
                jnp.asarray(lrs), jnp.float32(self.alpha),
                jnp.float32(l2w), jnp.float32(l1w),
                jnp.float32(1.0 if self.fit_intercept else 0.0),
                self._loss(), n_out=self._n_out(), mxu=mxu,
                interpret=interp,
            )
        else:
            W, losses = _sgd_sb_scan(
                self._w, sb.arrays[0], sb.arrays[1], sb.counts,
                jnp.asarray(lrs), jnp.float32(self.alpha),
                jnp.float32(l2w), jnp.float32(l1w),
                jnp.float32(1.0 if self.fit_intercept else 0.0),
                self._loss(), self._n_out(), mxu=mxu,
            )
        record_superblock_donation(w_bytes)
        self._w = W
        self._t += sb.n_blocks
        self._last_loss = losses[sb.n_blocks - 1]

    def _sb_step_sparse(self, sb, lrs, l2w, l1w, w_bytes):
        """The bucketed-nnz flavor of :meth:`_sb_step` (ISSUE 13): K
        minibatch steps over the staged sparse slab in ONE donated-carry
        scan — eta/gradient at nnz cost, same lr clock and padding-slot
        semantics; one gradient psum per block step under the sharded
        flavor (the dense sharded scan's exact collective shape)."""
        from ..observability import record_superblock_donation

        slab = sb.arrays[0]
        self._fused_stream = False
        self._fused_stream_reason = "sparse-stream"
        self._sparse_stream = True
        if sb.shard_counts is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = sb.shard_counts.sharding.mesh
            rep = NamedSharding(mesh, P())
            if getattr(self._w, "sharding", None) != rep:
                self._w = jax.device_put(self._w, rep)
            run = _sgd_sb_scan_sparse(self._loss(), self._n_out(),
                                      slab.n_rows, mesh=mesh)
            W, losses = run(
                self._w, slab.data, slab.cols, slab.rows, sb.arrays[1],
                sb.shard_counts, sb.counts, jnp.asarray(lrs),
                jnp.float32(self.alpha), jnp.float32(l2w),
                jnp.float32(l1w),
                jnp.float32(1.0 if self.fit_intercept else 0.0),
            )
        else:
            run = _sgd_sb_scan_sparse(self._loss(), self._n_out(),
                                      slab.n_rows)
            W, losses = run(
                self._w, slab.data, slab.cols, slab.rows, sb.arrays[1],
                sb.counts, jnp.asarray(lrs), jnp.float32(self.alpha),
                jnp.float32(l2w), jnp.float32(l1w),
                jnp.float32(1.0 if self.fit_intercept else 0.0),
            )
        record_superblock_donation(w_bytes)
        self._w = W
        self._t += sb.n_blocks
        self._last_loss = losses[sb.n_blocks - 1]

    def _stream_pass(self, Xh, yh, block_rows, order=None, classes=None,
                     shuffle=False, seed=None):
        """One partial_fit pass over host data as super-block scans (the
        Incremental wrapper's fused driver for host-resident X): block
        ``order[j]`` is the j-th minibatch, identical updates and lr
        clock to a per-block ``partial_fit`` loop over the same
        partition. Returns False when the super-block path is
        unavailable (opt-out, K == 1, sparse source) — the caller runs
        its per-block loop instead."""
        from ..parallel.streaming import BlockStream, _is_sparse_source

        sparse_src = _is_sparse_source(Xh)
        if classes is not None:
            self._set_classes(np.asarray(classes))
        if isinstance(self, ClassifierMixin) and \
                getattr(self, "classes_", None) is None:
            raise ValueError(
                "classes must be passed on the first call to partial_fit."
            )
        if not sparse_src:
            Xh = np.asarray(Xh)
        y_enc = np.asarray(self._encode_y(np.asarray(yh)))
        stream = BlockStream((Xh, y_enc), block_rows=block_rows,
                             shuffle=shuffle, seed=seed)
        if sparse_src and stream.sparse_plan is None:
            # sparse source without a device-resident staging plan
            # (config.stream_sparse off, over-density fallback): the
            # caller's per-block densify loop stays the path
            return False
        if stream.block_rows != int(block_rows):
            # the stream rounds block_rows to a shard multiple; a caller
            # partition it cannot reproduce must keep its own loop —
            # training different minibatches would be a silent change
            return False
        if not stream.use_superblocks():
            return False
        self._ensure_state(Xh.shape[1])
        for sb in stream.superblocks(order=order):
            self._sb_step(sb)
        self._last_stream_stats = getattr(stream, "stats", None)
        prof = stream.profile_snapshot()
        if prof is not None:
            # accumulate across partial_fit calls: one training profile
            # covers every pass this model ever trained on
            from ..observability.sketch import merge_profiles

            self.training_profile_ = merge_profiles(
                getattr(self, "training_profile_", None), prof
            )
        self._publish(Xh.shape[1])
        return True

    def _stream_fit_checkpoint(self, Xh, y_enc, stream):
        """A fingerprint-keyed pass-granular checkpoint slot for this
        host-streamed fit (reliability/stream_ckpt.py), or None when
        checkpointing is off, refused (multi-process), or the fit is a
        ``warm_start`` continuation (its starting weights are not
        derivable from the hyperparameters, so the identity token
        cannot cover them)."""
        if self.warm_start:
            return None
        from ..reliability.stream_ckpt import stream_checkpoint

        classes = getattr(self, "classes_", None)
        parts = (
            type(self).__name__, self._loss(), self.penalty,
            self.alpha, self.l1_ratio, self.eta0, self.learning_rate,
            self.power_t, self.max_iter, self.tol, self.shuffle,
            self.random_state, self.fit_intercept, self.fit_dtype,
            None if classes is None
            else tuple(np.asarray(classes).tolist()),
            tuple(Xh.shape), int(stream.block_rows),
        )
        return stream_checkpoint("sgd", parts, arrays=(Xh, y_enc))

    def _fit_stream_checkpointed(self, stream, ckpt):
        """The checkpointed flavor of the streamed epoch loop:
        identical minibatches and lr clock to the plain loops (the
        shuffle stream is fast-forwarded by one permutation draw per
        completed pass — np.random's shuffle consumption depends only
        on the array LENGTH, so the resumed pass sequence is
        bit-identical to the uninterrupted fit's), with the weight
        carry + lr clock saved after each pass and the slot cleared on
        completion. Autotune never applies here: a mid-fit partition
        resize would invalidate the checkpoint's identity token."""
        from ..observability._counters import record_stream_checkpoint

        start = 0
        st = ckpt.restore()
        if st is not None:
            self._w = jnp.asarray(np.asarray(st["w"], np.float32))
            self._t = int(st["t"])
            start = int(st["epoch"])
            record_stream_checkpoint(resume=True)
        if self.shuffle:
            burn = np.arange(stream.n_blocks)
            for _ in range(min(start, int(self.max_iter))):
                stream.rng.shuffle(burn)
        use_sb = stream.use_superblocks()
        for e in range(start, int(self.max_iter)):
            if use_sb:
                for sb in stream.superblocks():
                    self._sb_step(sb)
            else:
                for block in stream:
                    if block.n_rows == 0:
                        self._t += 1
                        continue
                    Xb, yb = block.arrays
                    self._one_step(Xb, yb, block.mask, block.n_rows)
            if ckpt.due(e + 1):
                ckpt.save(w=np.asarray(self._w), t=self._t, epoch=e + 1)
        ckpt.clear()

    def _fit_stream_grad_accum(self, stream, A):
        """The gradient-accumulation streamed fit
        (``config.stream_grad_accum`` = A >= 1): each update consumes A
        LOCAL micro-blocks' gradient sums — merged ONCE across
        processes (``psum_host``, f64, fixed gather order) — then one
        shared epilogue applies the update, so every process holds
        identical weights after every step. This is the documented
        optimizer variant that lifts the cross-host streamed-SGD
        refusal: sequential per-block updates cannot psum across
        process-local streams, but accumulated GROUP gradients can.

        Contracts: exact parity with the sequential single-process fit
        at A=1 (the micro kernel normalizes by the group's GLOBAL
        valid-row count inside autodiff — at A=1 single-process that IS
        the sequential step's traced objective; bit-exact vs the
        single-DEVICE sequential flavor, while the sharded sequential
        scan normalizes its raw sums after the psum and so differs at
        float-reassociation level on non-power-of-two block counts);
        at A>1 or P>1 the
        effective batch per update is A x P x block_rows — fewer,
        larger steps per pass (README documents the convergence
        caveat), with the lr clock ticking once per UPDATE. Local
        micro sums accumulate on host in f64 in block order — the same
        additions the cross-process merge performs, so a P-process fit
        at A and a single-process fit at P*A over the round-robin
        block interleave are bit-identical whenever the per-block
        kernels run at matching device partitioning (e.g.
        stream_mesh=1; different mesh widths reassociate the matmul
        partial sums at the usual ~1e-7 relative level). Pass-granular
        checkpointing does not arm here (a multi-process resume must
        be a collective decision)."""
        from ..config import get_config, mxu_dtype
        from ..parallel import distributed as dist

        if get_config().stream_nonfinite == "quarantine":
            # the per-group GLOBAL valid-row counts are exchanged (a
            # collective) BEFORE the blocks are read, so a count folded
            # to zero at read time would leave the group normalizer —
            # and the skip-empty-update contract every other flavor
            # honors — silently wrong. Refuse loudly instead
            raise ValueError(
                "stream_grad_accum does not compose with "
                "stream_nonfinite='quarantine' (group counts are "
                "exchanged before blocks are read); use "
                "stream_nonfinite='raise' or the sequential flavor"
            )
        if get_config().stream_checkpoint_path:
            import warnings

            warnings.warn(
                "stream_checkpoint_path is set but the grad-accum "
                "streamed SGD flavor does not checkpoint (its update "
                "schedule is a collective); the fit runs uncheckpointed",
                RuntimeWarning,
            )
        A = int(A)
        multi = dist.process_count() > 1
        n_blocks = stream.n_blocks
        block_rows = stream.block_rows
        starts = np.arange(n_blocks, dtype=np.int64) * block_rows
        counts = np.minimum(starts + block_rows, stream.n_rows) - starts
        n_groups_local = max(-(-n_blocks // A), 1)
        # every process must join the same NUMBER of group merges per
        # pass (the merge is a collective): pad to the widest local
        # pass; a process past its own blocks contributes zero sums
        n_groups = int(max(dist.allgather_object(n_groups_local))) \
            if multi else n_groups_local
        mxu = mxu_dtype(self.fit_dtype)
        n_out = self._n_out()
        loss_name = self._loss()
        iflag = np.float32(1.0 if self.fit_intercept else 0.0)
        w_shape = tuple(np.shape(self._w))
        # commit the weight carry REPLICATED on the stream's mesh once:
        # the micro kernels then always see compatible devices (a
        # virtual rank's blocks stage on ITS local submesh, not the
        # process default device), and every update's output inherits
        # the placement
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(stream.mesh, P())
        if getattr(self._w, "sharding", None) != rep:
            self._w = jax.device_put(self._w, rep)
        # the sparse grad-accum micro flavor (ISSUE 13): bucketed-nnz
        # per-block staging + nnz-cost value_and_grad. Single-device
        # streams only — the sparse per-block slabs place on the
        # stream's (replicated) mesh, and grad-accum's merge is the
        # host psum anyway; sharded streams keep the densify micro path
        use_sparse = (getattr(stream, "sparse_plan", None) is not None
                      and stream.sb_data_shards() == 1)
        self._sparse_stream = bool(use_sparse)
        for _ in range(int(self.max_iter)):
            order = np.arange(n_blocks)
            if self.shuffle:
                stream.rng.shuffle(order)
            # the per-group GLOBAL valid-row counts, exchanged once per
            # pass: the micro kernels normalize by them inside autodiff
            local_nv = np.zeros(n_groups, np.float64)
            for g in range(n_groups_local):
                local_nv[g] = float(
                    counts[order[g * A:(g + 1) * A]].sum()
                )
            group_nv = np.asarray(dist.psum_host(local_nv)) if multi \
                else local_nv
            for g in range(n_groups):
                gsum, lsum = None, 0.0
                nv = jnp.float32(group_nv[g])
                for b in order[g * A:(g + 1) * A]:
                    if use_sparse:
                        slab, dense, mask_d, _m = \
                            stream.sparse_block_put(int(b))
                        v, gr = _sgd_accum_micro_sparse(
                            self._w, slab.data, slab.cols, slab.rows,
                            dense[0], mask_d, nv, jnp.float32(iflag),
                            loss_name, n_out, slab.n_rows,
                        )
                    else:
                        blk = stream._put(stream._block_host(int(b)))
                        Xb, yb = blk.arrays
                        v, gr = _sgd_accum_micro(
                            self._w, Xb, yb, blk.mask, nv,
                            jnp.float32(iflag), loss_name, n_out,
                            mxu=mxu,
                        )
                    lsum += float(v)
                    g64 = np.asarray(gr, np.float64)
                    gsum = g64 if gsum is None else gsum + g64
                if gsum is None:
                    gsum = np.zeros(w_shape, np.float64)
                if multi:
                    lsum, gsum = dist.psum_host(
                        np.asarray(lsum, np.float64), gsum
                    )
                lr, alpha, l2w, l1w, _ = self._step_args()
                w_old = np.asarray(self._w, np.float64)
                self._w = _sgd_accum_apply(
                    self._w, jnp.asarray(np.asarray(gsum, np.float32)),
                    jnp.float32(lr), jnp.float32(alpha),
                    jnp.float32(l2w), jnp.float32(l1w),
                )
                self._last_loss = float(np.asarray(lsum)) \
                    + 0.5 * alpha * l2w \
                    * float(np.sum(w_old[..., :-1] ** 2))
            # the profile folds the first pass only, like the streams
            stream._passes = getattr(stream, "_passes", 0) + 1

    def _fit_device(self, X: ShardedArray, y, kwargs):
        """Epoch loop over DEVICE-resident blocks: each block is a sharded
        gather (take_rows) of the input — the (n, d) data never
        round-trips through host (VERDICT r2 #4; the reference's
        Incremental chains partial_fit over worker-resident chunks the
        same way, SURVEY.md §3.6)."""
        from ..parallel.sharded import take_rows

        ys = y if isinstance(y, ShardedArray) \
            else ShardedArray.from_array(np.asarray(y), mesh=X.mesh)
        if isinstance(self, ClassifierMixin):
            classes = kwargs.get("classes")
            if classes is not None:
                self._set_classes(np.asarray(classes))
            elif getattr(self, "classes_", None) is None:
                from ..utils.validation import device_classes

                self._set_classes(device_classes(ys))
        y_enc = self._encode_y(ys)
        n = X.n_rows
        # the grid_partition blocks — the SAME minibatches a host-input
        # fit or the Incremental wrapper trains (reproducibility across
        # input residency)
        _, S = fused_blocks(X)
        ranges = [r for r in
                  (np.arange(s, min(s + S, n)) for s in range(0, n, S))
                  if len(r)]
        self._ensure_state(X.shape[1])
        rng = np.random.RandomState(self.random_state)
        order = np.arange(len(ranges))
        for _ in range(self.max_iter):
            if self.shuffle:
                rng.shuffle(order)
            # blocks gather lazily per step (one extra block resident at
            # a time) — materializing all of them would hold a second
            # full copy of X in HBM for the whole fit
            for b in order:
                Xb = take_rows(X, ranges[b])
                yb = take_rows(y_enc, ranges[b])
                self._one_step(Xb.data, yb.data,
                               Xb.row_mask(jnp.float32), Xb.n_rows)
        self._publish(X.shape[1])
        self.n_iter_ = self.max_iter
        return self

    def fit(self, X, y, **kwargs):
        if not self.warm_start:
            self._w = None
            if getattr(self, "classes_", None) is not None:
                self.classes_ = None  # fresh fit re-derives classes
        if isinstance(X, ShardedArray):
            return self._fit_device(X, y, kwargs)
        from ..parallel import distributed as dist
        from ..parallel.streaming import (BlockStream, _is_sparse_source,
                                          fit_block_rows)

        from ..config import get_config

        grad_accum = int(get_config().stream_grad_accum)
        if dist.process_count() > 1 and grad_accum <= 0:
            # sequential per-block updates are ORDER-dependent — unlike
            # the additive GLM/KMeans/PCA accumulators they cannot psum
            # into a global fit; silently fitting each shard separately
            # would hand every process a different model. The
            # gradient-accumulation flavor (config.stream_grad_accum=A)
            # IS the documented cross-host variant: accumulated GROUP
            # gradients psum exactly
            raise NotImplementedError(
                "host-streamed SGD fit is single-process by default "
                "(sequential updates cannot psum across process-local "
                "streams); set config.stream_grad_accum=A (>= 1) for "
                "the gradient-accumulation flavor — one cross-host "
                "psum per A micro-blocks — or use the streamed GLM "
                "fits / device-resident data on the global mesh"
            )
        # sparse X streams as-is: BlockStream densifies one block at a
        # time (the text-pipeline bridge — a whole-corpus np.asarray
        # would materialize the dense matrix this path exists to avoid)
        Xh = X if _is_sparse_source(X) else np.asarray(X)
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        if isinstance(self, ClassifierMixin):
            classes = kwargs.get("classes")
            if classes is not None:
                self._set_classes(np.asarray(classes))
            elif getattr(self, "classes_", None) is None:
                self._set_classes(np.unique(yh))
        y_enc = np.asarray(self._encode_y(yh))
        stream = BlockStream(
            (Xh, y_enc),
            block_rows=fit_block_rows(Xh),
            shuffle=self.shuffle, seed=self.random_state,
        )
        self._ensure_state(Xh.shape[1])
        # fused/sparse-engagement audit defaults; _sb_step overwrites
        # when the super-block path runs
        self._fused_stream = False
        self._fused_stream_reason = "per-block-path"
        self._sparse_stream = False
        if grad_accum >= 1:
            # gradient-accumulation flavor (cross-host capable): A
            # micro-blocks' sums -> one psum -> one shared update
            self._fused_stream_reason = "grad-accum-xla"
            self._fit_stream_grad_accum(stream, grad_accum)
        elif (ckpt := self._stream_fit_checkpoint(Xh, y_enc,
                                                  stream)) is not None:
            # pass-granular checkpoint/auto-resume (ISSUE 11): same
            # minibatches and lr clock as the plain loops below, plus a
            # carry save after each pass and a clear on completion
            self._fit_stream_checkpointed(stream, ckpt)
        elif stream.use_superblocks():
            # super-block hot loop: one scan dispatch per K blocks with
            # the weight carry donated (same minibatches, same shuffled
            # order, same lr clock as the per-block loop below)
            for sb in stream.superblock_epochs(self.max_iter):
                self._sb_step(sb)
        else:
            for block in stream.epochs(self.max_iter):
                if block.n_rows == 0:
                    # quarantined block (stream_nonfinite): no update,
                    # but the lr clock advances exactly like the
                    # superblock scan's zero-count pass-through slot
                    self._t += 1
                    continue
                Xb, yb = block.arrays
                self._one_step(Xb, yb, block.mask, block.n_rows)
        # last pass's overlap accounting (host/put/wait vs compute) for
        # bench and diagnosis of transfer-bound fits
        self._last_stream_stats = getattr(stream, "stats", None)
        # per-feature training profile (drift.py scores serving traffic
        # against it); a fresh fit replaces any previous profile
        self.training_profile_ = stream.profile_snapshot()
        # the streamed-fit audit record (GLM fits carry the same keys):
        # which flavor ran, why fused was gated off if it was, and the
        # grad-accum width — so smoke suites assert engagement instead
        # of trusting the gate
        sparse_on = bool(getattr(self, "_sparse_stream", False))
        if sparse_on:
            sparse_reason = None
        elif getattr(stream, "sparse_plan", None) is not None:
            sparse_reason = "per-block-path"
        elif getattr(stream, "sparse_reason", None) is not None:
            sparse_reason = stream.sparse_reason
        else:
            sparse_reason = "dense-source"
        self.solver_info_ = {
            "streamed": True,
            "n_blocks": int(stream.n_blocks),
            "stream_shards": int(stream.sb_data_shards())
            if stream.use_superblocks() and grad_accum < 1 else 1,
            "grad_accum": grad_accum if grad_accum >= 1 else 0,
            "fused_stream": bool(getattr(self, "_fused_stream", False)),
            "fused_stream_reason": getattr(
                self, "_fused_stream_reason", None
            ),
            # the device-resident sparse audit trail (ISSUE 13),
            # mirroring fused_stream_reason: None iff the bucketed-nnz
            # programs carried the fit
            "sparse_stream": sparse_on,
            "sparse_stream_reason": sparse_reason,
        }
        self._publish(Xh.shape[1])
        self.n_iter_ = self.max_iter
        return self

    def _decision(self, X):
        X = as_sharded(X, dtype=np.float32)
        w = self._w
        return X, X.data @ w[:-1] + w[-1]

    def _eta_stream(self, X, block_rows):
        """Decision values for out-of-core / sparse X: blocks stream
        through the fitted weights, (n,) or (n, C) host result — same
        bridge as the GLM predict paths. The weights ride as HOST
        numpy: a cohort-trained ``_w`` may be committed to the full
        ambient mesh while the predict stream stages on its own
        (possibly single-device) stream mesh — an uncommitted operand
        follows the block's placement instead of raising a
        mixed-devices error."""
        from ..parallel.streaming import streamed_map

        W = np.asarray(self._w, np.float32)
        if self._n_out() is not None:
            return streamed_map(
                X, block_rows, lambda blk: _batched_eta(blk.arrays[0], W)
            )
        return streamed_map(
            X, block_rows, lambda blk: blk.arrays[0] @ W[:-1] + W[-1]
        )

    def _encode_y(self, y):
        if isinstance(y, ShardedArray):
            return y
        return np.asarray(y)

    def _publish(self, d):
        pass


class SGDClassifier(ClassifierMixin, _SGDBase):
    """Binary classifier; device analog of sklearn's SGDClassifier for the
    Incremental / adaptive-search streaming paths."""

    loss_default = "log_loss"

    def _batch_key(self):
        if getattr(self, "classes_", None) is None:
            # solo path enforces the first-call classes contract (raises);
            # batching without classes would train on un-encoded labels
            return None
        if self._n_out() is not None:
            return None  # multiclass weights are (C, d+1): solo path
        return super()._batch_key()

    def _set_classes(self, classes):
        if len(classes) < 2:
            raise ValueError("SGDClassifier needs at least 2 classes")
        have = getattr(self, "classes_", None)
        if have is not None and not np.array_equal(classes, have):
            # sklearn contract: classes must be identical across calls —
            # silently re-encoding labels mid-training corrupts the model
            raise ValueError(
                f"classes={classes} is not the same as on last call "
                f"to partial_fit, was: {have}"
            )
        self.classes_ = classes

    def partial_fit(self, X, y, classes=None, **kwargs):
        # sklearn contract: classes required on the first partial_fit call
        # (adaptive searches pass it through fit_params, as with dask-ml)
        if classes is None and getattr(self, "classes_", None) is None:
            raise ValueError(
                "classes must be passed on the first call to partial_fit."
            )
        return super().partial_fit(X, y, classes=classes, **kwargs)

    def _encode_y(self, y):
        if getattr(self, "classes_", None) is None:
            return y if isinstance(y, ShardedArray) else np.asarray(y)
        if self._n_out() is not None:
            # multiclass: labels map to class CODES 0..C-1 (searchsorted
            # over the sorted classes_, in the labels' NATIVE dtype —
            # handles string labels and >2**24 integer ids exactly);
            # the codes ride to the kernel as float32 (C-1 is tiny).
            # sklearn partial_fit contract: a label absent from classes_
            # (e.g. first appearing in a later block) must raise, not
            # silently train as a neighboring code — one host sync per
            # block buys that check.
            if isinstance(y, ShardedArray):
                classes_d = jnp.asarray(
                    np.asarray(self.classes_, np.dtype(str(y.dtype)))
                )
                idx = jnp.searchsorted(classes_d, y.data)
                idx_c = jnp.clip(idx, 0, len(self.classes_) - 1)
                ok = jnp.take(classes_d, idx_c) == y.data
                bad = jnp.any(y.row_mask(jnp.bool_) & ~ok)
                if bool(bad):
                    raise ValueError(
                        "y contains classes not passed via `classes` on "
                        "the first partial_fit call"
                    )
                return ShardedArray(
                    idx_c.astype(jnp.float32), y.n_rows, y.mesh,
                )
            yh = np.asarray(y)
            idx = np.clip(np.searchsorted(self.classes_, yh),
                          0, len(self.classes_) - 1)
            if not np.array_equal(np.take(self.classes_, idx), yh):
                raise ValueError(
                    "y contains classes not passed via `classes` on the "
                    "first partial_fit call"
                )
            return idx.astype(np.float32)
        neg, pos = self.classes_[0], self.classes_[1]
        if isinstance(y, ShardedArray):
            is_pos = y.data == jnp.asarray(pos)
            known = is_pos | (y.data == jnp.asarray(neg))
            if bool(jnp.any(y.row_mask(jnp.bool_) & ~known)):
                raise ValueError(
                    "y contains classes not passed via `classes` on the "
                    "first partial_fit call"
                )
            return ShardedArray(
                is_pos.astype(jnp.float32), y.n_rows, y.mesh,
            )
        yh = np.asarray(y)
        if not np.isin(yh, self.classes_).all():
            raise ValueError(
                "y contains classes not passed via `classes` on the "
                "first partial_fit call"
            )
        return (yh == pos).astype(np.float32)

    def _publish(self, d):
        w = to_host(self._w).astype(np.float64)
        if self._n_out() is not None:
            self.coef_ = w[:, :-1]
            self.intercept_ = w[:, -1]
        else:
            self.coef_ = w[:-1].reshape(1, -1)
            self.intercept_ = np.atleast_1d(w[-1])

    @classmethod
    def _batched_score_default(cls, models, X, y):
        """Accuracy of N models on a shared (device) test split — one
        matmul on the MXU instead of N predict calls."""
        Xs = as_sharded(X, dtype=np.float32)
        ys = as_sharded(models[0]._encode_y(y), mesh=Xs.mesh,
                        dtype=np.float32)
        W = jnp.stack([m._w for m in models])
        acc = _batched_accuracy(
            Xs.data, ys.data, Xs.row_mask(jnp.float32),
            jnp.float32(Xs.n_rows), W,
        )
        return np.asarray(acc, np.float64)

    @classmethod
    def _cohort_holdout_scores(cls, models, holdout, n_slots):
        """Round scoring as ONE batched dispatch over the staged
        validation slab (ISSUE 14): the PADDED slot stack keeps the
        scoring program's shape constant across shrinking brackets —
        same accuracy math as ``_batched_score_default``."""
        W = jnp.asarray(_stack_cohort_weights(models, n_slots))
        N = len(models)
        if holdout["kind"] == "sparse":
            eta = np.asarray(_batched_eta_sparse(
                holdout["data"], holdout["cols"], holdout["rows"], W,
                n_rows=holdout["n"],
            ))[:, :N]
            y01 = holdout["y"]
            acc = ((eta > 0).astype(np.float32)
                   == y01[:, None]).mean(axis=0)
            return np.asarray(acc, np.float64)
        Xs, ys = holdout["X"], holdout["y"]
        acc = _batched_accuracy(
            Xs.data, ys.data, Xs.row_mask(jnp.float32),
            jnp.float32(Xs.n_rows), W,
        )
        return np.asarray(acc, np.float64)[:N]

    def decision_function(self, X):
        check_is_fitted(self, "coef_")
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._eta_stream(X, block_rows)
        if self._n_out() is not None:
            Xs = as_sharded(X, dtype=np.float32)
            eta = _batched_eta(Xs.data, self._w)   # (n, C)
            return to_host(eta)[: Xs.n_rows]
        X, eta = self._decision(X)
        return to_host(eta)[: X.n_rows]

    def predict(self, X):
        scores = self.decision_function(X)
        if self._n_out() is not None:
            return self.classes_[np.argmax(scores, axis=1)]
        return self.classes_[(scores > 0).astype(int)]

    def predict_proba(self, X):
        if self._loss() != "log_loss":
            raise AttributeError("predict_proba requires loss='log_loss'")
        check_is_fitted(self, "coef_")
        from scipy.special import expit

        if self._n_out() is not None:
            p = expit(self.decision_function(X))   # OvR sigmoids
            return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        p1 = expit(self.decision_function(X))
        return np.stack([1 - p1, p1], axis=1)

    def score(self, X, y):
        return accuracy_score(
            y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y),
            self.predict(X),
        )


class SGDRegressor(RegressorMixin, _SGDBase):
    loss_default = "squared_error"

    def _set_classes(self, classes):  # pragma: no cover - defensive
        raise AttributeError("SGDRegressor has no classes")

    def _batch_prepare(self, fit_params):
        pass

    def _publish(self, d):
        w = to_host(self._w).astype(np.float64)
        self.coef_ = w[:-1]
        self.intercept_ = float(w[-1])

    @classmethod
    def _batched_score_default(cls, models, X, y):
        Xs = as_sharded(X, dtype=np.float32)
        ys = as_sharded(y, mesh=Xs.mesh, dtype=np.float32)
        W = jnp.stack([m._w for m in models])
        r2 = _batched_r2(
            Xs.data, ys.data, Xs.row_mask(jnp.float32),
            jnp.float32(Xs.n_rows), W,
        )
        return np.asarray(r2, np.float64)

    @classmethod
    def _cohort_holdout_scores(cls, models, holdout, n_slots):
        """R^2 twin of the classifier's one-dispatch round scoring —
        padded slot stack, stable program shape across bracket
        shrinks."""
        W = jnp.asarray(_stack_cohort_weights(models, n_slots))
        N = len(models)
        if holdout["kind"] == "sparse":
            eta = np.asarray(_batched_eta_sparse(
                holdout["data"], holdout["cols"], holdout["rows"], W,
                n_rows=holdout["n"],
            ))[:, :N]
            y = np.asarray(holdout["y"], np.float64)
            ss_tot = float(np.sum((y - y.mean()) ** 2))
            ss_res = np.sum((eta - y[:, None]) ** 2, axis=0)
            return 1.0 - ss_res / max(ss_tot, 1e-12)
        Xs, ys = holdout["X"], holdout["y"]
        r2 = _batched_r2(
            Xs.data, ys.data, Xs.row_mask(jnp.float32),
            jnp.float32(Xs.n_rows), W,
        )
        return np.asarray(r2, np.float64)[:N]

    def predict(self, X):
        check_is_fitted(self, "coef_")
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._eta_stream(X, block_rows)
        X, eta = self._decision(X)
        return to_host(eta)[: X.n_rows]

    def score(self, X, y):
        return r2_score(
            y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y),
            self.predict(X),
        )
