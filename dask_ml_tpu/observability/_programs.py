"""Compiled-program registry: per-program cost/memory attribution.

The observability core answers "where did the time go"; this module
answers "where did the FLOPs and HBM go". Every jitted hot path
(GLM/SGD/KMeans solver chunks, super-block scan programs, serving batch
entry points) is wrapped with :func:`track_program`, which keeps one
registry row per program name:

- ``compiles`` / ``compile_s`` — fresh XLA specializations this program
  paid (detected via jit-cache growth) and their measured compile time;
- ``flops_per_call`` / ``bytes_per_call`` — XLA's own
  ``Compiled.cost_analysis()`` for the latest specialization (measured
  program cost, not a hand-written analytic formula);
- ``hbm_peak_bytes`` (argument + output + temp) — ``memory_analysis()``
  of the latest specialization;
- ``calls`` / ``exec_s`` / ``flops_total`` — invocation accounting.
  ``exec_s`` is host-side dispatch time (no barrier is ever inserted —
  blocking would destroy the async-dispatch overlap the hot paths rely
  on): exact on the synchronous CPU backend, enqueue-only under TPU/GPU
  async dispatch. Per-span MFU (span wall + sync) is the measured
  number everywhere; the report only renders program-level MFU for cpu
  runs.

Each tracked call also feeds the flat counter registry
(``program_flops``), so span records pick up ``ctr_program_flops``
deltas and the report CLI computes **measured MFU per span** against
the peak table in ``_peak.py``.

FLOP semantics: ``cost_analysis`` counts a ``lax.scan`` body times its
(static) trip count, so super-block scan programs and fused epochs are
exact; a ``lax.while_loop`` body (the in-core solvers' outer iteration)
is counted ONCE because XLA cannot know the trip count — those
programs' flops_per_call, and any span MFU built on them, are honest
LOWER bounds (one iteration's worth per call).

Gating: ``config.obs_programs`` (default OFF). Disabled, a tracked call
is one config read and a plain passthrough — nothing enters traced
code, the registry stays empty, and no extra compile ever runs. Enabled,
each fresh compile pays ONE extra AOT ``lower().compile()`` of the same
program (in-memory cached by jax thereafter) to fetch the analyses; that
extra compile also increments the ``recompiles``/``compile_secs``
counters, which is why zero-recompile perf gates keep the knob off.
"""

from __future__ import annotations

import functools
import threading
import time

from ._counters import counter_add, counters_enabled

_lock = threading.Lock()
_programs: dict[str, dict] = {}


def programs_enabled() -> bool:
    from ..config import get_config

    return bool(get_config().obs_programs)


def _entry(name: str) -> dict:
    e = _programs.get(name)
    if e is None:
        e = _programs[name] = {
            "program": name,
            "compiles": 0,
            "compile_s": 0.0,
            "calls": 0,
            "exec_s": 0.0,
            "flops_per_call": None,
            "bytes_per_call": None,
            "flops_total": 0.0,
            # warm-call slice of flops_total: the numerator matching
            # exec_s (which excludes compiling calls' wall) — the
            # program-table MFU divides these two, never
            # flops_total/exec_s (inflated by N/(N-1) at low call
            # counts)
            "flops_exec": 0.0,
            "argument_bytes": None,
            "output_bytes": None,
            "temp_bytes": None,
            "generated_code_bytes": None,
            "hbm_peak_bytes": None,
        }
    return e


def programs_snapshot() -> list[dict]:
    """Registry rows (copies), most FLOPs-total first. Rows of
    plan-built programs carry their ``plan`` (owning plan group) and
    ``ladder_rung`` attribution (ISSUE 15) so a surprise recompile
    names the ladder that minted it."""
    with _lock:
        rows = [{k: v for k, v in e.items() if not k.startswith("_")}
                for e in _programs.values()]
    try:
        from ..plans.plan import annotate_programs

        annotate_programs(rows)
    except Exception:  # pragma: no cover - attribution never breaks it
        pass
    rows.sort(key=lambda e: -(e["flops_total"] or 0.0))
    return rows


def programs_reset() -> None:
    with _lock:
        _programs.clear()


def unwrap(fn):
    """Innermost callable under any stack of trackers/jits — the raw
    Python body super-block reducers lift into their scans."""
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    return fn


def _abstractify(x):
    """Concrete leaf -> ShapeDtypeStruct so the analysis lowering never
    touches buffers (tracked programs donate their carries — the data is
    gone by the time the post-call analysis runs; shape/dtype/sharding
    metadata survives deletion). The sharding rides along where the leaf
    has one: without it, an SPMD program would be re-lowered as the
    unsharded replicated specialization, misreporting per-device HBM
    (~n_devices too high) and timing a compile the workload never ran."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        import jax

        try:
            sharding = getattr(x, "sharding", None)
            # MULTI-device shardings only: a single-device sharding on
            # an uncommitted leaf (solver carries, host-built scalars)
            # would be treated as committed by the lowering and clash
            # with the data's mesh ("incompatible devices"); the real
            # call left those leaves free to be placed, so the analysis
            # must too
            if sharding is not None and len(sharding.device_set) <= 1:
                sharding = None
        except Exception:
            sharding = None
        if sharding is not None:
            try:
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
            except Exception:
                pass  # exotic sharding object: fall back unsharded
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def _shape_key(args, kwargs):
    """Hashable signature of one call's argument shapes/dtypes (array
    metadata survives donation). None when any leaf is unhashable."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    try:
        return hash(tuple(
            (tuple(x.shape), str(x.dtype))
            if hasattr(x, "shape") and hasattr(x, "dtype") else x
            for x in leaves
        ))
    except TypeError:
        return None


def _cost_dict(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax: one dict per program
        ca = ca[0] if ca else {}
    return ca or {}


def _analyze(name: str, fn, args, kwargs, skey=None, by_shape=None) -> None:
    """AOT-lower the program at the shapes just called and record XLA's
    cost/memory analysis + the measured compile time. Never raises —
    attribution must not kill the fit it observes."""
    import jax

    try:
        abs_args = jax.tree.map(_abstractify, args)
        abs_kwargs = jax.tree.map(_abstractify, kwargs)
        t0 = time.perf_counter()
        compiled = fn.lower(*abs_args, **abs_kwargs).compile()
        compile_s = time.perf_counter() - t0
        cost = _cost_dict(compiled)
        mem = compiled.memory_analysis()
        flops = cost.get("flops")
        nbytes = cost.get("bytes accessed")
        arg_b = getattr(mem, "argument_size_in_bytes", None)
        out_b = getattr(mem, "output_size_in_bytes", None)
        tmp_b = getattr(mem, "temp_size_in_bytes", None)
        code_b = getattr(mem, "generated_code_size_in_bytes", None)
    except Exception:
        with _lock:
            e = _entry(name)
            e["compiles"] += 1
        return
    with _lock:
        e = _entry(name)
        e["compiles"] += 1
        e["compile_s"] += compile_s
        if flops is not None:
            e["flops_per_call"] = float(flops)
            if skey is not None and by_shape is not None:
                by_shape[skey] = float(flops)
        if nbytes is not None:
            e["bytes_per_call"] = float(nbytes)
        for key, v in (("argument_bytes", arg_b), ("output_bytes", out_b),
                       ("temp_bytes", tmp_b),
                       ("generated_code_bytes", code_b)):
            if v is not None:
                e[key] = int(v)
        known = [v for v in (arg_b, out_b, tmp_b) if v is not None]
        if known:
            e["hbm_peak_bytes"] = int(sum(known))


def track_program(name: str):
    """Decorator registering a jitted callable in the program registry.

    Stacks OUTSIDE ``jax.jit`` (``track_program(n)(jax.jit(f))``); the
    wrapper never enters traced code. ``__wrapped__`` is pinned to the
    innermost raw function so existing ``.__wrapped__`` unwraps (the
    super-block reducers lift block-kernel bodies into scans) keep
    working; the jitted callable stays reachable as ``__wrapped_jit__``.
    """

    def deco(fn):
        cache_size = getattr(fn, "_cache_size", None)
        # per-specialization cost, PER WRAPPED CALLABLE: one program
        # name may cover several distinct jits (lru-cached reducer
        # flavors, multiple fitted estimators of one class) — a shared
        # per-name map would let one variant's analysis overwrite
        # another's at the same shapes and credit the wrong kernel
        by_shape: dict = {}

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if not programs_enabled():
                return fn(*args, **kwargs)
            before = None
            if cache_size is not None:
                try:
                    before = cache_size()
                except Exception:
                    before = None
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            skey = _shape_key(args, kwargs)
            grew = False
            if before is not None:
                try:
                    grew = cache_size() > before
                except Exception:
                    grew = False
                if grew:
                    _analyze(name, fn, args, kwargs, skey=skey,
                             by_shape=by_shape)
            with _lock:
                e = _entry(name)
                e["calls"] += 1
                # a compiling call's wall is trace+compile, not
                # execution (and compile_s already records it) — only
                # warm calls accumulate exec_s
                if not grew:
                    e["exec_s"] += dt
                # credit THIS call's specialization; one program name
                # spans many shapes (serving bucket grid). A shape whose
                # analysis failed credits NOTHING — borrowing another
                # shape's cost would silently skew flops_total and every
                # MFU built on it. (skey None = unhashable leaves: the
                # latest analysis is the only estimate available.)
                flops = by_shape.get(skey) if skey is not None \
                    else e["flops_per_call"]
                if flops:
                    e["flops_total"] += flops
                    if not grew:
                        e["flops_exec"] += flops
            if flops and counters_enabled():
                counter_add("program_flops", flops)
            return out

        # preserve the raw-body unwrap call sites rely on, and keep the
        # jit object reachable for AOT/introspection
        wrapped.__wrapped__ = unwrap(fn)
        wrapped.__wrapped_jit__ = fn
        if cache_size is not None:
            wrapped._cache_size = cache_size
        wrapped.program_name = name
        return wrapped

    return deco


def log_programs(logger, peak=True, **extra) -> list[dict]:
    """Emit one JSONL record holding the program registry snapshot (plus
    the resolved peak-FLOPs table when ``peak``, so an offline report can
    compute MFU); returns the snapshot. The report CLI reads the LAST
    such record as the run's programs table."""
    snap = programs_snapshot()
    if logger is None:
        return snap
    rec = {"programs": snap}
    # the plans table rides the same record (ISSUE 15): which plan /
    # ladder rung minted each warmed specialization
    try:
        from ..plans import plans_snapshot

        plrows = plans_snapshot()
    except Exception:
        plrows = None
    if plrows:
        rec["plans"] = plrows
    if peak:
        try:
            import jax

            from ._peak import resolve_peak

            pk = resolve_peak()
            rec.update(
                peak_flop_per_s_per_chip=pk["flops"],
                peak_source=pk["source"],
                device_kind=pk["device_kind"],
                n_chips=len(jax.local_devices()),
            )
        except Exception:
            pass  # no peak: the report skips MFU columns
    logger.log(**rec, **extra)
    return snap
