"""dask_ml_tpu — a TPU-native distributed ML framework with the
capabilities of dask-ml (see SURVEY.md for the blueprint).

Infrastructure layers:
- ``parallel/`` — mesh/sharding substrate (ShardedArray, streaming,
  multi-host runtime)
- ``ops/``      — masked reductions, distributed linalg (TSQR /
  randomized SVD), pairwise kernels, Pallas fused kernels
- ``models/``   — estimator implementations + GLM solver library
- ``io/``       — native (C++) block loaders
- ``observability/`` — JSONL metrics, span tracing, runtime counters,
  run-report CLI (``python -m dask_ml_tpu.observability.report``)
- ``plans/``    — the one execution plane for compiled programs: shape
  ladders, ProgramPlan build path (cache/track/donate/compile-cache),
  process-wide warmup registry
- ``serving/``  — online inference: ModelServer micro-batching over a
  shape-bucket ladder with admission control and warmup
- ``utils/``    — validation, checkpointing, testing

sklearn/dask-ml-parity namespaces (import as ``dask_ml_tpu.<name>``):
``cluster``, ``compose``, ``datasets``, ``decomposition``, ``ensemble``,
``feature_extraction``, ``impute``, ``linear_model``, ``metrics``,
``model_selection``, ``naive_bayes``, ``preprocessing``, ``wrappers``,
``xgboost``.
"""

__version__ = "0.1.0"

__all__ = [
    "cluster", "compose", "config", "datasets", "decomposition",
    "ensemble", "feature_extraction", "impute", "linear_model", "metrics",
    "model_selection", "naive_bayes", "observability", "ops", "parallel",
    "plans", "preprocessing", "serving", "utils", "wrappers", "xgboost",
    "__version__",
]
