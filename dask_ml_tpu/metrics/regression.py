"""Regression metrics. Reference: ``dask_ml/metrics/regression.py``
(SURVEY.md §2a Metrics row)."""

from __future__ import annotations

import jax.numpy as jnp

from .classification import _canon


def mean_squared_error(y_true, y_pred, sample_weight=None, squared=True):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    mse = jnp.sum(((t - p) ** 2) * w) / jnp.sum(w)
    return float(mse if squared else jnp.sqrt(mse))


def mean_absolute_error(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    return float(jnp.sum(jnp.abs(t - p) * w) / jnp.sum(w))


def r2_score(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    wsum = jnp.sum(w)
    mean = jnp.sum(t * w) / wsum
    ss_res = jnp.sum(((t - p) ** 2) * w)
    ss_tot = jnp.sum(((t - mean) ** 2) * w)
    return float(1.0 - ss_res / ss_tot)


def mean_squared_log_error(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    err = (jnp.log1p(t) - jnp.log1p(p)) ** 2
    return float(jnp.sum(err * w) / jnp.sum(w))
