"""GridSearchCV/RandomizedSearchCV tests (ref:
tests/model_selection/test_search.py — the reference ports sklearn's
search-test suite; parity with sklearn's GridSearchCV is the oracle)."""

import numpy as np
import pytest
import sklearn.model_selection as skms
from scipy.stats import uniform
from sklearn.pipeline import Pipeline

from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.model_selection import GridSearchCV, RandomizedSearchCV
from dask_ml_tpu.preprocessing import StandardScaler


@pytest.fixture(scope="module")
def data():
    return make_classification(n_samples=300, n_features=8, random_state=0)


def test_grid_search_matches_sklearn(data):
    X, y = data
    Xh, yh = X.to_numpy(), y.to_numpy()
    grid = {"C": [0.01, 1.0, 100.0]}
    ours = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=300), grid, cv=3
    ).fit(X, y)
    from sklearn.linear_model import LogisticRegression as SkLR

    ref = skms.GridSearchCV(SkLR(max_iter=1000), grid, cv=3).fit(Xh, yh)
    # near-tie grids can pick different winners; score parity is the oracle
    np.testing.assert_allclose(
        ours.cv_results_["mean_test_score"],
        ref.cv_results_["mean_test_score"], atol=0.05,
    )
    assert ours.best_score_ == pytest.approx(ref.best_score_, abs=0.05)


def test_grid_search_cv_results_structure(data):
    X, y = data
    grid = {"C": [0.1, 1.0], "solver": ["lbfgs", "newton"]}
    search = GridSearchCV(
        LogisticRegression(max_iter=200), grid, cv=2,
        return_train_score=True,
    ).fit(X, y)
    r = search.cv_results_
    assert len(r["params"]) == 4
    for key in ("mean_test_score", "std_test_score", "rank_test_score",
                "split0_test_score", "split1_test_score",
                "mean_train_score", "param_C", "param_solver"):
        assert key in r, key
    assert r["rank_test_score"].min() == 1
    assert search.best_index_ == np.argmax(r["mean_test_score"])


def test_grid_search_refit_predict(data):
    X, y = data
    search = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=200), {"C": [1.0, 10.0]},
        cv=2,
    ).fit(X, y)
    assert hasattr(search, "best_estimator_")
    pred = search.predict(X)
    assert search.score(X, y) > 0.7
    np.testing.assert_array_equal(search.classes_, [0.0, 1.0])


def test_grid_search_no_refit(data):
    X, y = data
    search = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=100), {"C": [1.0]},
        cv=2, refit=False,
    ).fit(X, y)
    with pytest.raises(AttributeError, match="refit"):
        search.predict(X)


def test_grid_search_pipeline_prefix_sharing(data):
    X, y = data
    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("clf", LogisticRegression(solver="lbfgs", max_iter=200)),
    ])
    # a pure clf__C grid takes the stacked fast path: the prefix is fit
    # exactly ONCE per fold (2 misses, zero hits needed) and all
    # candidates solve in one program
    search = GridSearchCV(pipe, {"clf__C": [0.1, 1.0, 10.0]}, cv=2)
    search.fit(X, y)
    assert search._c_grid_vmapped_ == 3
    hits, misses = search._memo_stats
    assert misses == 2 and hits == 0, (hits, misses)
    assert search.best_score_ > 0.7
    # a mixed grid takes the general path: scaler fit once per fold
    # (2 misses) then shared across the other 2 candidates x 2 folds
    # = 4 hits; classifiers never shared
    search = GridSearchCV(
        pipe, {"clf__C": [0.1, 1.0, 10.0],
               "clf__intercept_scaling": [1.0]}, cv=2,
    ).fit(X, y)
    assert not hasattr(search, "_c_grid_vmapped_")
    hits, misses = search._memo_stats
    assert hits == 4, (hits, misses)
    assert search.best_score_ > 0.7


def test_randomized_search(data):
    X, y = data
    search = RandomizedSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=200),
        {"C": uniform(0.1, 10)}, n_iter=4, random_state=0, cv=2,
    ).fit(X, y)
    assert len(search.cv_results_["params"]) == 4
    assert 0.5 < search.best_score_ <= 1.0


def test_search_error_score(data):
    X, y = data
    grid = {"C": [1.0, -5.0]}  # negative C: admm local solve still runs;
    # use penalty that errors instead
    search = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=50),
        {"penalty": ["l2", "bogus"]}, cv=2, error_score=-1.0, refit=False,
    ).fit(X, y)
    assert (search.cv_results_["mean_test_score"] == -1.0).sum() == 1

    with pytest.raises(ValueError):
        GridSearchCV(
            LogisticRegression(solver="lbfgs"),
            {"penalty": ["bogus"]}, cv=2, refit=False,
        ).fit(X, y)


def test_multimetric_grid_search_matches_sklearn(xy_classification):
    """Multimetric scoring (ex dask-searchcv parity): list/dict scoring
    produce per-metric cv_results_ columns; refit names the selection
    metric."""
    from sklearn.linear_model import LogisticRegression as SkLR
    from sklearn.model_selection import GridSearchCV as SkGrid
    from sklearn.model_selection import KFold as SkKFold

    from dask_ml_tpu.model_selection import GridSearchCV

    X, y = xy_classification
    grid = {"C": [0.1, 1.0, 10.0]}
    ours = GridSearchCV(
        SkLR(max_iter=200), grid, cv=3,
        scoring=["accuracy", "neg_log_loss"], refit="accuracy",
        scheduler="synchronous",
    ).fit(X, y)
    ref = SkGrid(
        SkLR(max_iter=200), grid, cv=SkKFold(3),
        scoring=["accuracy", "neg_log_loss"], refit="accuracy",
    ).fit(X, y)
    assert ours.multimetric_ is True
    for key in ("mean_test_accuracy", "mean_test_neg_log_loss",
                "rank_test_accuracy"):
        np.testing.assert_allclose(
            ours.cv_results_[key], ref.cv_results_[key], rtol=5e-3,
            atol=1e-4,
        )
    assert ours.best_params_ == ref.best_params_
    assert ours.best_estimator_.score(X, y) > 0.7
    # score() uses the refit metric's scorer
    assert 0.0 <= ours.score(X, y) <= 1.0


def test_multimetric_refit_validation(xy_classification):
    from sklearn.linear_model import LogisticRegression as SkLR

    from dask_ml_tpu.model_selection import GridSearchCV

    X, y = xy_classification
    with pytest.raises(ValueError, match="refit to name"):
        GridSearchCV(SkLR(), {"C": [1.0]},
                     scoring=["accuracy", "neg_log_loss"]).fit(X, y)
    # refit=False: results only, no best_* attributes
    s = GridSearchCV(
        SkLR(max_iter=100), {"C": [0.1, 1.0]}, cv=3, refit=False,
        scoring={"acc": "accuracy"}, scheduler="synchronous",
    ).fit(X, y)
    assert "mean_test_acc" in s.cv_results_
    assert not hasattr(s, "best_index_")


@pytest.mark.slow
def test_grid_search_list_of_grids(data):
    """param_grid as a LIST of grids: candidates are the union, and
    params absent from a sub-grid are masked in cv_results_ (sklearn and
    the reference's contract)."""
    X, y = data
    s = GridSearchCV(
        LogisticRegression(max_iter=30),
        [{"C": [0.1, 1.0]}, {"solver": ["newton"], "C": [1.0]}],
        cv=2,
    ).fit(X, y)
    r = s.cv_results_
    assert len(r["params"]) == 3
    col = r["param_solver"]
    assert np.ma.is_masked(col[0]) and np.ma.is_masked(col[1])
    assert col[2] == "newton"
    assert s.best_index_ == int(np.argmax(r["mean_test_score"]))


def test_randomized_search_reproducible(data):
    X, y = data
    from scipy.stats import loguniform

    dists = {"C": loguniform(1e-3, 1e2)}
    a = RandomizedSearchCV(LogisticRegression(max_iter=30), dists,
                           n_iter=4, random_state=5, cv=2).fit(X, y)
    b = RandomizedSearchCV(LogisticRegression(max_iter=30), dists,
                           n_iter=4, random_state=5, cv=2).fit(X, y)
    assert [p["C"] for p in a.cv_results_["params"]] == \
        [p["C"] for p in b.cv_results_["params"]]
    np.testing.assert_allclose(a.cv_results_["mean_test_score"],
                               b.cv_results_["mean_test_score"], rtol=1e-6)


def test_search_with_scorer_string(data):
    X, y = data
    s = GridSearchCV(LogisticRegression(max_iter=30), {"C": [0.5, 2.0]},
                     cv=2, scoring="neg_log_loss").fit(X, y)
    assert (s.cv_results_["mean_test_score"] <= 0).all()
    assert s.best_score_ == s.cv_results_["mean_test_score"].max()


def test_search_with_custom_make_scorer(data):
    """sklearn make_scorer objects plug straight in (the reference's
    check_scoring passes them through)."""
    from sklearn.metrics import f1_score, make_scorer

    X, y = data
    s = GridSearchCV(
        LogisticRegression(max_iter=30), {"C": [0.5, 2.0]}, cv=2,
        scoring=make_scorer(f1_score),
    ).fit(X, y)
    assert 0.0 <= s.best_score_ <= 1.0
    assert len(s.cv_results_["mean_test_score"]) == 2


@pytest.mark.slow
def test_search_accepts_cv_splitter_objects(data):
    """cv may be an int or any splitter instance (KFold/ShuffleSplit),
    as in the reference."""
    from dask_ml_tpu.model_selection import KFold, ShuffleSplit

    X, y = data
    for cv, n_splits in ((KFold(n_splits=3, shuffle=True, random_state=0), 3),
                         (ShuffleSplit(n_splits=2, test_size=0.3,
                                       random_state=0), 2)):
        s = GridSearchCV(LogisticRegression(max_iter=20),
                         {"C": [1.0]}, cv=cv).fit(X, y)
        split_cols = [k for k in s.cv_results_
                      if k.startswith("split") and k.endswith("test_score")]
        assert len(split_cols) == n_splits


def test_multimetric_custom_callable_on_sharded(data):
    from sklearn.metrics import f1_score, make_scorer

    X, y = data  # sharded fixture
    s = GridSearchCV(
        LogisticRegression(max_iter=25), {"C": [0.5, 2.0]}, cv=2,
        scoring={"f1": make_scorer(f1_score), "acc": "accuracy"},
        refit="f1",
    ).fit(X, y)
    assert "mean_test_f1" in s.cv_results_
    assert "mean_test_acc" in s.cv_results_
    assert 0.5 < s.best_score_ <= 1.0


class TestCGridFastPath:
    """Homogeneous C-grid fast path: every candidate solved in ONE
    compiled stacked-lam program per fold (SURVEY.md §3.4)."""

    def _data(self):
        from dask_ml_tpu.datasets import make_classification

        return make_classification(n_samples=4000, n_features=10,
                                   random_state=0)

    def test_matches_general_path_and_sklearn_selection(self):
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV

        X, y = self._data()
        grid = {"C": [0.01, 0.1, 1.0, 10.0]}
        fast = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=80), grid, cv=3
        ).fit(X, y)
        assert fast._c_grid_vmapped_ == 4
        # general path: an extra constant key defeats the key-set gate
        slow = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=80),
            {"C": grid["C"], "intercept_scaling": [1.0]}, cv=3,
        ).fit(X, y)
        assert not hasattr(slow, "_c_grid_vmapped_")
        np.testing.assert_allclose(
            fast.cv_results_["mean_test_score"],
            slow.cv_results_["mean_test_score"], atol=2e-3,
        )
        assert fast.best_params_["C"] == slow.best_params_["C"]
        np.testing.assert_allclose(
            fast.best_estimator_.coef_, slow.best_estimator_.coef_,
            atol=1e-3,
        )

    def test_fallback_cases_still_fit(self):
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV

        X, y = self._data()
        grid = {"C": [0.1, 1.0]}
        # non-lbfgs solver and l1 penalty take the general path and
        # still produce a fitted search; multiclass (below) takes the
        # stacked k*C arm of the fast path
        for est in (
            LogisticRegression(solver="admm", max_iter=20),
            LogisticRegression(solver="proximal_grad", penalty="l1",
                               max_iter=20),
        ):
            s = GridSearchCV(est, grid, cv=2).fit(X, y)
            assert not hasattr(s, "_c_grid_vmapped_")
            assert np.isfinite(s.best_score_)
        from dask_ml_tpu.datasets import make_classification

        Xm, ym = make_classification(n_samples=3000, n_features=8,
                                     n_classes=3, n_informative=6,
                                     random_state=1)
        s = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=40), grid, cv=2
        ).fit(Xm, ym)
        # multiclass takes the stacked k*C arm of the fast path
        assert s._c_grid_vmapped_ == 2
        assert s.best_estimator_.coef_.shape == (3, 8)

    def test_multiclass_grid_matches_general_path(self):
        from dask_ml_tpu.datasets import make_classification
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV

        Xm, ym = make_classification(n_samples=4000, n_features=10,
                                     n_classes=4, n_informative=8,
                                     random_state=1)
        grid = {"C": [0.01, 0.1, 1.0]}
        fast = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=60), grid, cv=2
        ).fit(Xm, ym)
        assert fast._c_grid_vmapped_ == 3
        slow = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=60),
            {"C": grid["C"], "intercept_scaling": [1.0]}, cv=2,
        ).fit(Xm, ym)
        np.testing.assert_allclose(
            fast.cv_results_["mean_test_score"],
            slow.cv_results_["mean_test_score"], atol=3e-3,
        )
        # near-tied scores may flip the argmax between paths; the model
        # quality must match regardless
        assert abs(fast.best_score_ - slow.best_score_) < 3e-3
        ref = LogisticRegression(solver="lbfgs", max_iter=60,
                                 C=fast.best_params_["C"]).fit(Xm, ym)
        np.testing.assert_allclose(fast.best_estimator_.coef_, ref.coef_,
                                   atol=2e-3)
        p = np.asarray(fast.predict_proba(Xm))
        np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)

    def test_regression_families(self):
        from dask_ml_tpu.datasets import make_counts, make_regression
        from dask_ml_tpu.linear_model import (LinearRegression,
                                              PoissonRegression)
        from dask_ml_tpu.model_selection import GridSearchCV

        Xr, yr = make_regression(n_samples=3000, n_features=8,
                                 random_state=0)
        s = GridSearchCV(LinearRegression(solver="lbfgs", max_iter=60),
                         {"C": [0.1, 1.0, 10.0]}, cv=2).fit(Xr, yr)
        assert s._c_grid_vmapped_ == 3 and np.isfinite(s.best_score_)
        Xc, yc = make_counts(n_samples=3000, n_features=6, random_state=0)
        s2 = GridSearchCV(PoissonRegression(solver="lbfgs", max_iter=60),
                          {"C": [0.1, 1.0]}, cv=2).fit(Xc, yc)
        assert s2._c_grid_vmapped_ == 2 and np.isfinite(s2.best_score_)

    def test_randomized_search_C_distribution_takes_fast_path(self):
        import scipy.stats as ss

        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import RandomizedSearchCV

        X, y = self._data()
        s = RandomizedSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=40),
            {"C": ss.expon(scale=1.0)}, n_iter=5, cv=2, random_state=0,
        ).fit(X, y)
        assert s._c_grid_vmapped_ == 5
        assert len({p["C"] for p in s.cv_results_["params"]}) == 5
        assert np.isfinite(s.best_score_)

    def test_pipeline_last_step_C_grid(self):
        from sklearn.pipeline import Pipeline

        from dask_ml_tpu.datasets import make_classification
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV
        from dask_ml_tpu.preprocessing import StandardScaler

        X, y = make_classification(n_samples=4000, n_features=10,
                                   random_state=0)
        pipe = Pipeline([
            ("scale", StandardScaler()),
            ("clf", LogisticRegression(solver="lbfgs", max_iter=60)),
        ])
        grid = {"clf__C": [0.01, 0.1, 1.0]}
        fast = GridSearchCV(pipe, grid, cv=2).fit(X, y)
        assert fast._c_grid_vmapped_ == 3
        slow = GridSearchCV(
            pipe, {"clf__C": grid["clf__C"],
                   "clf__intercept_scaling": [1.0]}, cv=2,
        ).fit(X, y)
        np.testing.assert_allclose(
            fast.cv_results_["mean_test_score"],
            slow.cv_results_["mean_test_score"], atol=3e-3,
        )
        assert abs(fast.best_score_ - slow.best_score_) < 3e-3
        # refit pipeline scores on RAW inputs (prefix re-applied)
        assert fast.best_estimator_.score(X, y) > 0.9
        # multiclass flows through the pipeline arm too
        Xm, ym = make_classification(n_samples=3000, n_features=8,
                                     n_classes=3, n_informative=6,
                                     random_state=2)
        fm = GridSearchCV(pipe, {"clf__C": [0.1, 1.0]}, cv=2).fit(Xm, ym)
        assert fm._c_grid_vmapped_ == 2
        assert fm.best_estimator_.named_steps["clf"].coef_.shape == (3, 8)


class TestCGridSharedBudgetDiagnostics:
    """The stacked C-grid solve shares one iteration budget across
    candidates (ADVICE r5); each fitted clone must still publish its OWN
    per-candidate convergence point as n_iter_, with the full vector in
    solver_info_."""

    def test_per_candidate_n_iter(self):
        from dask_ml_tpu.datasets import make_classification
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_classification(n_samples=3000, n_features=10,
                                   n_informative=6, random_state=0)
        Cs = [0.001, 0.1, 10.0]
        models = LogisticRegression(
            solver="lbfgs", max_iter=100, tol=1e-6
        )._fit_C_grid(X, y, Cs)
        assert models is not None
        per_cand = models[0].solver_info_["n_iter_per_candidate"]
        assert len(per_cand) == len(Cs)
        for m, expect in zip(models, per_cand):
            assert m.n_iter_ == expect
        # the joint budget is the slowest candidate's count: at least
        # one clone hits it, and none exceeds it
        budget = max(per_cand)
        assert all(1 <= it <= budget for it in per_cand)

    def test_multiclass_per_candidate_n_iter(self):
        from dask_ml_tpu.datasets import make_classification
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.parallel.sharded import as_sharded  # noqa: F401

        X, y = make_classification(n_samples=3000, n_features=8,
                                   n_classes=3, n_informative=6,
                                   random_state=1)
        Cs = [0.01, 1.0]
        models = LogisticRegression(
            solver="lbfgs", max_iter=80
        )._fit_C_grid(X, y, Cs)
        assert models is not None
        info = models[0].solver_info_
        per_cand = info["n_iter_per_candidate"]
        blocks = np.asarray(info["n_iter_per_block"])
        assert blocks.shape == (len(Cs), 3)  # (k candidates, C classes)
        # a candidate's n_iter is its slowest class's convergence point
        np.testing.assert_array_equal(blocks.max(axis=1), per_cand)
        for m, expect in zip(models, per_cand):
            assert m.n_iter_ == expect

    def test_stacked_multiclass_fit_reports_per_class(self):
        from dask_ml_tpu.datasets import make_classification
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_classification(n_samples=3000, n_features=8,
                                   n_classes=3, n_informative=6,
                                   random_state=2)
        clf = LogisticRegression(solver="lbfgs", max_iter=80).fit(X, y)
        per_class = clf.solver_info_["n_iter_per_class"]
        assert len(per_class) == 3
        assert clf.n_iter_ == max(per_class) == clf.solver_info_["n_iter"]
