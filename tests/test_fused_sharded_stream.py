"""ISSUE 12: the fused Pallas streamed kernels composed with the
data-parallel shard_map scan programs, plus the gradient-accumulation
cross-host streamed SGD flavor.

Contracts under test, per the tentpole:

- fused x sharded parity: with ``pallas_stream_interpret`` on (the CPU
  CI stand-in for a real TPU's compiled kernels), the shard_map scan
  programs trace the FUSED bodies — program-registry names
  ``pallas.*.psum`` — and GLM/SGD/KMeans streamed fits match the
  unfused sharded flavor to 1e-6 at mesh {1, 2, 8}, ragged per-shard
  tails included;
- tile selection reasons about the PER-SHARD slab height (S/D rows),
  not the global block: a block that divides into non-128-multiple
  slabs refuses with reason "non-128-mult shard rows" instead of
  mistracing;
- the shuffled SGD fit keeps its lr-clock identity (same ``_t``, same
  weights) across the fused/unfused flavors;
- ``fused_stream_reason`` lands in solver_info_ naming why fused was
  gated off — and is None exactly when the kernels engaged;
- ``stream_grad_accum``: exact (bit-level) parity with the sequential
  single-host fit at A=1, documented-tolerance convergence at A in
  {2, 4}, and the virtual-2-process flavor bit-matching the
  single-process A*P fit over the interleaved blocks;
- the sharded streamed-ADMM dispatch is tracked under its
  ``...admm_local.gspmd`` program name with the reduce-volume estimate
  on the ``gspmd_reduce_bytes`` counter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.parallel.streaming import BlockStream

MESHES = (1, 2, 8)


def _mk_xy(n=2300, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) > 0).astype(np.float32)
    return X, y


def _objective(stream, n, d, **kw):
    from dask_ml_tpu.models.solvers.streamed import StreamedObjective

    return StreamedObjective(
        stream, n, jnp.asarray(0.1, jnp.float32), jnp.ones(d + 1),
        0.5, "logistic", "l2", True, **kw,
    )


class TestFusedShardedGLM:
    @pytest.mark.parametrize("sm", MESHES)
    def test_objective_parity_vs_unfused_sharded(self, sm):
        """1024-row blocks divide into 128-multiple slabs at every mesh
        width; n=2300 leaves a ragged tail block whose trailing shards
        are all-padding."""
        n, d = 2300, 6
        X, y = _mk_xy(n, d)
        beta = np.random.RandomState(3).randn(d + 1)
        out = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024, stream_mesh=sm,
                            pallas_stream_interpret=interp):
                o = _objective(BlockStream((X, y), block_rows=1024), n, d)
                mxu, fused, _, reason = o._sb_flavor("vg")
                assert fused is interp, (fused, reason)
                assert (reason is None) is interp
                v, g = o.value_and_grad(beta)
                v2, g2, h = o.value_and_grad_and_hess(beta)
                out[interp] = (v, g, v2, g2, h, o.value(beta))
        for a, b in zip(out[True], out[False]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_sharded_reducer_is_tracked_as_pallas_psum(self):
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        mesh = stream_data_mesh()
        assert mesh.devices.size == 8
        fused = _sb_reducer("vg", "logistic", True, 0, fused=True,
                            interpret=True, mesh=mesh)
        assert fused.program_name == "pallas.glm_vg.psum"
        plain = _sb_reducer("vg", "logistic", True, 0, mesh=mesh)
        assert plain.program_name == "superblock.glm.vg.psum"
        multi = _sb_reducer("vg", "logistic", True, 3, fused=True,
                            interpret=True, mesh=mesh)
        assert multi.program_name == "pallas.glm_vg_multi.psum"

    def test_fused_fit_records_engagement_and_matches(self):
        n, d = 2300, 6
        X, y = _mk_xy(n, d)
        from dask_ml_tpu.linear_model import LogisticRegression

        fits = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024,
                            pallas_stream_interpret=interp):
                fits[interp] = LogisticRegression(
                    solver="lbfgs", max_iter=15
                ).fit(X.astype(np.float64), y.astype(np.float64))
        info = fits[True].solver_info_
        assert info["fused_stream"] is True
        assert info["fused_stream_reason"] is None
        assert info["stream_shards"] == 8
        assert fits[False].solver_info_["fused_stream"] is False
        assert fits[False].solver_info_["fused_stream_reason"] == "off-TPU"
        # per-PASS parity is 1e-6 (the objective test above); a full
        # 15-iteration solve accumulates it — compare relatively
        np.testing.assert_allclose(fits[True].coef_, fits[False].coef_,
                                   rtol=1e-6, atol=1e-6)

    def test_multiclass_objective_parity(self):
        from dask_ml_tpu.models.solvers.streamed import (
            MulticlassStreamedObjective,
        )

        n, d, C = 2300, 5, 3
        X, _ = _mk_xy(n, d)
        y = np.random.RandomState(5).randint(0, C, n).astype(np.float32)
        beta = np.random.RandomState(6).randn(C * (d + 1))
        out = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024, stream_mesh=8,
                            pallas_stream_interpret=interp):
                o = MulticlassStreamedObjective(
                    BlockStream((X, y), block_rows=1024), n,
                    jnp.asarray(0.1, jnp.float32),
                    jnp.ones(C * (d + 1)), 0.5, "logistic", "l2", True,
                    n_classes=C,
                )
                _, fused, _, reason = o._sb_flavor("vg")
                assert fused is interp, reason
                # the per-class Hessian stack stays XLA, with a reason
                assert o._sb_flavor("vgh")[3] == "multiclass-hessian-xla"
                out[interp] = o.value_and_grad(beta)
        np.testing.assert_allclose(out[True][0], out[False][0], rtol=1e-6)
        np.testing.assert_allclose(out[True][1], out[False][1],
                                   atol=1e-6, rtol=1e-6)

    def test_non_128_multiple_shard_slab_refuses_with_reason(self):
        """A 96-row block divides into 12-row slabs at D=8 — the fused
        flavor must refuse on the PER-SHARD height with the documented
        reason, never mistrace."""
        n, d = 1100, 6
        X, y = _mk_xy(n, d)
        with config.set(stream_block_rows=96,
                        pallas_stream_interpret=True):
            o = _objective(BlockStream((X, y), block_rows=96), n, d)
            mxu, fused, _, reason = o._sb_flavor("vg")
        assert fused is False and reason == "non-128-mult shard rows"


class TestFusedShardedSGD:
    @pytest.mark.parametrize("sm", MESHES)
    def test_shuffled_fit_parity_and_lr_clock_identity(self, sm):
        from dask_ml_tpu.models.sgd import SGDClassifier

        n, d = 8192, 8
        X, y = _mk_xy(n, d, seed=1)
        res = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024, stream_mesh=sm,
                            pallas_stream_interpret=interp):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=True).fit(X, y)
                res[interp] = (m.coef_.copy(), m.intercept_.copy(),
                               m._t, m.solver_info_)
        assert res[True][2] == res[False][2]        # identical lr clock
        assert res[True][3]["fused_stream"] is True
        assert res[True][3]["fused_stream_reason"] is None
        assert res[False][3]["fused_stream"] is False
        np.testing.assert_allclose(res[True][0], res[False][0], atol=1e-6)
        np.testing.assert_allclose(res[True][1], res[False][1], atol=1e-6)

    def test_sharded_scan_tracked_as_pallas_psum(self):
        from dask_ml_tpu.models.sgd import _sgd_sb_scan_sharded
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        mesh = stream_data_mesh()
        fused = _sgd_sb_scan_sharded(mesh, "log_loss", None, None,
                                     fused=True, interpret=True)
        assert fused.program_name == "pallas.sgd_step.psum"
        plain = _sgd_sb_scan_sharded(mesh, "log_loss", None, None)
        assert plain.program_name == "superblock.sgd_scan.psum"

    def test_multiclass_fused_parity(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        n = 8192
        X, _ = _mk_xy(n, 8, seed=2)
        y = np.random.RandomState(5).randint(0, 3, n).astype(float)
        res = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024, stream_mesh=8,
                            pallas_stream_interpret=interp):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=False, penalty="elasticnet",
                                  l1_ratio=0.4).fit(X, y)
                res[interp] = (m.coef_.copy(), m.solver_info_)
        assert res[True][1]["fused_stream"] is True
        np.testing.assert_allclose(res[True][0], res[False][0], atol=1e-6)

    def test_dispatch_shape_and_zero_recompiles_after_pass1(self):
        """The fused flavor must not change the dispatch shape — one
        scan dispatch per super-block, NOT per shard — nor mint XLA
        compiles after the first pass."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        n = 8192
        X, y = _mk_xy(n, 8, seed=3)
        with config.set(stream_block_rows=1024,
                        pallas_stream_interpret=True):
            SGDClassifier(max_iter=1, random_state=0,
                          shuffle=False).fit(X, y)  # pass 1 compiles
            obs.counters_reset()
            m = SGDClassifier(max_iter=3, random_state=0,
                              shuffle=False).fit(X, y)
        st = dict(m._last_stream_stats or {})
        assert st["sb_shards"] == 8
        assert st["dispatches_per_pass"] == \
            -(-st["n_blocks"] // st["superblock_k"])
        snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0, snap
        assert m.solver_info_["fused_stream"] is True

    def test_cohort_scan_fused_matches_xla(self):
        from dask_ml_tpu.models.sgd import (_sgd_cohort_scan,
                                            _sgd_cohort_scan_pallas)

        rng = np.random.RandomState(7)
        B, bs, d, N, S = 3, 256, 8, 4, 5
        Xr = jnp.asarray(rng.randn(B, bs, d).astype(np.float32))
        yr = jnp.asarray((rng.rand(B, bs) > 0.5).astype(np.float32))
        NV = jnp.asarray([bs, bs - 40, bs], jnp.int32)
        order = jnp.asarray(np.array([0, 1, 2, 0, 1], np.int32))
        W = jnp.asarray(rng.randn(N, d + 1).astype(np.float32) * 0.1)
        LRS = jnp.asarray(np.full((S, N), 0.05, np.float32))
        args = (jnp.full((N,), 1e-3), jnp.full((N,), 0.7),
                jnp.full((N,), 0.3),
                jnp.asarray([1.0, 0.0, 1.0, 1.0], jnp.float32))
        Wx, lx = _sgd_cohort_scan(Xr, yr, NV, order, jnp.array(W), LRS,
                                  *args, "log_loss")
        Wp, lp = _sgd_cohort_scan_pallas(Xr, yr, NV, order,
                                         jnp.array(W), LRS, *args,
                                         "log_loss", interpret=True)
        np.testing.assert_allclose(Wp, Wx, atol=1e-5)
        np.testing.assert_allclose(lp, lx, rtol=1e-5, atol=1e-5)

    def test_batched_fused_calls_pick_pallas_when_gated_in(self):
        """The adaptive-search cohort driver routes through the fused
        scan when the stacked block height fits the kernel grid, and
        the advanced models match the XLA route."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        rng = np.random.RandomState(11)
        blocks = [(rng.randn(256, 6).astype(np.float32),
                   (rng.rand(256) > 0.5).astype(np.float32))
                  for _ in range(3)]

        def cohort():
            ms = [SGDClassifier(alpha=a, random_state=0)
                  for a in (1e-4, 1e-3)]
            for m in ms:
                m._set_classes(np.array([0.0, 1.0]))
            return ms

        with config.set(pallas_stream_interpret=True):
            fused = SGDClassifier._batched_fused_calls(cohort(), blocks)
        plain = SGDClassifier._batched_fused_calls(cohort(), blocks)
        for mf, mp in zip(fused, plain):
            np.testing.assert_allclose(np.asarray(mf._w),
                                       np.asarray(mp._w), atol=1e-5)


class TestFusedShardedKMeans:
    def test_streamed_lloyd_fused_parity(self):
        from dask_ml_tpu.models.kmeans import KMeans

        rng = np.random.RandomState(2)
        X = np.concatenate([
            rng.randn(1400, 5).astype(np.float32) + c for c in (0, 6, 12)
        ])
        res = {}
        for interp in (False, True):
            with config.set(stream_block_rows=1024,
                            pallas_stream_interpret=interp):
                km = KMeans(n_clusters=3, random_state=0,
                            max_iter=15).fit(X)
                res[interp] = (np.sort(km.cluster_centers_, axis=0),
                               km.inertia_)
        np.testing.assert_allclose(res[True][0], res[False][0],
                                   atol=1e-5)
        assert res[True][1] == pytest.approx(res[False][1], rel=1e-5)

    def test_sharded_assign_stats_tracked_as_pallas_psum(self):
        from dask_ml_tpu.models.kmeans import _sb_assign_stats_sharded
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        mesh = stream_data_mesh()
        fused = _sb_assign_stats_sharded(mesh, None, fused=True,
                                         interpret=True)
        assert fused.program_name == "pallas.kmeans_stream.psum"
        plain = _sb_assign_stats_sharded(mesh, None)
        assert plain.program_name == "superblock.kmeans_assign.psum"


class TestGradAccum:
    def _xy(self, n=5000, d=8):
        # 5000 rows / 512-row blocks: a ragged 392-row tail whose
        # valid-row count is NOT a power of two — the case where a
        # normalize-after-the-sum flavor would diverge in the last bit
        return _mk_xy(n, d, seed=9)

    def test_a1_exact_parity_with_sequential(self):
        """Bit-exact vs the sequential SINGLE-DEVICE flavor
        (stream_mesh=1), whose step normalizes inside autodiff exactly
        like the micro kernel; the sharded sequential scan normalizes
        its raw sums after the psum, so parity there is
        float-reassociation-level (second assert)."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = self._xy()
        with config.set(stream_block_rows=512, stream_mesh=1):
            base = SGDClassifier(max_iter=3, random_state=0,
                                 shuffle=False).fit(X, y)
        with config.set(stream_block_rows=512, stream_mesh=1,
                        stream_grad_accum=1):
            a1 = SGDClassifier(max_iter=3, random_state=0,
                               shuffle=False).fit(X, y)
        assert a1.solver_info_["grad_accum"] == 1
        assert a1._t == base._t
        np.testing.assert_array_equal(a1.coef_, base.coef_)
        np.testing.assert_array_equal(a1.intercept_, base.intercept_)
        with config.set(stream_block_rows=512):
            sh = SGDClassifier(max_iter=3, random_state=0,
                               shuffle=False).fit(X, y)
        with config.set(stream_block_rows=512, stream_grad_accum=1):
            g8 = SGDClassifier(max_iter=3, random_state=0,
                               shuffle=False).fit(X, y)
        np.testing.assert_allclose(g8.coef_, sh.coef_, atol=1e-6)

    def test_a1_exact_parity_shuffled(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = self._xy()
        with config.set(stream_block_rows=512, stream_mesh=1):
            base = SGDClassifier(max_iter=2, random_state=0,
                                 shuffle=True).fit(X, y)
        with config.set(stream_block_rows=512, stream_mesh=1,
                        stream_grad_accum=1):
            a1 = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=True).fit(X, y)
        np.testing.assert_array_equal(a1.coef_, base.coef_)

    def test_a1_exact_parity_multiclass(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, _ = self._xy()
        y = np.random.RandomState(5).randint(0, 3, len(X)).astype(float)
        with config.set(stream_block_rows=512, stream_mesh=1):
            base = SGDClassifier(max_iter=2, random_state=0,
                                 shuffle=False).fit(X, y)
        with config.set(stream_block_rows=512, stream_mesh=1,
                        stream_grad_accum=1):
            a1 = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=False).fit(X, y)
        np.testing.assert_array_equal(a1.coef_, base.coef_)

    @pytest.mark.parametrize("A", [2, 4])
    def test_larger_a_converges_within_documented_tolerance(self, A):
        """A>1 trains on A-block effective batches — fewer, larger
        steps: the fit converges to a near-identical model (the
        documented tolerance: >=99% prediction agreement with the
        sequential fit and comparable accuracy)."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = self._xy()
        with config.set(stream_block_rows=512):
            base = SGDClassifier(max_iter=3, random_state=0,
                                 shuffle=False).fit(X, y)
        with config.set(stream_block_rows=512, stream_grad_accum=A):
            m = SGDClassifier(max_iter=3, random_state=0,
                              shuffle=False).fit(X, y)
        assert m.solver_info_["grad_accum"] == A
        assert np.mean(m.predict(X) == base.predict(X)) >= 0.99
        assert m.score(X, y) >= base.score(X, y) - 0.01

    def test_two_virtual_processes_match_single_process_a2(self):
        """P processes at A over round-robin block shards ==
        single-process at A*P, bit-exact (both accumulate/merge the
        identical f64 additions in the identical order; stream_mesh=1
        pins the per-block kernels to one device so their partial sums
        cannot reassociate)."""
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.parallel import distributed as dist

        n, d, br = 4096, 8, 256
        X, y = self._xy(n, d)
        blocks = [X[i:i + br] for i in range(0, n, br)]
        yblocks = [y[i:i + br] for i in range(0, n, br)]

        def proc(rank):
            Xl = np.concatenate(blocks[rank::2])
            yl = np.concatenate(yblocks[rank::2])
            with config.set(stream_block_rows=br, stream_grad_accum=1,
                            stream_mesh=1):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=False).fit(Xl, yl)
            return np.asarray(m.coef_)

        res = dist.run_virtual_processes(proc, world=2)
        with config.set(stream_block_rows=br, stream_grad_accum=2,
                        stream_mesh=1):
            ref = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(X, y)
        np.testing.assert_array_equal(res[0], res[1])
        np.testing.assert_array_equal(res[0], ref.coef_)

    def test_quarantine_composition_refused(self):
        """Group counts are exchanged before blocks are read, so the
        quarantine policy (which folds counts to zero at read time)
        cannot compose — refuse loudly instead of normalizing wrong."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = self._xy(1024)
        with config.set(stream_block_rows=256, stream_grad_accum=1,
                        stream_nonfinite="quarantine"):
            with pytest.raises(ValueError, match="quarantine"):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(X, y)

    def test_refusal_still_names_the_escape_hatch(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.parallel import distributed as dist

        X, y = self._xy(1024)

        def proc(rank):
            SGDClassifier(max_iter=1).fit(X, y)

        with pytest.raises(NotImplementedError,
                           match="stream_grad_accum"):
            dist.run_virtual_processes(proc, world=2)


class TestAdmmGspmdTracking:
    def test_sharded_admm_records_program_and_reduce_bytes(self):
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.solvers.streamed import _sb_admm_local

        assert _sb_admm_local(2, "logistic", True, 0,
                              gspmd=True).program_name \
            == "superblock.glm.admm_local.gspmd"
        assert _sb_admm_local(2, "logistic", True, 0).program_name \
            == "superblock.glm.admm_local"
        X, y = _mk_xy(2048, 5)
        obs.counters_reset()
        with config.set(stream_block_rows=512):
            clf = LogisticRegression(solver="admm", max_iter=4).fit(
                X.astype(np.float64), y.astype(np.float64)
            )
        snap = obs.counters_snapshot()
        assert clf.solver_info_["stream_shards"] == 8
        assert snap.get("gspmd_reduce_dispatches", 0) >= 1, snap
        assert snap.get("gspmd_reduce_bytes", 0) > 0
        # trivial mesh: no implicit GSPMD, no counter movement
        obs.counters_reset()
        with config.set(stream_block_rows=512, stream_mesh=1):
            LogisticRegression(solver="admm", max_iter=2).fit(
                X.astype(np.float64), y.astype(np.float64)
            )
        assert obs.counters_snapshot().get("gspmd_reduce_bytes", 0) == 0
