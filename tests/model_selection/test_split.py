"""Split tests (ref: tests/model_selection/test_split.py)."""

import numpy as np
import pytest

from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.model_selection import KFold, ShuffleSplit, train_test_split
from dask_ml_tpu.parallel import ShardedArray


@pytest.fixture(scope="module")
def data():
    return make_classification(n_samples=500, n_features=6, random_state=0)


def test_train_test_split_shapes(data):
    X, y = data
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.2, random_state=0)
    assert isinstance(Xtr, ShardedArray)
    assert Xtr.shape[0] + Xte.shape[0] == 500
    assert Xte.shape[0] == pytest.approx(100, abs=8)  # blockwise rounding
    assert ytr.shape[0] == Xtr.shape[0]


def test_train_test_split_no_overlap(data):
    X, y = data
    # tag each row with a unique value via the first feature
    Xh = X.to_numpy().copy()  # to_numpy view of a jax array is read-only
    Xh[:, 0] = np.arange(500)
    Xs = ShardedArray.from_array(Xh, X.mesh)
    Xtr, Xte = train_test_split(Xs, test_size=0.25, random_state=1)
    ids_tr = set(Xtr.to_numpy()[:, 0].astype(int))
    ids_te = set(Xte.to_numpy()[:, 0].astype(int))
    assert not ids_tr & ids_te
    assert len(ids_tr | ids_te) == 500


def test_train_test_split_blockwise_false(data):
    X, y = data
    Xtr, Xte, ytr, yte = train_test_split(
        X, y, test_size=0.2, blockwise=False, random_state=0
    )
    assert Xte.shape[0] == 100


def test_train_test_split_numpy_arrays():
    X = np.arange(100).reshape(50, 2)
    y = np.arange(50)
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.2, random_state=0)
    assert isinstance(Xtr, np.ndarray)
    assert len(Xte) == 10


def test_train_test_split_errors(data):
    X, y = data
    with pytest.raises(ValueError, match="inconsistent"):
        train_test_split(X, np.arange(10))
    with pytest.raises(ValueError):
        train_test_split(X, test_size=0.9, train_size=0.9)


def test_kfold(data):
    X, _ = data
    kf = KFold(n_splits=5)
    folds = list(kf.split(X))
    assert len(folds) == 5
    all_test = np.concatenate([te for _, te in folds])
    assert sorted(all_test) == list(range(500))
    for tr, te in folds:
        assert not set(tr) & set(te)
        assert len(tr) + len(te) == 500


def test_kfold_shuffle(data):
    X, _ = data
    f1 = list(KFold(n_splits=3, shuffle=True, random_state=0).split(X))
    f2 = list(KFold(n_splits=3, shuffle=True, random_state=0).split(X))
    np.testing.assert_array_equal(f1[0][1], f2[0][1])


def test_shuffle_split(data):
    X, _ = data
    ss = ShuffleSplit(n_splits=3, test_size=0.2, random_state=0)
    folds = list(ss.split(X))
    assert len(folds) == 3
    assert ss.get_n_splits() == 3
    tr, te = folds[0]
    assert not set(tr) & set(te)


def test_unshuffled_split_is_train_leading():
    """sklearn contract: shuffle=False gives train = leading rows, test =
    trailing (the chronological-holdout idiom)."""
    import numpy as np

    from dask_ml_tpu.model_selection import train_test_split

    X = np.arange(100)[:, None].astype(np.float32)
    Xtr, Xte = train_test_split(X, test_size=0.25, shuffle=False)
    assert Xtr[0, 0] == 0 and Xtr[-1, 0] == 74
    assert Xte[0, 0] == 75 and Xte[-1, 0] == 99
