from .validation import check_array, check_is_fitted, check_X_y
