"""Fleet-scope observability (ISSUE 19): cross-process trace
propagation (``X-Trace-Context`` + ``rtrace.trace_context``), live
metrics federation (``observability/fleet.MetricsFederator`` riding the
federation status poller), the ``/status/fleet`` surface, Perfetto
cross-process flow chains, and ``report --watch``.

The load-bearing assertions: a federated request is ONE trace across
the process boundary (router and worker records share the router's
pid-prefixed id, reroute legs chain through the same id with
``rerouted_from_process`` naming the corpse), fleet histograms merge
bucket-for-bucket so merged quantiles match pooling the raw
observations, the federator shares the poller's single /status scrape
per interval (the PR 6 double-consume lesson), dead processes' series
DROP rather than latch, the federated exposition stays grammar-clean
(one TYPE line per family), and federation off — the default — builds
nothing, registers nothing, and starts no thread.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from dask_ml_tpu import config
from dask_ml_tpu.observability import _requests as rtrace
from dask_ml_tpu.observability import live
from dask_ml_tpu.observability._hist import (
    Histogram,
    merge_snapshots,
    percentiles_from,
)
from dask_ml_tpu.observability.fleet import MetricsFederator
from dask_ml_tpu.serving import (
    BucketLadder,
    FederatedFleet,
    FleetServer,
    HttpEndpoint,
    LocalEndpoint,
    ProcessDown,
)
from dask_ml_tpu.serving.federation import FleetEndpoint


@pytest.fixture(scope="module")
def fitted():
    """One fitted model + host rows (the serving fixture)."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=400, n_features=10, n_informative=5, random_state=0
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=25).fit(X, y)
    return clf, X.to_numpy().astype(np.float32)


@pytest.fixture(autouse=True)
def _trace_isolation():
    rtrace.traces_reset()
    yield
    rtrace.traces_reset()


def _ladder():
    return BucketLadder(8, 64, 2.0)


def _pair(clf, name="fobs"):
    f1 = FleetServer(clf, name=name, replicas=1, ladder=_ladder(),
                     batch_window_ms=1.0).warmup().start()
    f2 = FleetServer(clf, name=name, replicas=1, ladder=_ladder(),
                     batch_window_ms=1.0).warmup().start()
    fed = FederatedFleet(
        [LocalEndpoint(f1, "p0"), LocalEndpoint(f2, "p1")],
        name=name, ladder=_ladder(),
    ).start()
    return f1, f2, fed


# -- trace-context propagation ----------------------------------------------

def test_trace_context_continues_and_restores():
    """Traces minted inside trace_context REUSE the given id; outside
    they mint fresh pid-prefixed ids; nesting restores the outer id."""
    with rtrace.trace_context(424242):
        assert rtrace.RequestTrace("predict", 1).trace_id == 424242
        with rtrace.trace_context(777):
            assert rtrace.RequestTrace("predict", 1).trace_id == 777
        assert rtrace.RequestTrace("predict", 1).trace_id == 424242
    fresh = rtrace.RequestTrace("predict", 1).trace_id
    assert fresh != 424242 and (fresh >> 24) > 0


def test_trace_context_is_thread_local():
    """Rank threads must not leak continuation ids into each other —
    the property the virtual-rank harness and the fed pool rely on."""
    from dask_ml_tpu.parallel.distributed import run_virtual_processes

    def rank_trace(rank):
        with rtrace.trace_context(1000 + rank):
            return rtrace.RequestTrace("predict", 1).trace_id

    ids = run_virtual_processes(rank_trace, world=2)
    assert ids == [1000, 1001]
    assert rtrace._pending_ctx() is None


def test_local_endpoint_joins_router_and_worker_traces(fitted):
    """A federated request is ONE trace: the router's record (admit/
    dispatch/complete, tagged with the chosen process) and the worker
    fleet's full-stage record share the router's id, and the worker's
    window telescopes inside the router's."""
    clf, Xh = fitted
    with config.set(obs_trace_sample=1.0):
        f1, f2, fed = _pair(clf)
        try:
            fed.predict(Xh[:8])
        finally:
            fed.stop()
            f1.stop(drain=False)
            f2.stop(drain=False)
    recs = rtrace.traces_data()["traces"]
    router = [r for r in recs if r.get("federation") == "fobs"]
    assert len(router) == 1, recs
    rt = router[0]
    assert rt["outcome"] == "ok"
    assert rt.get("process") in ("p0", "p1")
    assert set(rt["stages"]) >= {"admit", "dispatch", "complete"}
    workers = [r for r in recs if r["trace_id"] == rt["trace_id"]
               and r.get("federation") != "fobs"]
    assert len(workers) == 1, recs
    wk = workers[0]
    # the worker leg ran the full pipeline and telescopes: its stage
    # durations sum to its e2e, which fits inside the router's window
    assert set(wk["stages"]) >= {"admit", "queue_pop", "complete"}
    assert sum(wk["durations"].values()) == pytest.approx(
        wk["e2e_s"], abs=5e-5)
    assert wk["e2e_s"] <= rt["e2e_s"] + 1e-4


def test_trace_propagate_toggle_mints_fresh_worker_ids(fitted):
    """obs_trace_propagate=False keeps the plane on but severs the
    continuation: router and worker record DIFFERENT ids."""
    clf, Xh = fitted
    with config.set(obs_trace_sample=1.0, obs_trace_propagate=False):
        f1, f2, fed = _pair(clf, name="fobs-off")
        try:
            fed.predict(Xh[:8])
        finally:
            fed.stop()
            f1.stop(drain=False)
            f2.stop(drain=False)
    recs = rtrace.traces_data()["traces"]
    assert len(recs) == 2, recs
    assert len({r["trace_id"] for r in recs}) == 2


def test_http_endpoint_continues_trace_over_wire(fitted):
    """X-Trace-Context across a REAL HTTP hop: the receiving process's
    handler re-enters the router's trace id around its fleet submit."""
    from dask_ml_tpu.observability.live import TelemetryServer

    clf, Xh = fitted
    ts = TelemetryServer(port=0).start()
    with config.set(obs_trace_sample=1.0):
        # built INSIDE the config block: the serving fleet captures its
        # trace gate (and its workers' config) at construction — the
        # real remote process enables sampling via its own env/config
        fleet = FleetServer(clf, name="fobs-http", replicas=1,
                            ladder=_ladder(), batch_window_ms=1.0) \
            .warmup().start()
        try:
            ep = HttpEndpoint(ts.url, name="fobs-http",
                              process_id="h0", timeout_s=30.0)
            fed = FederatedFleet([ep], name="fobs-http",
                                 ladder=_ladder()).start()
            try:
                fed.predict(Xh[:8])
            finally:
                fed.stop()
        finally:
            fleet.stop()
            ts.stop()
    recs = rtrace.traces_data()["traces"]
    router = [r for r in recs if r.get("federation") == "fobs-http"]
    assert len(router) == 1, recs
    rid = router[0]["trace_id"]
    workers = [r for r in recs if r["trace_id"] == rid
               and r.get("federation") != "fobs-http"]
    assert len(workers) == 1, recs
    assert "queue_pop" in workers[0]["stages"]


class _DyingEndpoint(FleetEndpoint):
    """Ranks as a live process, dies on every submit — the router must
    reroute and chain the trace through the survivor."""

    def __init__(self, process_id, fleet_name):
        self.process_id = str(process_id)
        self.fleet_name = str(fleet_name)

    def status(self):
        # rank FIRST: no queue, instant predicted completion
        return {"fleet": self.fleet_name, "queue_rows": 0,
                "replicas": [{"exec_s": {"predict:64":
                                         {"count": 50, "p50_s": 1e-6,
                                          "p90_s": 1e-6}}}],
                "healthy_replicas": 1}

    def status_doc(self):
        return {"serving": [self.status()], "counters": {},
                "telemetry": {"gauges": [], "histograms": []}}

    def submit(self, X, method="predict", rerouted_from=None,
               trace_ctx=None):
        raise ProcessDown(f"{self.process_id}: killed mid-flight")


def test_killed_process_reroute_chains_parent_trace(fitted):
    """A process dying mid-flight yields ONE joined trace: the router's
    record carries ``rerouted_from_process`` naming the corpse, and the
    SURVIVOR's full-stage record continues the same id with the same
    reroute tag (the X-Fed-Reroute + X-Trace-Context pair)."""
    clf, Xh = fitted
    with config.set(obs_trace_sample=1.0):
        f1 = FleetServer(clf, name="fobs-kill", replicas=1,
                         ladder=_ladder(), batch_window_ms=1.0) \
            .warmup().start()
        try:
            fed = FederatedFleet(
                [_DyingEndpoint("corpse", "fobs-kill"),
                 LocalEndpoint(f1, "survivor")],
                name="fobs-kill", ladder=_ladder(),
            ).start()
            try:
                out = fed.predict(Xh[:8])
                assert out.shape[0] == 8
            finally:
                fed.stop()
        finally:
            f1.stop(drain=False)
    recs = rtrace.traces_data()["traces"]
    router = [r for r in recs if r.get("federation") == "fobs-kill"]
    assert len(router) == 1, recs
    rt = router[0]
    assert rt["outcome"] == "ok"
    assert rt.get("rerouted_from_process") == "corpse"
    assert rt.get("process") == "survivor"
    legs = [r for r in recs if r["trace_id"] == rt["trace_id"]
            and r.get("federation") != "fobs-kill"]
    assert len(legs) == 1, recs
    assert legs[0].get("rerouted_from_process") == "corpse"


# -- histogram merge ---------------------------------------------------------

def test_histogram_merge_exact_sums():
    a, b = Histogram(), Histogram()
    for v in (1e-4, 0.003, 0.02, 0.7):
        a.observe(v)
    for v in (0.005, 5.0):
        b.observe(v)
    m = Histogram().merge(a).merge(b.snapshot())  # object AND dict
    assert m.count == 6
    assert m.sum == pytest.approx(a.sum + b.sum)
    snap = m.snapshot()
    assert snap["min"] == pytest.approx(1e-4)
    assert snap["max"] == pytest.approx(5.0)
    pooled = Histogram()
    for v in (1e-4, 0.003, 0.02, 0.7, 0.005, 5.0):
        pooled.observe(v)
    assert snap["counts"] == pooled.snapshot()["counts"]


def test_histogram_merge_bounds_mismatch_raises():
    a = Histogram((0.1, 1.0))
    with pytest.raises(ValueError):
        a.merge(Histogram((0.1, 2.0)))
    with pytest.raises(ValueError):
        a.merge(Histogram())


def test_merged_percentiles_match_pooled_within_bucket_width():
    """Property: for random observations split over 3 'processes', the
    merged quantiles equal the pooled-histogram quantiles EXACTLY
    (fixed bounds => bucket-for-bucket), and both sit within one
    1-2-5 bucket width of the true sample quantile."""
    rng = np.random.default_rng(7)
    for _ in range(5):
        obs = rng.lognormal(mean=-4.0, sigma=1.5, size=300)
        parts = np.array_split(obs, 3)
        hists = []
        pooled = Histogram()
        for part in parts:
            h = Histogram()
            for v in part:
                h.observe(float(v))
                pooled.observe(float(v))
            hists.append(h.snapshot())
        merged = merge_snapshots(hists)
        assert merged["counts"] == pooled.snapshot()["counts"]
        mp = percentiles_from(merged, (50, 99))
        pp = pooled.percentiles((50, 99))
        for q in ("p50", "p99"):
            assert mp[q] == pytest.approx(pp[q])
            exact = float(np.percentile(obs, int(q[1:])))
            # one bucket width on the 1-2-5 ladder: factor <= 2.5,
            # clamped estimates can only tighten it
            assert mp[q] <= exact * 2.5 + 1e-12
            assert mp[q] >= exact / 2.5 - 1e-12


def test_merge_snapshots_none_tolerant_and_empty():
    assert merge_snapshots([]) is None
    assert merge_snapshots([None, None]) is None
    h = Histogram()
    h.observe(0.01)
    out = merge_snapshots([None, h.snapshot(), None])
    assert out["count"] == 1


# -- the federator -----------------------------------------------------------

def _doc(requests=0, violations=0, queue=0.0, obs=()):
    h = Histogram()
    for v in obs:
        h.observe(v)
    return {
        "counters": {"serving_requests": requests,
                     "serving_slo_violations": violations},
        "telemetry": {
            "gauges": [["serving_queue_rows", [], float(queue)]],
            "histograms": [["serving_latency_seconds",
                            [["method", "predict"]], h.snapshot()]],
        },
    }


def test_federator_counters_sum_gauges_labeled_hists_merge():
    fed = MetricsFederator(name="m")
    assert fed.ingest([("p0", _doc(10, 1, 3.0, (0.01, 0.02))),
                       ("p1", _doc(5, 0, 1.0, (0.5,)))],
                      scrape_s=0.002)
    txt = "\n".join(fed.render_lines())
    assert "dask_ml_tpu_fleet_serving_requests_total 15" in txt
    assert "dask_ml_tpu_fleet_serving_slo_violations_total 1" in txt
    assert ('dask_ml_tpu_fleet_serving_queue_rows{process="p0"} 3'
            in txt)
    assert ('dask_ml_tpu_fleet_serving_queue_rows{process="p1"} 1'
            in txt)
    # the merged histogram holds all three observations
    assert ('dask_ml_tpu_fleet_serving_latency_seconds_count'
            '{method="predict"} 3') in txt
    blk = fed.fleet_block()
    assert blk["n_scraped"] == 2 and blk["processes"] == ["p0", "p1"]
    key = 'serving_latency_seconds{method="predict"}'
    assert blk["histograms"][key]["count"] == 3
    assert blk["scrape_seconds"] == pytest.approx(0.002)


def test_federator_dead_series_dropped_not_latched():
    fed = MetricsFederator(name="m")
    fed.ingest([("p0", _doc(1, queue=2.0)), ("p1", _doc(1, queue=5.0))])
    assert 'process="p1"' in "\n".join(fed.render_lines())
    # p1 dies: its doc is None this interval — every p1 series vanishes
    fed.ingest([("p0", _doc(2, queue=2.0)), ("p1", None)])
    txt = "\n".join(fed.render_lines())
    assert 'process="p1"' not in txt
    assert fed.fleet_block()["processes"] == ["p0"]
    # a process absent from the snapshot list entirely (retired
    # endpoint) drops too
    fed.ingest([("p1", _doc(9, queue=1.0))])
    assert fed.fleet_block()["processes"] == ["p1"]


def test_federator_throttle_still_drops_dead(monkeypatch):
    """obs_fleet_poll_s throttles the merge work but a dead process's
    series still drop on the throttled tick (never latch)."""
    fed = MetricsFederator(name="m", min_interval_s=3600.0)
    assert fed.ingest([("p0", _doc(1)), ("p1", _doc(1))])
    assert fed.ingest([("p0", _doc(2)), ("p1", None)]) is False
    assert fed.fleet_block()["processes"] == ["p0"]


def test_federated_exposition_grammar_one_type_per_family(fitted):
    """The router's full /metrics page with the federator registered:
    every sample line belongs to exactly one declared family, no family
    declares TYPE twice, and every fleet family is namespaced."""
    fed = MetricsFederator(name="m")
    fed.ingest([("p0", _doc(10, 1, 3.0, (0.01,))),
                ("p1", _doc(5, 0, 1.0, (0.5,)))], scrape_s=0.001)
    live.register_fleet_provider(fed)
    try:
        page = live.render_prometheus()
    finally:
        live.unregister_fleet_provider(fed)
    types = {}
    for ln in page.splitlines():
        if ln.startswith("# TYPE "):
            _, _, fam, kind = ln.split()
            assert fam not in types, f"duplicate TYPE for {fam}"
            types[fam] = kind
    assert types['dask_ml_tpu_fleet_serving_requests_total'] == \
        "counter"
    assert types["dask_ml_tpu_fleet_processes"] == "gauge"
    assert types["dask_ml_tpu_fleet_serving_latency_seconds"] == \
        "histogram"
    for ln in page.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{")[0].split(" ")[0]
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                fam = name[:-len(suffix)]
                break
        assert fam in types, f"sample {name} has no TYPE line"


def test_slo_burn_rate_latches_alerts():
    """A window burning past the budget latches an alert that SURVIVES
    the burn subsiding; the burn gauge itself recovers."""
    fed = MetricsFederator(name="m", slo_ms=50.0)
    fed.ingest([("p0", _doc(100, 0))])
    assert fed.fleet_block()["slo"]["burn_rate"] == 0.0
    # 10 violations over 100 requests = 10% >> the 1% budget
    fed.ingest([("p0", _doc(200, 10))])
    blk = fed.fleet_block()["slo"]
    assert blk["burn_rate"] == pytest.approx(10.0)
    assert len(blk["alerts"]) == 1
    assert blk["alerts"][0]["violations"] == 10
    # burn subsides: gauge drops, the latched alert stays
    fed.ingest([("p0", _doc(300, 10))])
    blk = fed.fleet_block()["slo"]
    assert blk["burn_rate"] == 0.0
    assert len(blk["alerts"]) == 1


def test_status_fleet_http_surface():
    """/status/fleet serves the registered federator's block; /status
    embeds the same block under "fleet"; no federator => {} / absent."""
    from dask_ml_tpu.observability.live import TelemetryServer

    def get(url):
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return json.loads(resp.read().decode())

    ts = TelemetryServer(port=0).start()
    try:
        assert get(f"{ts.url}/status/fleet") == {}
        assert "fleet" not in get(f"{ts.url}/status")
        fed = MetricsFederator(name="m")
        fed.ingest([("p0", _doc(3))])
        live.register_fleet_provider(fed)
        try:
            doc = get(f"{ts.url}/status/fleet")
            assert doc["n_scraped"] == 1
            assert doc["counters"]["serving_requests"] == 3
            assert get(f"{ts.url}/status")["fleet"]["n_scraped"] == 1
        finally:
            live.unregister_fleet_provider(fed)
    finally:
        ts.stop()


# -- the poller shares ONE scrape with the federator -------------------------

class _CountingEndpoint(FleetEndpoint):
    def __init__(self, process_id):
        self.process_id = str(process_id)
        self.doc_calls = 0
        self.status_calls = 0

    def status_doc(self):
        self.doc_calls += 1
        return {"serving": [{"fleet": "cnt", "queue_rows": 0,
                             "replicas": [], "healthy_replicas": 1}],
                "counters": {"serving_requests": 7,
                             "serving_slo_violations": 0},
                "telemetry": {"gauges": [], "histograms": []}}

    def status(self):
        self.status_calls += 1
        return self.status_doc()["serving"][0]


def test_poller_single_scrape_feeds_routing_and_federator():
    """The PR 6 lesson applied fleet-wide: one status_doc fetch per
    process per poll interval feeds BOTH the routing stats and the
    metrics federator — the federator never issues its own read."""
    ep = _CountingEndpoint("p0")
    with config.set(obs_fleet_federate=True):
        fed = FederatedFleet([ep], name="cnt", ladder=_ladder())
    assert fed._federator is not None
    fed._poll_once()
    assert ep.doc_calls == 1
    assert ep.status_calls == 0
    assert fed._federator.fleet_block()["counters"][
        "serving_requests"] == 7
    fed._poll_once()
    assert ep.doc_calls == 2


# -- zero-overhead contract --------------------------------------------------

def test_federation_off_by_default_builds_nothing(fitted):
    """The default config builds no federator, registers no provider,
    and leaves the router's exposition byte-identical — the fleet plane
    costs nothing unless asked for."""
    clf, _ = fitted
    before = live.render_prometheus()
    f1 = FleetServer(clf, name="fobs-zero", replicas=1,
                     ladder=_ladder(), batch_window_ms=1.0).start()
    try:
        fed = FederatedFleet([LocalEndpoint(f1, "p0")],
                             name="fobs-zero", ladder=_ladder())
        assert fed._federator is None
        with fed:
            assert not live._fleet_providers
            assert "fleet_" not in live.render_prometheus()
    finally:
        f1.stop(drain=False)
    assert "dask_ml_tpu_fleet_" not in before


def test_federator_rides_poller_no_new_threads(fitted):
    """Federation ON adds zero threads: the thread census before and
    after start() differs only by the poller + submit pool the
    federation already owned (no federator thread exists to find)."""
    clf, _ = fitted
    f1 = FleetServer(clf, name="fobs-thr", replicas=1,
                     ladder=_ladder(), batch_window_ms=1.0).start()
    try:
        with config.set(obs_fleet_federate=True):
            fed = FederatedFleet([LocalEndpoint(f1, "p0")],
                                 name="fobs-thr", ladder=_ladder())
        names_before = {t.name for t in threading.enumerate()}
        with fed:
            new = {t.name for t in threading.enumerate()} \
                - names_before
            assert all(n.startswith(("fed-poller", "fed-submit"))
                       for n in new), new
    finally:
        f1.stop(drain=False)


# -- Perfetto cross-process flow chains --------------------------------------

def test_export_flow_chain_joins_processes():
    """Three legs of one trace across two pids (router, corpse leg,
    survivor) chain as s -> t -> f flow events on pid-prefixed lanes —
    one arrow threading the whole federated request."""
    from dask_ml_tpu.observability.export import to_chrome_trace

    rid = (77 << 24) | 5
    records = [
        # router leg (pid 77)
        {"req_trace": True, "trace_id": rid, "pid": 77,
         "method": "predict", "n_rows": 8, "t_unix": 100.0,
         "e2e_s": 0.05, "outcome": "ok",
         "stages": {"admit": 0.0, "dispatch": 0.001, "complete": 0.05},
         "durations": {}, "threads": {"admit": "MainThread"}},
        # worker leg on the survivor (pid 99)
        {"req_trace": True, "trace_id": rid, "pid": 99,
         "method": "predict", "n_rows": 8, "t_unix": 100.002,
         "e2e_s": 0.04, "outcome": "ok",
         "rerouted_from_process": "p0",
         "stages": {"admit": 0.0, "queue_pop": 0.001, "pack": 0.002,
                    "dispatch": 0.003, "execute_done": 0.03,
                    "demux": 0.035, "complete": 0.04},
         "durations": {}, "threads": {"admit": "http",
                                      "worker": "w0"}},
        # an unrelated single-leg trace keeps its s/f pair
        {"req_trace": True, "trace_id": (77 << 24) | 9, "pid": 77,
         "method": "predict", "n_rows": 1, "t_unix": 101.0,
         "e2e_s": 0.01, "outcome": "ok",
         "stages": {"admit": 0.0, "complete": 0.01},
         "durations": {}, "threads": {"admit": "MainThread"}},
    ]
    trace = to_chrome_trace(records)
    flows = [e for e in trace["traceEvents"]
             if e.get("cat") == "request" and e["ph"] in "stf"
             and e["id"] == rid]
    phases = [e["ph"] for e in flows]
    assert phases.count("s") == 1
    assert phases.count("f") == 1
    assert phases.count("t") == 2  # first leg's end + second leg's start
    assert [e["ph"] for e in flows[:1]] == ["s"]
    assert flows[-1]["ph"] == "f" and flows[-1]["bp"] == "e"
    # multi-process laning: the two legs live on pid-prefixed lanes
    lanes = [e["args"]["name"]
             for e in trace["traceEvents"] if e["ph"] == "M"]
    assert any(v.startswith("pid77.") for v in lanes)
    assert any(v.startswith("pid99.") for v in lanes)
    # the single-stage single leg would have no slices, but the other
    # single-leg trace still emits its own s/f pair
    other = [e for e in trace["traceEvents"]
             if e.get("cat") == "request" and e.get("ph") in "stf"
             and e.get("id") == ((77 << 24) | 9)]
    assert [e["ph"] for e in other] == ["s", "f"]


# -- report --watch ----------------------------------------------------------

def test_report_watch_once_renders_frame(capsys):
    """`report --watch URL --once` renders one live frame off /status +
    /traces and exits 0 — the CI-checkable slice of the watch loop."""
    from dask_ml_tpu.observability import report as report_cli
    from dask_ml_tpu.observability.live import TelemetryServer

    fed = MetricsFederator(name="m")
    fed.ingest([("p0", _doc(3, 1))])
    live.register_fleet_provider(fed)
    ts = TelemetryServer(port=0).start()
    try:
        rc = report_cli.main(["--watch", ts.url, "--once"])
    finally:
        ts.stop()
        live.unregister_fleet_provider(fed)
    out = capsys.readouterr().out
    assert rc == 0
    assert f"live: {ts.url}" in out
    assert "fleet federation" in out
    assert "run report:" in out
    assert "\x1b[2J" not in out  # --once never clears the screen


def test_report_watch_once_unreachable_is_nonzero(capsys):
    from dask_ml_tpu.observability import report as report_cli

    rc = report_cli.main(["--watch", "http://127.0.0.1:9",
                          "--once", "--interval", "0.2"])
    assert rc == 1
    assert "unreachable" in capsys.readouterr().out


# -- real process boundary ---------------------------------------------------

_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
# the child process OPTS INTO tracing via its own env-level config —
# propagation joins ids, each process owns its sampling knob
os.environ["DASK_ML_TPU_OBS_TRACE_SAMPLE"] = "1.0"
port = int(sys.argv[1])
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import BucketLadder, FleetServer
from dask_ml_tpu.observability.live import TelemetryServer
X, y = make_classification(n_samples=200, n_features=10,
                           n_informative=5, random_state=0)
clf = LogisticRegression(solver="lbfgs", max_iter=10).fit(X, y)
fleet = FleetServer(clf, name="fedtrace", replicas=1,
                    ladder=BucketLadder(8, 64, 2.0),
                    batch_window_ms=1.0).warmup().start()
ts = TelemetryServer(port=port).start()
print("FED_READY", port, flush=True)
time.sleep(180)
"""


@pytest.mark.slow
def test_trace_joins_across_real_process_boundary():
    """Two REAL child processes each serving the fleet over HTTP: the
    parent's routed request produces a router trace whose id appears in
    the CHOSEN child's own /traces surface with the full worker-stage
    set — X-Trace-Context surviving an actual process boundary."""
    import os
    import subprocess
    import sys

    from tests._mp_capability import REPO, free_port

    ports = [free_port(), free_port()]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD.format(repo=REPO), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for port in ports
    ]

    def get(url, timeout=5.0):
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode()

    try:
        import time as _time

        deadline = _time.monotonic() + 120.0
        for port in ports:
            while True:
                try:
                    if "ok" in get(f"http://127.0.0.1:{port}/healthz"):
                        break
                except OSError:
                    if _time.monotonic() > deadline:
                        outs = [p.communicate(timeout=5)[0]
                                if p.poll() is not None else "(alive)"
                                for p in procs]
                        raise AssertionError(
                            f"children never came up: {outs}")
                    _time.sleep(0.25)

        eps = [HttpEndpoint(f"http://127.0.0.1:{port}",
                            name="fedtrace", process_id=f"c{i}",
                            timeout_s=30.0)
               for i, port in enumerate(ports)]
        rng = np.random.default_rng(0)
        X8 = rng.normal(size=(8, 10)).astype(np.float32)
        with config.set(obs_trace_sample=1.0):
            fed = FederatedFleet(eps, name="fedtrace",
                                 ladder=_ladder()).start()
            try:
                out = fed.predict(X8)
                assert out.shape[0] == 8
            finally:
                fed.stop()

        router = [r for r in rtrace.traces_data()["traces"]
                  if r.get("federation") == "fedtrace"]
        assert len(router) == 1, router
        rt = router[0]
        assert rt["outcome"] == "ok"
        chosen = rt["process"]
        port = ports[int(chosen[1:])]
        tdoc = json.loads(get(f"http://127.0.0.1:{port}/traces"))
        legs = [t for t in tdoc["traces"]
                if t["trace_id"] == rt["trace_id"]]
        assert len(legs) == 1, tdoc["traces"]
        assert set(legs[0]["stages"]) >= {"admit", "queue_pop",
                                          "complete"}
        assert legs[0]["pid"] != os.getpid()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=10)
