"""Federation verify gate (ISSUE 17): TWO subprocess fleet processes
behind one :class:`FederatedFleet` router must survive a SIGKILL of the
currently-preferred process mid-traffic with

- ZERO lost admitted requests: every submitted request resolves, and
  every answer matches one of the published versions exactly — the
  whole-request re-issue on :class:`ProcessDown` is the mechanism;
- the survivor's sampled traces carrying ``rerouted_from_process``
  (the ``X-Fed-Reroute`` header crossed the process boundary);
- registry RE-CONVERGENCE on the next publish: the survivor's local
  registry pins the control registry's CURRENT version id;
- ZERO post-warmup XLA compiles in the survivor across the whole run
  (routing, failover and the fanned-out hot-swap are all shape-stable);
- ONE trace across the process boundary (ISSUE 19): a routed request's
  trace id shows up in BOTH the router's and the chosen worker's
  ``/traces`` (the ``X-Trace-Context`` header), the worker leg carries
  the full stage set and telescopes inside the router's window;
- the router's federated ``/metrics`` aggregate
  (``dask_ml_tpu_fleet_serving_requests_total``) exactly equals the sum
  of the live per-process ``/status`` counter scrapes;

and, in-parent, a replayed synthetic burst against a 1-replica fleet
whose top-bucket window predicts SLO pressure must fire a plans-warm
autoscale scale-up while the replay itself holds the SLO verdict.

The parent picks two free ports, launches each child with
``DASK_ML_TPU_OBS_HTTP_PORT`` pointing at its own telemetry server,
federates over :class:`HttpEndpoint`\\ s, and asserts on the router's
own counters plus the survivor's ``/status`` and ``/traces``.

Prints one JSON line: {"ok": true, "requests": ..., "recompiles": 0,
"published": 2, ...}. Run: ``python scripts/federation_smoke.py``
(exit 0 = gate holds).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import os, time

from dask_ml_tpu import config
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import BucketLadder, FleetServer

X, y = make_classification(n_samples=600, n_features=12,
                           n_informative=6, random_state=0)
a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)

# trace plane ON: the parent drives the sample rate (1.0 for the
# cross-process trace-join audit — every worker leg is kept; the env
# also raises the keep ring so neither the reroute-audit trace nor the
# joined leg is evicted by the kept traffic behind it). The 0.01
# default keeps the standalone run production-like: reroute-tagged
# traces are ALWAYS kept (the tail sampler's contract)
sample = float(os.environ.get("FED_SMOKE_TRACE_SAMPLE", "0.01"))
with config.set(obs_trace_sample=sample):
    fleet = FleetServer(a, name="fedclf", replicas=2,
                        ladder=BucketLadder(8, 128, 2.0),
                        batch_window_ms=1.0, timeout_ms=0).warmup()
    with fleet:
        print("FED_READY", flush=True)
        # serve until the parent terminates (or SIGKILLs) this process
        time.sleep(float(os.environ.get("FED_SMOKE_LINGER", "180")))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_json(url, timeout=2.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _wait_fleet(base, child, deadline):
    """Block until ``base``'s /status shows the 2-replica fleet."""
    while time.time() < deadline:
        if child.poll() is not None:
            raise RuntimeError(
                "child exited before its fleet came up: "
                + child.stderr.read()[-2000:]
            )
        try:
            doc = _get_json(base + "/status")
        except Exception:
            time.sleep(0.05)
            continue
        for s in doc.get("serving", ()):
            if isinstance(s, dict) and s.get("fleet") == "fedclf" \
                    and s.get("healthy_replicas") == 2:
                return
        time.sleep(0.05)
    raise RuntimeError(f"deadline: {base}/status never showed the fleet")


def _federation_section(out):
    import numpy as np

    from dask_ml_tpu import config, observability as obs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.observability import _requests as rtrace
    from dask_ml_tpu.observability.live import TelemetryServer
    from dask_ml_tpu.serving import (
        BucketLadder,
        FederatedFleet,
        HttpEndpoint,
        ServingError,
    )

    # the parent's twin of the children's deterministic fit: expected
    # answers for BOTH versions (exact-match is the lost-request test)
    X, y = make_classification(n_samples=600, n_features=12,
                               n_informative=6, random_state=0)
    X2, y2 = make_classification(n_samples=600, n_features=12,
                                 n_informative=6, random_state=7)
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
    Xh = X.to_numpy().astype(np.float32)
    preds = {1: np.asarray(a.predict(Xh)), 2: np.asarray(b.predict(Xh))}

    ports = [_free_port(), _free_port()]
    bases = [f"http://127.0.0.1:{p}" for p in ports]
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    children = [
        subprocess.Popen(
            [sys.executable, "-c", CHILD],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "DASK_ML_TPU_OBS_HTTP_PORT": str(p),
                 "FED_SMOKE_TRACE_SAMPLE": "1.0",
                 "DASK_ML_TPU_OBS_TRACE_KEEP": "4096"},
            cwd=here, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for p in ports
    ]
    deadline = time.time() + 180
    ts = None
    try:
        for base, child in zip(bases, children):
            _wait_fleet(base, child, deadline)

        eps = [HttpEndpoint(bases[i], name="fedclf", process_id=f"p{i}",
                            timeout_s=30.0) for i in (0, 1)]
        c0 = obs.counters_snapshot()
        # the ROUTER's own telemetry surface: its /metrics carries the
        # federated dask_ml_tpu_fleet_* families (the MetricsFederator
        # rides the status poller), its /traces the router-side trace
        ts = TelemetryServer(port=0).start()
        with config.set(obs_fleet_federate=True), FederatedFleet(
                eps, name="fedclf",
                ladder=BucketLadder(8, 128, 2.0),
                poll_s=0.25, retry_s=60.0) as fed:
            # warm probes through BOTH processes, then align version
            # numbering: control v1 pins over each child's
            # construction-time v1 (idempotent overwrite)
            for ep in eps:
                got = ep.submit(Xh[:64])
                assert np.array_equal(got, preds[1][:64]), \
                    "cross-process fit is not deterministic"
            v1 = fed.publish(a)
            assert v1 == 1, v1
            time.sleep(0.3)
            base_rec = [
                _get_json(base + "/status")["counters"]
                .get("recompiles", 0)
                for base in bases
            ]

            # cross-process trace join: ONE traced foreground request,
            # then its id must appear on BOTH sides of the HTTP hop —
            # the router's /traces and the chosen child's — with the
            # full worker stage set telescoping inside the router's
            # window (done pre-kill: the chosen process must still be
            # alive to serve its /traces)
            with config.set(obs_trace_sample=1.0):
                got = fed.predict(Xh[:64])
            assert np.array_equal(got, preds[1][:64])
            routed = [r for r in rtrace.traces_data()["traces"]
                      if r.get("federation") == "fedclf"]
            assert len(routed) == 1, routed
            rt = routed[0]
            rid = rt["trace_id"]
            assert rt["outcome"] == "ok", rt
            pdoc = _get_json(ts.url + "/traces")
            assert any(t["trace_id"] == rid
                       for t in pdoc.get("traces", ())), \
                "router /traces misses the routed trace"
            chosen = int(rt["process"][1])
            wdoc = _get_json(bases[chosen] + "/traces")
            legs = [t for t in wdoc.get("traces", ())
                    if t["trace_id"] == rid]
            assert len(legs) == 1, \
                f"worker /traces misses trace {rid}"
            leg = legs[0]
            assert set(leg["stages"]) >= {
                "admit", "queue_pop", "pack", "dispatch",
                "execute_done", "demux", "complete"}, leg
            assert leg["e2e_s"] <= rt["e2e_s"] + 1e-3, (leg, rt)
            assert sum(leg["durations"].values()) <= \
                leg["e2e_s"] + 1e-3, leg
            out.update(trace_id=rid, trace_worker=f"p{chosen}",
                       trace_router_e2e_s=rt["e2e_s"],
                       trace_worker_e2e_s=leg["e2e_s"])

            N_CLIENTS = 3
            # per-thread slots, summed after join (no racy +=)
            sent = [0] * N_CLIENTS
            done = [0] * N_CLIENTS
            errs = []
            stop = threading.Event()

            def client(seed):
                rng = np.random.RandomState(seed)
                while not stop.is_set():
                    n = int(rng.randint(1, 100))
                    i = int(rng.randint(0, Xh.shape[0] - n))
                    sent[seed] += 1
                    try:
                        got = fed.submit(Xh[i:i + n]).result(60)
                    except ServingError as exc:
                        errs.append(repr(exc))   # a shed/timeout IS a
                        continue                 # lost request here
                    except Exception as exc:
                        errs.append(repr(exc))
                        continue
                    if not any(np.array_equal(got, preds[v][i:i + n])
                               for v in (1, 2)):
                        errs.append(f"mismatch at n={n} i={i}")
                        continue
                    done[seed] += 1

            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(N_CLIENTS)]
            for t in threads:
                t.start()
            time.sleep(0.8)

            # SIGKILL the process the router currently PREFERS — the
            # next requests provably route at the corpse and must fail
            # over whole
            victim = int(fed._ranked("predict", 64)[0]
                         .endpoint.process_id[1])
            survivor = 1 - victim
            os.kill(children[victim].pid, signal.SIGKILL)
            children[victim].wait(10)
            # a few foreground requests right through the failover
            # window (the clients race it too)
            for _ in range(3):
                got = fed.predict(Xh[:64])
                assert np.array_equal(got, preds[1][:64])
            time.sleep(0.8)

            # the NEXT publish re-converges the survivor to the
            # control registry's current version
            v2 = fed.publish(b)
            assert v2 == 2, v2
            time.sleep(0.5)
            stop.set()
            for t in threads:
                t.join()

            n_sent, n_done = sum(sent), sum(done)
            assert not errs, errs[:3]
            assert n_done == n_sent, (n_done, n_sent)
            assert n_sent >= 50, f"only {n_sent} requests — no real load"

            fstats = fed.stats()
            assert fstats["live_processes"] == 1, fstats
            dead = [p for p in fstats["processes"]
                    if p["process"] == f"p{victim}"]
            assert dead and not dead[0]["alive"], fstats

            c1 = obs.counters_snapshot()
            reroutes = c1.get("serving_process_reroutes", 0) \
                - c0.get("serving_process_reroutes", 0)
            failovers = c1.get("serving_process_failovers", 0) \
                - c0.get("serving_process_failovers", 0)
            assert reroutes >= 1, f"{reroutes} process reroutes"
            assert failovers >= 1, f"{failovers} process failovers"

            sdoc = _get_json(bases[survivor] + "/status")
            recompiles = sdoc["counters"].get("recompiles", 0) \
                - base_rec[survivor]
            assert recompiles == 0, \
                f"{recompiles} post-warmup compiles in survivor"
            entry = [s for s in sdoc["serving"]
                     if s.get("fleet") == "fedclf"][0]
            assert entry["version"] == v2, entry
            reg = sdoc.get("registry", {}).get("fedclf", {})
            assert reg.get("current") == v2, reg

            tdoc = _get_json(bases[survivor] + "/traces")
            tagged = [t for t in tdoc.get("traces", ())
                      if t.get("rerouted_from_process") == f"p{victim}"
                      and t.get("outcome") == "ok"]
            assert tagged, "no survivor trace carries the reroute tag"

            # metrics federation: with traffic quiesced, the router's
            # federated counter aggregate must EQUAL the sum of the
            # live processes' own /status scrapes (the dead child's
            # series dropped — it contributes nothing)
            live_reqs = sdoc["counters"].get("serving_requests", 0)
            fed._poll_once()
            page = urllib.request.urlopen(
                ts.url + "/metrics", timeout=5.0).read().decode()
            fleet_reqs = None
            for line in page.splitlines():
                if line.startswith(
                        "dask_ml_tpu_fleet_serving_requests_total "):
                    fleet_reqs = int(float(line.split()[1]))
            assert fleet_reqs == live_reqs, (fleet_reqs, live_reqs)
            fleet_doc = _get_json(ts.url + "/status/fleet")
            assert fleet_doc["processes"] == [f"p{survivor}"], fleet_doc

            out.update(
                fleet_requests_total=fleet_reqs,
                requests=n_done, reroutes=reroutes,
                failovers=failovers, recompiles=recompiles,
                published=v2, survivor=f"p{survivor}",
                rerouted_traced=len(tagged),
            )
    finally:
        if ts is not None:
            ts.stop()
        for child in children:
            if child.poll() is None:
                child.terminate()
                try:
                    child.wait(10)
                except Exception:
                    child.kill()


def _autoscale_section(out):
    """A replayed burst whose top-bucket window predicts SLO pressure
    must ADD a replica (plans-warm, off the serving path) while the
    replay itself passes its SLO verdict."""
    from dask_ml_tpu import config
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import (
        BucketLadder,
        FleetServer,
        ReplicaAutoscaler,
        replay_load_test,
        synthesize_records,
    )

    X, y = make_classification(n_samples=600, n_features=12,
                               n_informative=6, random_state=0)
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    Xh = X.to_numpy().astype("float32")

    with config.set(serving_slo_ms=5000.0):
        fleet = FleetServer(a, name="fed-as", replicas=1,
                            ladder=BucketLadder(8, 128, 2.0),
                            batch_window_ms=1.0, timeout_ms=0).warmup()
        with fleet:
            # the recorded burst's story: yesterday's window showed the
            # top bucket running at 90% of the SLO — above the 80% up
            # band (scale), below the door (no shedding)
            r0 = fleet.replicas[0]
            for _ in range(50):
                r0._exec.observe("predict", fleet.ladder.max_rows, 4.5)
            scaler = ReplicaAutoscaler(fleet, min_replicas=1,
                                       max_replicas=2, interval_s=0.05,
                                       patience=2, cooldown_s=5.0)
            scaler.start()
            try:
                report = replay_load_test(
                    fleet, Xh,
                    records=synthesize_records(150, rows=(1, 64),
                                               rate_rps=300.0, seed=1),
                    slo_ms=5000.0, quantile=99.0,
                )
                deadline = time.time() + 20
                while not scaler.events and time.time() < deadline:
                    time.sleep(0.05)
            finally:
                scaler.stop()
            ups = [e for e in scaler.events if e[0] == "up"]
            assert ups, f"no scale-up fired: {scaler.events}"
            assert len(fleet.replicas) == 2, len(fleet.replicas)
            assert report["passed"], report
            assert report["error"] == 0 and report["timeout"] == 0, \
                report
            out.update(
                autoscale_replicas=len(fleet.replicas),
                scaleup_spinup_s=ups[0][2],
                loadtest={k: report[k] for k in
                          ("requests", "ok", "shed", "passed")},
                loadtest_p99_ms=report["latency_ms"]["p99"],
            )


def main():
    out = {"ok": False}
    try:
        _federation_section(out)
        _autoscale_section(out)
        out["ok"] = True
    except Exception as exc:
        out["ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
