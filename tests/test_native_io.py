"""Native loader tests (native/fast_loader.cpp via ctypes)."""

import numpy as np
import pytest

from dask_ml_tpu.io import load_library, read_csv_f32, read_csv_sharded


def test_native_library_builds():
    assert load_library() is not None, "g++ build of fast_loader failed"


def test_read_csv_matches_numpy(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 7).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.6f")
    got = read_csv_f32(str(p))
    ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_read_csv_multithreaded_consistent(tmp_path):
    rng = np.random.RandomState(1)
    X = rng.randn(5000, 3).astype(np.float32)
    p = tmp_path / "big.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.5f")
    a = read_csv_f32(str(p), n_threads=1)
    b = read_csv_f32(str(p), n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_read_csv_malformed(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1.0,2.0\n3.0\n")
    with pytest.raises(ValueError, match="malformed"):
        read_csv_f32(str(p))


def test_read_csv_missing():
    with pytest.raises(IOError):
        read_csv_f32("/nonexistent/file.csv")


def test_read_csv_sharded(tmp_path):
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    p = tmp_path / "s.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.1f")
    sx = read_csv_sharded(str(p))
    np.testing.assert_allclose(sx.to_numpy(), X)
