from .native import load_library, read_csv_f32, read_csv_sharded
