"""Federation plane: route one request stream over N fleet PROCESSES.

The FleetServer (PR 6) scales a model across replicas inside one
process — one GIL, one failure domain, one host's devices. This module
adds the layer above it, the production shape ROADMAP item 4 names:

- **endpoints** — a :class:`FleetEndpoint` per fleet process.
  :class:`LocalEndpoint` wraps an in-process :class:`FleetServer`
  (tests, the virtual-rank harness); :class:`HttpEndpoint` talks to a
  REMOTE process's live telemetry server (PR 5), whose new ``POST
  /fleet/<name>/<op>`` surface this module also implements
  (:func:`handle_http` — the live ``_Handler`` delegates to it);
- **predicted-completion routing** — a background poller caches every
  process's ``/status`` fleet block (queued rows, windowed exec
  quantiles, replica health); :meth:`FederatedFleet.submit` ranks live
  processes by :func:`~.policy.predict_completion_s` fed from
  :func:`~.policy.exec_from_snapshot` (the remote twin of the local
  predictor) and places the request on the fastest predicted finisher;
- **failover with zero lost admitted requests** — inference is
  idempotent, so a request in flight to a process that dies (SIGKILL,
  connection reset) is RE-ISSUED whole on the next-ranked process; the
  survivor's trace carries ``rerouted_from_process`` (the cross-process
  generalization of the fleet's ``rerouted_from`` tag, propagated over
  HTTP in the ``X-Fed-Reroute`` header) and the hop counts as
  ``serving_process_reroutes``. A dead process's gauge series are
  dropped (never latched) and it counts one
  ``serving_process_failovers``;
- **cross-process publish fan-out** — :meth:`FederatedFleet.publish`
  writes the router's CONTROL registry, then pushes the snapshot to
  every live process tagged with the control registry's version id and
  a monotonically increasing fan-out ``seq``. Each receiving fleet
  applies it through :func:`apply_publish`: stale seqs are dropped
  (last-writer-wins — back-to-back publishes converge every process to
  the control registry's CURRENT version no matter the arrival order)
  and the version id is PINNED into the local registry
  (``ModelRegistry.publish(version=...)``), so version NUMBERS agree
  fleet-wide and each process's ``_on_publish`` rolls its usual
  zero-recompile hot-swap.

Trust boundary: the publish op ships a pickled estimator — the same
trust level as the process boundary it crosses. The telemetry server
binds 127.0.0.1 by default; point HttpEndpoints only at processes you
already trust with code execution (a pickle IS code).
"""

from __future__ import annotations

import contextlib
import dataclasses
import http.client
import io
import json
import pickle
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from . import metrics as smetrics
from ._buckets import BucketLadder
from ._server import (
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    SloShed,
)
from .fleet import NoHealthyReplicas
from .policy import exec_from_snapshot, predict_completion_s
from .registry import ModelRegistry

__all__ = ["FederatedFleet", "FleetEndpoint", "LocalEndpoint",
           "HttpEndpoint", "ProcessDown", "NoLiveProcesses",
           "apply_publish", "handle_http"]


class ProcessDown(ServingError):
    """A fleet process stopped answering (connection refused/reset,
    status poll dead). The router fails the request over; the process
    rejoins routing when its status poll answers again."""


class NoLiveProcesses(ServingError):
    """Every federated process is down or refused this request — the
    federation twin of :class:`~.fleet.NoHealthyReplicas`."""


# -- wire helpers ------------------------------------------------------------

def _npy_bytes(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _npy_load(body: bytes):
    return np.load(io.BytesIO(body), allow_pickle=False)


def _fleet_entry(doc: dict, name: str, process_id: str) -> dict:
    """Extract one fleet's serving entry from a full /status doc —
    shared by :meth:`HttpEndpoint.status` and the router's poller (the
    poller fetches the DOC once and extracts locally, so the metrics
    federator rides the same scrape)."""
    for entry in doc.get("serving", ()):
        if entry.get("fleet") == name:
            return entry
    raise ProcessDown(
        f"{process_id}: no fleet {name!r} on /status"
    )


# -- endpoints ---------------------------------------------------------------

class FleetEndpoint:
    """One fleet process as the router sees it: a process id, a status
    probe, a blocking submit, and a publish-apply hook. Subclasses wrap
    an in-process FleetServer (:class:`LocalEndpoint`) or a remote
    process's HTTP surface (:class:`HttpEndpoint`)."""

    process_id: str = "?"

    def status(self) -> dict:
        """The process's fleet stats block (queue_rows, exec_s windows,
        replica health). Raises :class:`ProcessDown` when unreachable."""
        raise NotImplementedError

    def status_doc(self) -> dict:
        """The process's FULL /status document (serving block plus
        counters/telemetry) — fetched ONCE per poll interval so the
        routing poller and the metrics federator share a single scrape
        (a second reader of the windowed-quantile cursors would
        double-consume the deltas). Raises :class:`ProcessDown`."""
        return {"serving": [self.status()]}

    def submit(self, X, method="predict", rerouted_from=None,
               trace_ctx=None):
        """BLOCKING: place one request and return its result array.
        ``rerouted_from`` names the process this request failed over
        from — the receiving fleet tags the survivor's trace with it.
        ``trace_ctx`` carries the router's trace id so the remote
        process CONTINUES the same trace (pid-prefixed ids are
        collision-free fleet-wide)."""
        raise NotImplementedError

    def apply_publish(self, estimator, version, seq, tag=None,
                      quantize=None) -> bool:
        """Install one fanned-out publish (seq-guarded, version-pinned).
        Returns False when the seq was stale (already superseded)."""
        raise NotImplementedError

    def close(self):
        pass

    def __repr__(self):
        return f"{type(self).__name__}({self.process_id!r})"


class LocalEndpoint(FleetEndpoint):
    """An in-process :class:`FleetServer` as a federation endpoint —
    the virtual-rank test harness's transport (and the degenerate
    single-process federation)."""

    def __init__(self, fleet, process_id=None):
        self.fleet = fleet
        self.process_id = str(
            process_id if process_id is not None else f"local:{id(fleet)}"
        )

    def status(self) -> dict:
        try:
            if not self.fleet._started:
                raise ProcessDown(f"{self.process_id}: fleet stopped")
            return self.fleet.stats()
        except ProcessDown:
            raise
        except Exception as exc:
            raise ProcessDown(f"{self.process_id}: {exc}") from exc

    def status_doc(self) -> dict:
        # an in-process endpoint shares THIS process's registry —
        # shipping its counters/telemetry to the federator would
        # double-count them against the router's own /metrics, so the
        # doc carries only the serving block
        return {"serving": [self.status()], "counters": {},
                "telemetry": {"gauges": [], "histograms": []}}

    def submit(self, X, method="predict", rerouted_from=None,
               trace_ctx=None):
        import concurrent.futures as cf

        from ..config import get_config
        from ..observability import _requests as rtrace

        cfg = get_config()
        timeout_s = float(cfg.serving_federation_timeout_s)
        try:
            with contextlib.ExitStack() as stack:
                if rerouted_from is not None:
                    stack.enter_context(rtrace.tagging(
                        rerouted_from_process=rerouted_from))
                if trace_ctx is not None \
                        and bool(cfg.obs_trace_propagate):
                    # the in-process twin of the X-Trace-Context
                    # header: the fleet's _admit (synchronous, on this
                    # thread) mints its trace with the ROUTER's id
                    stack.enter_context(
                        rtrace.trace_context(trace_ctx))
                fut = self.fleet.submit(X, method=method)
            return fut.result(timeout_s if timeout_s > 0 else None)
        except (ServerClosed, NoHealthyReplicas) as exc:
            raise ProcessDown(f"{self.process_id}: {exc}") from exc
        except cf.TimeoutError:
            raise RequestTimeout(
                f"{self.process_id}: no result within "
                f"{timeout_s:.1f}s federation budget"
            ) from None

    def apply_publish(self, estimator, version, seq, tag=None,
                      quantize=None) -> bool:
        return apply_publish(self.fleet, estimator, version, seq,
                             tag=tag, quantize=quantize)


class HttpEndpoint(FleetEndpoint):
    """A REMOTE fleet process behind its live telemetry server: GETs
    ``/status`` for the poll plane and POSTs ``/fleet/<name>/<op>``
    (npy request/response bodies; pickle for publish — see the module
    trust note) for the request/publish planes."""

    def __init__(self, base_url, name="model", process_id=None,
                 timeout_s=None):
        from ..config import get_config

        self.base_url = str(base_url).rstrip("/")
        self.name = str(name)
        self.process_id = str(process_id if process_id is not None
                              else self.base_url)
        self.timeout_s = float(
            get_config().serving_federation_timeout_s
            if timeout_s is None else timeout_s
        )

    def _post(self, op, body, headers):
        req = urllib.request.Request(
            f"{self.base_url}/fleet/{self.name}/{op}", data=body,
            headers=headers, method="POST",
        )
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as exc:
            # typed serving errors ride HTTP status + X-Fed-Error; read
            # the body so the connection is reusable
            body = exc.read()
            return exc.code, body, dict(exc.headers or {})
        except (urllib.error.URLError, http.client.HTTPException,
                ConnectionError, OSError, TimeoutError) as exc:
            # IncompleteRead / RemoteDisconnected and friends are the
            # process dying mid-response — same failover as a refused
            # connection (inference is idempotent, re-issue is safe)
            raise ProcessDown(f"{self.process_id}: {exc}") from exc

    def status_doc(self) -> dict:
        try:
            with urllib.request.urlopen(f"{self.base_url}/status",
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, http.client.HTTPException,
                ConnectionError, OSError, TimeoutError,
                ValueError) as exc:
            raise ProcessDown(f"{self.process_id}: {exc}") from exc

    def status(self) -> dict:
        return _fleet_entry(self.status_doc(), self.name,
                            self.process_id)

    def submit(self, X, method="predict", rerouted_from=None,
               trace_ctx=None):
        from ..config import get_config

        headers = {"Content-Type": "application/x-npy"}
        if rerouted_from is not None:
            headers["X-Fed-Reroute"] = str(rerouted_from)
        if trace_ctx is not None \
                and bool(get_config().obs_trace_propagate):
            headers["X-Trace-Context"] = str(int(trace_ctx))
        code, body, rhead = self._post(method, _npy_bytes(X), headers)
        if code == 200:
            return _npy_load(body)
        kind = rhead.get("X-Fed-Error", "")
        msg = body.decode(errors="replace").strip() or f"HTTP {code}"
        if kind == "slo_shed":
            raise SloShed(f"{self.process_id}: {msg}")
        if kind == "overloaded":
            raise ServerOverloaded(f"{self.process_id}: {msg}")
        if kind == "timeout":
            raise RequestTimeout(f"{self.process_id}: {msg}")
        # closed / unknown fleet / anything else: this process cannot
        # take the request — fail over
        raise ProcessDown(f"{self.process_id}: {msg}")

    def apply_publish(self, estimator, version, seq, tag=None,
                      quantize=None) -> bool:
        headers = {
            "Content-Type": "application/x-pickle",
            "X-Fed-Version": str(int(version)),
            "X-Fed-Seq": str(int(seq)),
        }
        if tag is not None:
            headers["X-Fed-Tag"] = str(tag)
        if quantize is not None:
            headers["X-Fed-Quantize"] = str(quantize)
        code, body, _ = self._post("publish", pickle.dumps(estimator),
                                   headers)
        if code != 200:
            raise ProcessDown(
                f"{self.process_id}: publish failed: "
                f"{body.decode(errors='replace').strip()}"
            )
        return bool(json.loads(body.decode()).get("applied", False))


# -- receiving side ----------------------------------------------------------

# serializes fan-in applies per process: two fan-outs landing
# concurrently must check-and-advance the seq AND publish in one
# critical section, or the registry's current could regress to the
# stale one
_apply_lock = threading.Lock()


def apply_publish(fleet, estimator, version, seq, tag=None,
                  quantize=None) -> bool:
    """Install one fanned-out publish on a receiving fleet: drop stale
    seqs (last-writer-wins — the fan-out generalization of the fleet's
    ``_on_publish`` converge-to-current contract), pin the origin
    version id into the local registry, and let the fleet's own
    subscriber roll the zero-recompile hot-swap."""
    seq = int(seq)
    with _apply_lock:
        if seq <= getattr(fleet, "_fed_seq", 0):
            return False
        fleet._fed_seq = seq
        fleet.registry.publish(fleet.name, estimator, tag=tag,
                               quantize=quantize, version=int(version))
    return True


def _find_fleet(name):
    """The live-registered FleetServer carrying ``name`` in THIS
    process (fleet.start() registers it for /status; the federation
    POST surface reuses that same registration)."""
    from ..observability.live import _server_set

    for srv in list(_server_set()):
        if getattr(srv, "name", None) == name \
                and hasattr(srv, "replicas"):
            return srv
    return None


def handle_http(path, headers, body):
    """The ``POST /fleet/<name>/<op>`` handler the live telemetry
    server delegates to. Returns ``(code, body_bytes, content_type,
    extra_headers)``. Ops: a served method name (npy in, npy out) or
    ``publish`` (pickle in — module trust note applies). Typed serving
    errors map to status codes the :class:`HttpEndpoint` reverses:
    429 + ``X-Fed-Error: slo_shed|overloaded``, 503 closed/unknown,
    504 timeout."""
    from ..observability import _requests as rtrace

    parts = [p for p in path.split("/") if p]
    if len(parts) != 3 or parts[0] != "fleet":
        return (404, b"not found\n", "text/plain; charset=utf-8", {})
    _, name, op = parts
    fleet = _find_fleet(name)
    if fleet is None:
        return (503, f"no live fleet {name!r} in this process\n"
                .encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "unknown"})
    if op == "publish":
        try:
            est = pickle.loads(body)
            version = int(headers.get("X-Fed-Version", 0))
            seq = int(headers.get("X-Fed-Seq", 0))
        except Exception as exc:
            return (400, f"bad publish body: {exc}\n".encode(),
                    "text/plain; charset=utf-8", {})
        applied = apply_publish(
            fleet, est, version, seq,
            tag=headers.get("X-Fed-Tag"),
            quantize=headers.get("X-Fed-Quantize"),
        )
        out = json.dumps({"applied": applied,
                          "version": fleet.version}).encode() + b"\n"
        return (200, out, "application/json", {})
    try:
        X = _npy_load(body)
    except Exception as exc:
        return (400, f"bad npy body: {exc}\n".encode(),
                "text/plain; charset=utf-8", {})
    rerouted = headers.get("X-Fed-Reroute")
    from ..config import get_config

    cfg = get_config()
    trace_ctx = None
    if bool(cfg.obs_trace_propagate):
        try:
            trace_ctx = int(headers.get("X-Trace-Context", ""))
        except (TypeError, ValueError):
            trace_ctx = None
    try:
        with contextlib.ExitStack() as stack:
            if rerouted:
                # the survivor's trace records the process this request
                # failed over FROM (thread-local pending tag, picked up
                # by the replica's _admit)
                stack.enter_context(rtrace.tagging(
                    rerouted_from_process=rerouted))
            if trace_ctx is not None:
                # continue the ROUTER's trace: _admit runs on this
                # thread and mints the trace with the propagated id, so
                # the request is ONE trace across the process boundary
                stack.enter_context(rtrace.trace_context(trace_ctx))
            fut = fleet.submit(X, method=op)
        timeout_s = float(cfg.serving_federation_timeout_s)
        result = fut.result(timeout_s if timeout_s > 0 else None)
    except SloShed as exc:
        return (429, f"{exc}\n".encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "slo_shed"})
    except ServerOverloaded as exc:
        return (429, f"{exc}\n".encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "overloaded"})
    except (ServerClosed, NoHealthyReplicas) as exc:
        return (503, f"{exc}\n".encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "closed"})
    except RequestTimeout as exc:
        return (504, f"{exc}\n".encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "timeout"})
    except AttributeError:
        return (400, f"unknown method {op!r}\n".encode(),
                "text/plain; charset=utf-8", {})
    except Exception as exc:  # ServingError etc.
        return (500, f"{exc}\n".encode(), "text/plain; charset=utf-8",
                {"X-Fed-Error": "error"})
    return (200, _npy_bytes(result), "application/x-npy", {})


# -- the router --------------------------------------------------------------

class _ProcessState:
    __slots__ = ("endpoint", "alive", "stats", "doc", "t_status",
                 "t_dead")

    def __init__(self, endpoint):
        self.endpoint = endpoint
        self.alive = True       # optimistic: first poll corrects it
        self.stats = None
        self.doc = None         # last full /status doc (one scrape
        self.t_status = 0.0     # feeds routing AND the federator)
        self.t_dead = 0.0


class FederatedFleet:
    """Client-side router over N fleet processes.

    Parameters
    ----------
    endpoints : sequence of FleetEndpoint (or (url, process_id) strs)
        The fleet processes. Strings build :class:`HttpEndpoint`\\ s.
    name : str, the registry/fleet name every process serves
    ladder : BucketLadder, default from config — sizes the completion
        predictor's top bucket (must match the processes' ladders)
    poll_s / timeout_s / retry_s : floats, default
        ``config.serving_federation_*`` — status-poll period, per-call
        HTTP budget, dead-process re-probe period.

    Use as a context manager::

        with FederatedFleet([url0, url1], name="model") as fed:
            y = fed.predict(x)          # routed + failed over
            fed.publish(new_clf)        # fans out, converges versions
    """

    def __init__(self, endpoints, name="model", ladder=None,
                 registry=None, poll_s=None, timeout_s=None,
                 retry_s=None):
        from ..config import get_config

        cfg = get_config()
        self.name = str(name)
        eps = []
        for ep in endpoints:
            if isinstance(ep, FleetEndpoint):
                eps.append(ep)
            else:
                eps.append(HttpEndpoint(ep, name=self.name,
                                        timeout_s=timeout_s))
        if not eps:
            raise ValueError("FederatedFleet needs >= 1 endpoint")
        self._procs = [_ProcessState(ep) for ep in eps]
        self.ladder = ladder if ladder is not None \
            else BucketLadder.from_config()
        # the CONTROL registry: the fan-out's source of truth for
        # version ids (pinned into every process's local registry)
        self.registry = registry if registry is not None \
            else ModelRegistry()
        self._poll_s = float(cfg.serving_federation_poll_s
                             if poll_s is None else poll_s)
        self._retry_s = float(cfg.serving_federation_retry_s
                              if retry_s is None else retry_s)
        self._pub_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._poller = None
        self._pool = None
        # fleet metrics federation rides the status poller (never its
        # own thread, never its own scrape); off by default — disabled
        # builds nothing and registers nothing (zero-overhead contract)
        self._federator = None
        if bool(cfg.obs_fleet_federate):
            from ..observability.fleet import MetricsFederator

            self._federator = MetricsFederator(
                name=self.name,
                slo_ms=float(cfg.serving_slo_ms),
                min_interval_s=float(cfg.obs_fleet_poll_s),
            )

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        import concurrent.futures as cf

        if self._pool is None:
            self._pool = cf.ThreadPoolExecutor(
                max_workers=max(4, 2 * len(self._procs)),
                thread_name_prefix="fed-submit",
            )
        self._stop.clear()
        if self._federator is not None:
            from ..observability import live

            live.register_fleet_provider(self._federator)
        self._poll_once()
        if self._poller is None:
            self._poller = threading.Thread(
                target=self._poll_loop, name="fed-poller", daemon=True,
            )
            self._poller.start()
        return self

    def stop(self):
        self._stop.set()
        if self._poller is not None:
            self._poller.join(5.0)
            self._poller = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._federator is not None:
            from ..observability import live

            live.unregister_fleet_provider(self._federator)
        for p in self._procs:
            try:
                p.endpoint.close()
            except Exception:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    # -- poll plane --------------------------------------------------------
    def _poll_loop(self):
        while not self._stop.wait(self._poll_s):
            try:
                self._poll_once()
            except Exception:
                pass

    def _poll_once(self):
        now = time.monotonic()
        t0 = time.perf_counter()
        snapshots = []
        for p in self._procs:
            pid = p.endpoint.process_id
            if not p.alive and now - p.t_dead < self._retry_s:
                # back off re-probing a known-dead process (its fleet
                # series still DROP this interval, never latch)
                snapshots.append((pid, None))
                continue
            try:
                # ONE scrape per process per interval: the full doc
                # feeds routing (serving entry, extracted here) and the
                # metrics federator (counters + telemetry) — a second
                # GET would double-consume the windowed-quantile
                # cursors behind srv.stats()
                doc = p.endpoint.status_doc()
                stats = _fleet_entry(doc, self.name, pid)
            except ProcessDown:
                self._mark_dead(p)
                snapshots.append((pid, None))
                continue
            with self._lock:
                back = not p.alive
                p.alive = True
                p.stats = stats
                p.doc = doc
                p.t_status = time.monotonic()
            smetrics.set_process_gauges(
                pid, healthy=True,
                replicas=stats.get("healthy_replicas"),
            )
            snapshots.append((pid, doc))
            if back:
                # a recovered process rejoins routing; its registry
                # re-converges on the next publish fan-out
                pass
        if self._federator is not None:
            self._federator.ingest(
                snapshots, scrape_s=time.perf_counter() - t0)

    def _mark_dead(self, p):
        with self._lock:
            was_alive = p.alive
            p.alive = False
            p.t_dead = time.monotonic()
            p.stats = None
        if was_alive:
            smetrics.record_process_failover()
            # never latch a dead process's gauge series on /metrics
            smetrics.drop_process_gauges(p.endpoint.process_id)

    # -- request plane -----------------------------------------------------
    def _ranked(self, method, n_rows):
        """Live processes ordered by predicted completion (unknown
        predictions — cold windows — rank AFTER known-fast ones but
        still receive traffic via queue_rows tiebreak)."""
        with self._lock:
            live = [p for p in self._procs if p.alive]
        scored = []
        for p in live:
            stats = p.stats or {}
            queue_rows = int(stats.get("queue_rows", 0) or 0)
            exec_s = None
            for rep in stats.get("replicas", ()):
                v = exec_from_snapshot(rep.get("exec_s"), method,
                                       self.ladder.max_rows)
                if v is not None and (exec_s is None or v < exec_s):
                    exec_s = v
            predicted = predict_completion_s(
                queue_rows, n_rows, self.ladder.max_rows, exec_s)
            scored.append((predicted if predicted is not None
                           else float("inf"), queue_rows, p))
        scored.sort(key=lambda t: (t[0], t[1],
                                   t[2].endpoint.process_id))
        return [p for _, _, p in scored]

    def _route(self, X, method, n_rows, tr=None):
        ranked = self._ranked(method, n_rows)
        if not ranked:
            raise NoLiveProcesses(
                f"0/{len(self._procs)} federated processes live"
            )
        last_exc = None
        rerouted_from = None
        for p in ranked:
            if tr is not None:
                # one dispatch stamp per placement attempt: a rerouted
                # request's router trace telescopes every leg
                tr.stamp("dispatch")
                tr.tag(process=p.endpoint.process_id)
            try:
                return p.endpoint.submit(
                    X, method=method, rerouted_from=rerouted_from,
                    trace_ctx=tr.trace_id if tr is not None else None,
                )
            except ProcessDown as exc:
                # the process died under this request (or refused it as
                # closed): inference is idempotent, so the WHOLE request
                # re-issues on the next-ranked survivor — this retry is
                # the zero-lost-admitted-requests mechanism
                last_exc = exc
                self._mark_dead(p)
                smetrics.record_process_reroute()
                rerouted_from = p.endpoint.process_id
                if tr is not None:
                    tr.tag(rerouted_from_process=rerouted_from)
            except ServerOverloaded as exc:
                last_exc = exc
                smetrics.record_process_reroute()
                rerouted_from = p.endpoint.process_id
                if tr is not None:
                    tr.tag(rerouted_from_process=rerouted_from)
            # SloShed / RequestTimeout propagate: admission refused the
            # request deliberately (re-issuing would double-spend its
            # budget), and a timeout already burned it
        if isinstance(last_exc, ProcessDown):
            raise NoLiveProcesses(
                f"every federated process refused this request; "
                f"last: {last_exc}"
            ) from last_exc
        raise last_exc

    def _run_request(self, X, method, tr=None, cfg=None):
        if tr is None:
            return self._route(X, method,
                               1 if X.ndim == 1 else int(X.shape[0]))
        from .. import config

        n_rows = 1 if X.ndim == 1 else int(X.shape[0])
        # config overrides are thread-local: re-apply the SUBMIT
        # caller's config on this pool thread (the ModelServer worker
        # idiom) so tr.finish() samples/keeps per the caller's knobs
        with config.set(**dataclasses.asdict(cfg)):
            try:
                result = self._route(X, method, n_rows, tr=tr)
            except SloShed:
                tr.tag(slo_shed=True)
                tr.finish("slo_shed")
                raise
            except RequestTimeout:
                tr.finish("timeout")
                raise
            except Exception:
                tr.finish("error")
                raise
            tr.finish("ok")
            return result

    def submit(self, X, method="predict"):
        """Admit one request to the federation: returns a Future
        resolving to the result array (routing, failover and reroute
        tagging happen on the router's worker thread). With request
        tracing on, the router mints the trace HERE (caller thread, so
        thread-local tag/config context applies) and every process the
        request touches continues the same trace id."""
        if self._pool is None:
            raise ServerClosed("FederatedFleet is not started")
        from ..observability import _requests as rtrace

        X = np.asarray(X, np.float32)
        tr = cfg = None
        if rtrace.tracing_enabled():
            from ..config import get_config

            tr = rtrace.new_trace(
                method, 1 if X.ndim == 1 else int(X.shape[0]))
            tr.tag(federation=self.name)
            cfg = get_config()
        return self._pool.submit(self._run_request, X, method, tr, cfg)

    def _call(self, X, method):
        return self.submit(X, method=method).result()

    def predict(self, X):
        return self._call(X, "predict")

    def predict_proba(self, X):
        return self._call(X, "predict_proba")

    def decision_function(self, X):
        return self._call(X, "decision_function")

    def transform(self, X):
        return self._call(X, "transform")

    # -- publish plane -----------------------------------------------------
    def publish(self, estimator, tag=None, quantize=None) -> int:
        """Publish to the control registry and fan the snapshot out to
        every live process (version-pinned + seq-guarded — see
        :func:`apply_publish`). Returns the control version id. Dead
        processes are skipped; they re-converge on their next publish
        after recovery."""
        version = self.registry.publish(self.name, estimator, tag=tag,
                                        quantize=quantize)
        self._fan_out()
        return version

    def _fan_out(self):
        """Push the control registry's CURRENT version to every live
        process. Re-reading current under the seq lock (instead of
        shipping the version a caller just published) is what makes
        back-to-back publishes converge: a slow fan-out thread pushes
        the NEWEST version with the NEWEST seq, never resurrects its
        own stale one."""
        with self._lock:
            try:
                mv = self.registry.get(self.name)
            except KeyError:
                return
            self._pub_seq += 1
            seq = self._pub_seq
            live = [p for p in self._procs if p.alive]
        smetrics.record_federation_publish()
        for p in live:
            try:
                p.endpoint.apply_publish(
                    mv.estimator, mv.version, seq, tag=mv.tag,
                    quantize=getattr(mv, "quantize", None),
                )
            except ProcessDown:
                self._mark_dead(p)

    def rollback(self, version=None) -> int:
        """Roll the control registry back and fan the re-pointed
        version out (a rollback IS a publish on the wire: the archived
        snapshot ships with its ORIGINAL pinned version id under a
        fresh seq)."""
        v = self.registry.rollback(self.name, version=version)
        self._fan_out()
        return v

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        """The router's live view: per-process alive/queue/staleness —
        the /status ``federation`` shape scripts assert on."""
        with self._lock:
            procs = [{
                "process": p.endpoint.process_id,
                "alive": p.alive,
                "status_age_s": round(time.monotonic() - p.t_status, 3)
                if p.t_status else None,
                "queue_rows": int((p.stats or {}).get("queue_rows", 0)
                                  or 0),
                "version": (p.stats or {}).get("version"),
                "healthy_replicas": (p.stats or {})
                .get("healthy_replicas"),
            } for p in self._procs]
        return {
            "federation": self.name,
            "n_processes": len(procs),
            "live_processes": sum(1 for p in procs if p["alive"]),
            "processes": procs,
        }
