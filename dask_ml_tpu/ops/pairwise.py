"""Pairwise distance / kernel primitives.

Reference equivalent: ``dask_ml/metrics/pairwise.py``, which maps
sklearn's Cython ``pairwise_distances_argmin_min`` over blocks (SURVEY.md
§3.1). TPU design: one fused XLA expression — the ``x @ y.T`` term rides the
MXU, the norm/argmin epilogue fuses into it, so the "distance + argmin"
pattern the reference pays a Cython call per block for becomes a single
compiled kernel over the whole sharded array.

``y`` (centers / anchor points) is small and replicated; ``x`` may be the
padded row-sharded data — callers mask invalid rows on the results.
"""

from __future__ import annotations

import jax.numpy as jnp


def row_norms_sq(x):
    return jnp.sum(x * x, axis=-1)


def euclidean_distances_sq(x, y):
    """Squared euclidean distances (n, m) via the MXU-friendly expansion
    ||x||^2 - 2 x.y + ||y||^2, clamped at 0 against cancellation."""
    d2 = (
        row_norms_sq(x)[:, None]
        - 2.0 * (x @ y.T)
        + row_norms_sq(y)[None, :]
    )
    return jnp.maximum(d2, 0.0)


def euclidean_distances(x, y):
    return jnp.sqrt(euclidean_distances_sq(x, y))


def pairwise_distances_argmin_min(x, y):
    """(labels, min_dists) of nearest row of y for each row of x.

    The KMeans hot kernel (SURVEY.md §3.1 🔥): distances + argmin fuse into
    one program instead of the reference's per-block Cython call.
    """
    d2 = euclidean_distances_sq(x, y)
    labels = jnp.argmin(d2, axis=1)
    return labels, jnp.sqrt(jnp.min(d2, axis=1))


def linear_kernel(x, y):
    return x @ y.T


def rbf_kernel(x, y, gamma=None):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return jnp.exp(-gamma * euclidean_distances_sq(x, y))


def polynomial_kernel(x, y, degree=3, gamma=None, coef0=1.0):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return (gamma * (x @ y.T) + coef0) ** degree


def sigmoid_kernel(x, y, gamma=None, coef0=1.0):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return jnp.tanh(gamma * (x @ y.T) + coef0)
