"""XGBoost bridge.

Reference: ``dask_ml/xgboost.py`` (SURVEY.md §2a xgboost row) — a thin
re-export of dask-xgboost's train/predict and sklearn wrappers, later
deprecated upstream in favor of ``xgboost.dask``. xgboost is not in this
image, so the bridge is gated: importing the module works; using any
symbol raises with the upstream guidance.
"""


def __getattr__(name):
    if name in ("train", "predict", "XGBClassifier", "XGBRegressor"):
        raise ImportError(
            f"dask_ml_tpu.xgboost.{name} requires the 'xgboost' package, "
            "which is not installed in this environment. Upstream dask-ml "
            "deprecated this bridge in favor of xgboost's native "
            "distributed API; use that with jax arrays via DMatrix."
        )
    raise AttributeError(name)
