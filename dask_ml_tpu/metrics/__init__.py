"""Ref: dask_ml/metrics/__init__.py."""
from .classification import (UndefinedMetricWarning, accuracy_score,
                             average_precision_score,
                             balanced_accuracy_score, confusion_matrix,
                             f1_score, log_loss,
                             precision_recall_curve, precision_score,
                             recall_score, roc_auc_score, roc_curve)
from .regression import (explained_variance_score, max_error,
                         mean_absolute_error, mean_squared_error,
                         mean_squared_log_error, median_absolute_error,
                         r2_score)
from .pairwise import (cosine_distances, euclidean_distances,
                       linear_kernel, manhattan_distances,
                       pairwise_distances, pairwise_distances_argmin,
                       pairwise_distances_argmin_min,
                       pairwise_kernels, polynomial_kernel, rbf_kernel,
                       sigmoid_kernel)
from .scorer import SCORERS, check_scoring, get_scorer
