"""Multi-host runtime tests (SURVEY.md §5 distributed-comm row).

Single-process paths run in-process; the REAL 2-process bring-up
(jax.distributed.initialize + cross-process collective over the gloo/DCN
control plane) runs in subprocesses — the analog of the reference's
``gen_cluster`` in-process scheduler+workers, but with actual separate
processes. Fault injection: one worker is killed and the survivor's
checkpoint-restart path is exercised (SURVEY.md §5 failure row)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_process_runtime():
    from dask_ml_tpu.parallel import distributed as dist

    dist.initialize()  # no coordinator configured -> single-process no-op
    assert dist.process_count() == 1
    assert dist.process_index() == 0
    assert dist.is_coordinator()
    assert dist.barrier() == float(len(__import__("jax").devices()))
    out = dist.broadcast_host(np.arange(3.0))
    np.testing.assert_array_equal(out, np.arange(3.0))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    from dask_ml_tpu.parallel import distributed as dist
    # global mesh spans both processes' devices
    mesh = dist.global_mesh()
    assert mesh.shape["data"] == 2 * nproc, mesh.shape
    # cross-process collective: barrier psum over every device
    total = dist.barrier()
    assert total == 2 * nproc, total
    # control-plane broadcast from the coordinator
    val = np.array([42.0, 7.0]) if dist.is_coordinator() else np.zeros(2)
    got = dist.broadcast_host(val)
    assert np.allclose(got, [42.0, 7.0]), got
    print("proc", pid, "OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_collectives(tmp_path):
    """Real 2-process jax.distributed bring-up: global mesh, psum barrier,
    coordinator broadcast."""
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=REPO), str(i), "2", port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} OK" in out
    finally:
        for p in procs:  # no orphans on timeout/assert failure
            if p.poll() is None:
                p.kill()


_DYING_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    if pid == 1:
        # fault injection: worker 1 dies before joining the runtime
        sys.exit(17)
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid,
        initialization_timeout=15)
    print("unexpected success", flush=True)
    sys.exit(3)
""")


@pytest.mark.slow
def test_worker_death_detected(tmp_path):
    """Fault injection: a worker dies during bring-up. The survivor's
    coordination service DETECTS the loss (deadline heartbeat) and
    terminates the process — the SPMD whole-slice failure mode whose
    recovery path is checkpoint-restart (utils/checkpoint.py), not
    dask-style lineage recompute (SURVEY.md §5 failure row)."""
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _DYING_WORKER.format(repo=REPO), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        out0, _ = procs[0].communicate(timeout=120)
        procs[1].communicate(timeout=30)
        assert procs[1].returncode == 17  # the injected death
        # survivor must NOT hang or report success: it terminates after
        # detecting the dead peer (abort or raised deadline error)
        assert procs[0].returncode != 3, out0
        assert "Deadline" in out0 or "DEADLINE" in out0 or "died" in out0, out0
    finally:
        for p in procs:  # no orphans on timeout/assert failure
            if p.poll() is None:
                p.kill()
