"""Versioned model registry: the serving fleet's source of truth for
WHAT is being served.

``dask-ml``'s serving story froze one fitted estimator into
``ParallelPostFit``; a production fleet needs the model to be a NAMED,
VERSIONED, swappable thing: training publishes snapshots, serving
subscribes and hot-swaps, an operator rolls back a bad push — all
without restarting (or recompiling) anything.

- :meth:`ModelRegistry.publish` stores a deep-copied snapshot of a
  fitted estimator under ``(name, version)`` and notifies subscribers
  (the FleetServer's swap hook). Snapshotting matters for
  serve-while-training: ``Incremental``'s ``partial_fit`` keeps
  mutating its estimator between passes, and an un-copied publish would
  retroactively rewrite every archived version (and break rollback).
- :meth:`ModelRegistry.rollback` re-points ``current`` at an archived
  version and notifies subscribers the same way — a rollback IS a swap.
- History is bounded (``config.serving_registry_keep``); the current
  version is never evicted.

Thread-safe; subscriber callbacks run on the publishing thread, outside
the registry lock (a slow swap must not block concurrent gets), and a
callback error never poisons the publish — it is recorded per
subscriber and re-raised to the publisher AFTER every subscriber ran.
"""

from __future__ import annotations

import copy
import threading
import time

from . import metrics as smetrics

__all__ = ["ModelRegistry", "ModelVersion", "RegistryError",
           "UnknownModelError"]


class RegistryError(KeyError):
    """Base class for registry lookup failures."""


class UnknownModelError(RegistryError):
    """No such model name / version in this registry."""


class ModelVersion:
    """One immutable published snapshot: ``estimator`` plus identity,
    the publisher (thread name unless given — the /status registry
    block's audit field), and the estimator's per-feature training
    profile (``training_profile_``, see observability/sketch.py) so a
    version's drift baseline is archived WITH the version — rollback
    restores the matching baseline, not the current one's."""

    __slots__ = ("name", "version", "estimator", "t_publish", "tag",
                 "publisher", "profile", "quantize")

    def __init__(self, name, version, estimator, tag=None,
                 publisher=None, quantize=None):
        self.name = name
        self.version = int(version)
        self.estimator = estimator
        self.t_publish = time.time()
        self.tag = tag
        self.publisher = str(publisher) if publisher is not None \
            else threading.current_thread().name
        self.profile = getattr(estimator, "training_profile_", None)
        # serving precision flavor for THIS version (None = float32,
        # "int8" = weight-quantized entry points): subscribers
        # (ModelServer/FleetServer swap hooks) serve the version
        # through the matching pre-warmed flavor, so flipping a model
        # f32 <-> int8 is an ordinary zero-recompile hot-swap
        self.quantize = quantize

    def __repr__(self):
        tag = f", tag={self.tag!r}" if self.tag else ""
        return f"ModelVersion({self.name!r}, v{self.version}{tag})"


class ModelRegistry:
    """Named, versioned fitted-model store with publish/rollback
    notification.

    ``keep`` bounds per-name history (default from
    ``config.serving_registry_keep``); versions number from 1 and never
    reuse ids — a rollback re-points ``current`` without minting a new
    version, so audit trails stay monotonic.
    """

    def __init__(self, keep=None):
        from ..config import get_config

        self.keep = int(get_config().serving_registry_keep
                        if keep is None else keep)
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1, got {self.keep}")
        self._lock = threading.Lock()
        self._models: dict[str, dict[int, ModelVersion]] = {}
        self._current: dict[str, int] = {}
        self._next: dict[str, int] = {}
        self._subs: dict[str, list] = {}
        # list this registry on /status (weakly referenced — a dropped
        # registry disappears from the page with no unregister call)
        from ..observability.live import register_registry

        register_registry(self)

    # -- write plane -------------------------------------------------------
    def publish(self, name, estimator, tag=None, snapshot=True,
                publisher=None, quantize=None, version=None) -> int:
        """Store ``estimator`` as the next version of ``name``, make it
        current, notify subscribers. Returns the new version id.
        ``publisher`` labels the version on /status (defaults to the
        publishing thread's name). ``quantize="int8"`` flags the
        version for the weight-quantized serving flavor — subscribers
        swap it in through their pre-warmed int8 entry points
        (per-channel scales are computed at swap time from this
        snapshot's weights).

        ``version`` PINS the version id instead of minting the next one
        — the federation plane's cross-process convergence hook: a
        publish fanned out from another process carries the ORIGIN
        registry's id, and pinning it here makes version numbers agree
        fleet-wide (re-publishing an id this registry already holds
        overwrites that slot — replays of the same fan-out are
        idempotent, not version-inflating). The local counter advances
        past any pinned id so local publishes never collide with it.

        ``snapshot=True`` (default) deep-copies the estimator so later
        in-place training (``partial_fit``) cannot mutate the archive;
        pass False only for estimators the caller promises never to
        touch again."""
        est = copy.deepcopy(estimator) if snapshot else estimator
        with self._lock:
            if version is None:
                version = self._next.get(name, 1)
            else:
                version = int(version)
                if version < 1:
                    raise ValueError(
                        f"pinned version must be >= 1, got {version}")
            self._next[name] = max(self._next.get(name, 1),
                                   version + 1)
            mv = ModelVersion(name, version, est, tag=tag,
                              publisher=publisher, quantize=quantize)
            versions = self._models.setdefault(name, {})
            versions[version] = mv
            self._current[name] = version
            self._evict_locked(name)
            subs = list(self._subs.get(name, ()))
        smetrics.record_publish()
        self._publish_gauge(name, version)
        self._notify(subs, mv)
        return version

    def rollback(self, name, version=None) -> int:
        """Re-point ``name``'s current at an ARCHIVED version (default:
        the one just before current) and notify subscribers — the
        operator's bad-push escape hatch. Returns the now-current
        version id."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise UnknownModelError(name)
            cur = self._current[name]
            if version is None:
                older = [v for v in sorted(versions) if v < cur]
                if not older:
                    raise UnknownModelError(
                        f"{name}: no version older than current v{cur} "
                        "to roll back to"
                    )
                version = older[-1]
            version = int(version)
            if version not in versions:
                raise UnknownModelError(
                    f"{name}: version {version} not in registry "
                    f"(kept: {sorted(versions)})"
                )
            self._current[name] = version
            mv = versions[version]
            subs = list(self._subs.get(name, ()))
        smetrics.record_publish(rollback=True)
        self._publish_gauge(name, version)
        self._notify(subs, mv)
        return version

    def _evict_locked(self, name):
        versions = self._models[name]
        cur = self._current[name]
        for v in sorted(versions):
            if len(versions) <= self.keep:
                break
            if v != cur:
                del versions[v]

    @staticmethod
    def _publish_gauge(name, version):
        from ..observability.live import gauge_set, live_publishing

        if live_publishing():
            gauge_set("registry_version", int(version),
                      (("model", str(name)),))

    def _notify(self, subs, mv):
        first_exc = None
        for cb in subs:
            try:
                cb(mv)
            except Exception as exc:  # every subscriber still runs
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc

    # -- read plane --------------------------------------------------------
    def get(self, name, version=None) -> ModelVersion:
        """The current (or an explicit archived) version of ``name``."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise UnknownModelError(name)
            v = self._current[name] if version is None else int(version)
            mv = versions.get(v)
            if mv is None:
                raise UnknownModelError(
                    f"{name}: version {v} not in registry "
                    f"(kept: {sorted(versions)})"
                )
            return mv

    def current_version(self, name) -> int:
        with self._lock:
            if name not in self._current:
                raise UnknownModelError(name)
            return self._current[name]

    def versions(self, name) -> tuple:
        """Kept version ids for ``name`` (ascending); empty tuple for an
        unknown name."""
        with self._lock:
            return tuple(sorted(self._models.get(name, ())))

    def names(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._models))

    def status_snapshot(self) -> dict:
        """{name: {current, versions, t_publish, publisher, tag}} — the
        /status ``registry`` block: what is serving, what is archived,
        who pushed it and when, without instrumenting application
        code."""
        out = {}
        with self._lock:
            for name, versions in self._models.items():
                cur = self._current.get(name)
                mv = versions.get(cur)
                out[name] = {
                    "current": cur,
                    "versions": sorted(versions),
                    "t_publish": round(mv.t_publish, 3) if mv else None,
                    "publisher": mv.publisher if mv else None,
                    "tag": mv.tag if mv else None,
                    "quantize": mv.quantize if mv else None,
                }
        return out

    # -- subscription ------------------------------------------------------
    def subscribe(self, name, callback):
        """``callback(ModelVersion)`` fires on every publish/rollback of
        ``name`` — how a fleet follows a model. If the name already has
        a current version the callback fires immediately with it (a
        late-joining fleet must not serve stale params until the next
        publish)."""
        with self._lock:
            self._subs.setdefault(name, []).append(callback)
            cur = self._current.get(name)
            mv = self._models[name][cur] if cur is not None else None
        if mv is not None:
            callback(mv)
        return callback

    def unsubscribe(self, name, callback):
        with self._lock:
            subs = self._subs.get(name, [])
            if callback in subs:
                subs.remove(callback)
