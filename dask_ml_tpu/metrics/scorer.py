"""Dask-aware scorers. Ref: ``dask_ml/metrics/scorer.py`` (SURVEY.md §2a
Metrics row): SCORERS / get_scorer / check_scoring working on sharded
inputs."""

from __future__ import annotations

from .classification import accuracy_score, log_loss
from .regression import (
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)


def _make_scorer(metric, greater_is_better=True, needs_proba=False):
    sign = 1.0 if greater_is_better else -1.0

    def scorer(estimator, X, y):
        pred = (estimator.predict_proba(X) if needs_proba
                else estimator.predict(X))
        return sign * metric(y, pred)

    return scorer


SCORERS = {
    "accuracy": _make_scorer(accuracy_score),
    "neg_mean_squared_error": _make_scorer(mean_squared_error,
                                           greater_is_better=False),
    "neg_mean_absolute_error": _make_scorer(mean_absolute_error,
                                            greater_is_better=False),
    "neg_log_loss": _make_scorer(log_loss, greater_is_better=False,
                                 needs_proba=True),
    "r2": _make_scorer(r2_score),
}


def get_scorer(scoring, compute=True):
    if callable(scoring):
        return scoring
    try:
        return SCORERS[scoring]
    except KeyError:
        raise ValueError(
            f"{scoring!r} is not a valid scoring value; options: "
            f"{sorted(SCORERS)}"
        )


def check_scoring(estimator, scoring=None, **kwargs):
    if scoring is None:
        if not hasattr(estimator, "score"):
            raise TypeError(
                f"estimator {estimator!r} has no score method; pass scoring="
            )
        return lambda est, X, y: est.score(X, y)
    return get_scorer(scoring)
