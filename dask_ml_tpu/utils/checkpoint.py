"""Checkpoint / resume.

Reference: **none** — dask-ml keeps search models as in-memory futures and
a killed search restarts from scratch (SURVEY.md §5 checkpoint row).
Built anyway, deliberately exceeding the reference: TPU slices fail whole
(no lineage recompute), so recovery = checkpoint-restart at iteration
granularity for solvers and trial granularity for searches.

Device pytrees go through orbax; host objects (sklearn estimators inside
wrappers/searches) go through pickle in the same directory.
"""

from __future__ import annotations

import os
import pickle

import jax
import numpy as np


def _orbax():
    import orbax.checkpoint as ocp

    return ocp


def save_pytree(path, tree, force=True):
    """Save a jax pytree (solver/optimizer state) with orbax."""
    ocp = _orbax()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=force)


def restore_pytree(path, like=None):
    ocp = _orbax()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(path, like)
        return ckptr.restore(path)


def save_host(path, obj):
    """Pickle host-side state (search history, sklearn models)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(obj, f)


def restore_host(path):
    with open(path, "rb") as f:
        return pickle.load(f)


class SearchCheckpoint:
    """Controller-state persistence for adaptive searches: history,
    per-model metadata, and model states, written every round so a killed
    search resumes at trial granularity (SURVEY.md §5 failure row)."""

    def __init__(self, directory):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_round(self, round_idx, history, meta, models, extra=None):
        state = {
            "round": round_idx,
            "history": history,
            "meta": meta,
            "models": models,
        }
        if extra:
            state.update(extra)
        save_host(self._path("controller.pkl"), state)

    def load(self):
        p = self._path("controller.pkl")
        if not os.path.exists(p):
            return None
        return restore_host(p)

    def clear(self):
        """Remove the controller state — called on successful completion so
        a finished search is never resumed into a new one."""
        p = self._path("controller.pkl")
        if os.path.exists(p):
            os.remove(p)
