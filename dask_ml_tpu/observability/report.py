"""Run-report CLI: aggregate a recorded JSONL metrics/trace file into a
per-component summary.

Usage::

    python -m dask_ml_tpu.observability.report metrics.jsonl

Reads the records the subsystem emits — span records (``span`` field),
per-step solver/search records (``component`` field), stream-pass
overlap records (``stream_pass``), and counter snapshots (``counters``)
— and prints: time per span (wall + device-sync), samples/s where a
span recorded its row count, each component's convergence trajectory
(first→last loss-like metric and step count), streaming overlap totals,
and the run's counter totals (recompiles, host↔device bytes). The point
(ISSUE 1): a BENCH round's JSONL answers "where did this fit spend its
time" without re-running anything.
"""

from __future__ import annotations

import json
import sys

# the metric each component's convergence trajectory is read from, in
# preference order (first key present in its step records wins)
_LOSS_KEYS = ("loss", "inertia", "center_shift2", "primal_residual",
              "score", "opt_residual", "grad_norm")


def load_records(path):
    """Parse a JSONL file, skipping blank/corrupt lines (a crashed run
    may truncate its last line — the report must still read the rest)."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
    return records


def _fmt_seconds(s):
    return f"{s:.3f}s"


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _table(title, headers, rows):
    if not rows:
        return []
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [title, fmt.format(*headers),
           fmt.format(*("-" * w for w in widths))]
    out.extend(fmt.format(*(str(c) for c in r)) for r in rows)
    out.append("")
    return out


def summarize_spans(records):
    """[(key, count, wall, sync, samples/s or None)] grouped by
    (span name, component)."""
    groups = {}
    for r in records:
        if "span" not in r:
            continue
        key = r["span"]
        if r.get("component"):
            key = f"{r['component']}.{key}"
        g = groups.setdefault(key, {"n": 0, "wall": 0.0, "sync": 0.0,
                                    "rows": 0.0})
        g["n"] += 1
        g["wall"] += float(r.get("wall_s", 0.0))
        g["sync"] += float(r.get("sync_s", 0.0))
        g["rows"] += float(r.get("n_rows", 0.0))
    out = []
    for key in sorted(groups, key=lambda k: -groups[k]["wall"]):
        g = groups[key]
        sps = g["rows"] / g["wall"] if g["rows"] and g["wall"] > 0 else None
        out.append((key, g["n"], g["wall"], g["sync"], sps))
    return out


def summarize_components(records):
    """Per-component step telemetry: record count, steps, convergence
    trajectory (first → last of the component's loss-like metric)."""
    comps = {}
    for r in records:
        if "span" in r or "component" not in r:
            continue
        c = comps.setdefault(r["component"], {"n": 0, "steps": set(),
                                              "key": None, "first": None,
                                              "last": None})
        c["n"] += 1
        if r.get("step") is not None:
            c["steps"].add(r["step"])
        if c["key"] is None:
            for k in _LOSS_KEYS:
                if k in r:
                    c["key"] = k
                    break
        k = c["key"]
        if k is not None and k in r:
            if c["first"] is None:
                c["first"] = float(r[k])
            c["last"] = float(r[k])
    out = []
    for name in sorted(comps):
        c = comps[name]
        traj = "-"
        if c["key"] is not None and c["first"] is not None:
            traj = f"{c['key']}: {c['first']:.6g} -> {c['last']:.6g}"
        out.append((name, c["n"], len(c["steps"]), traj))
    return out


def summarize_stream(records):
    """Streaming-pass overlap totals (from BlockStream's per-pass
    records): the double-buffer health check, plus the super-block
    dispatch amortization — a per-block pass costs one dispatch per
    block, a super-block pass one per K blocks, so dispatches/blocks
    shows the measured collapse."""
    passes = [r for r in records if "stream_pass" in r]
    if not passes:
        return None
    tot = {k: sum(float(p.get(k, 0.0)) for p in passes)
           for k in ("host_s", "put_s", "wait_s", "consume_s", "pass_s")}
    tot["n_passes"] = len(passes)
    tot["n_blocks"] = sum(int(p.get("n_blocks", 0)) for p in passes)
    # per-block passes dispatch once per block; super-block passes
    # record their own (smaller) dispatch count
    tot["dispatches"] = sum(
        int(p.get("dispatches", p.get("n_blocks", 0))) for p in passes
    )
    sb = [int(p["superblock_k"]) for p in passes if p.get("superblock_k")]
    tot["superblock_k"] = max(sb) if sb else 1
    return tot


def final_counters(records):
    """The run's counter totals: the LAST explicit counters snapshot,
    else the sum of per-span counter deltas."""
    snaps = [r for r in records if r.get("counters")]
    if snaps:
        return {k: v for k, v in snaps[-1].items()
                if k not in ("counters", "time", "step", "component")}
    totals = {}
    for r in records:
        # top-level spans only: a parent span's delta already contains
        # every nested child's (the registry is one global accumulator),
        # so summing all records would double-count
        if r.get("parent_id") is not None:
            continue
        for k, v in r.items():
            if k.startswith("ctr_"):
                totals[k[4:]] = totals.get(k[4:], 0) + v
    return totals


def build_report(records, path="<records>"):
    """The full report as one string (the CLI prints it; tests assert on
    it)."""
    lines = [f"run report: {path}  ({len(records)} records)", ""]
    span_rows = []
    for key, n, wall, sync, sps in summarize_spans(records):
        span_rows.append((
            key, n, _fmt_seconds(wall), _fmt_seconds(sync),
            f"{sps:,.0f}" if sps else "-",
        ))
    lines += _table("spans (time by component)",
                    ("span", "count", "wall", "device_sync", "samples/s"),
                    span_rows)
    comp_rows = summarize_components(records)
    lines += _table("per-step telemetry",
                    ("component", "records", "steps", "convergence"),
                    comp_rows)
    st = summarize_stream(records)
    if st:
        lines += _table(
            "streaming overlap",
            ("passes", "blocks", "dispatches", "sb_k", "host", "put",
             "wait", "consume"),
            [(st["n_passes"], st["n_blocks"], st["dispatches"],
              st["superblock_k"], _fmt_seconds(st["host_s"]),
              _fmt_seconds(st["put_s"]), _fmt_seconds(st["wait_s"]),
              _fmt_seconds(st["consume_s"]))],
        )
    ctr = final_counters(records)
    if ctr:
        rows = []
        for k in sorted(ctr):
            v = ctr[k]
            shown = _fmt_bytes(v) if k.endswith("bytes") else (
                _fmt_seconds(v) if k.endswith("secs") else v)
            rows.append((k, shown))
        lines += _table("counters", ("counter", "total"), rows)
    if not span_rows and not comp_rows and not st and not ctr:
        lines.append("no observability records found "
                     "(set config.metrics_path or config.trace_dir)")
    return "\n".join(lines).rstrip() + "\n"


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    rc = 0
    for path in argv:
        try:
            records = load_records(path)
        except OSError as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            rc = 1
            continue
        sys.stdout.write(build_report(records, path=path))
    return rc


if __name__ == "__main__":
    sys.exit(main())
