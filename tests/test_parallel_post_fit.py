"""ParallelPostFit / Incremental wrapper tests (ref:
tests/test_parallel_post_fit.py, tests/test_incremental.py)."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression as SkLinear
from sklearn.linear_model import LogisticRegression as SkLogistic
from sklearn.linear_model import SGDClassifier

from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.parallel import ShardedArray
from dask_ml_tpu.wrappers import Incremental, ParallelPostFit


@pytest.fixture(scope="module")
def data():
    return make_classification(n_samples=600, n_features=10, random_state=0)


def test_parallel_post_fit_predict(data):
    X, y = data
    clf = ParallelPostFit(SkLogistic(max_iter=500)).fit(X, y)
    pred = clf.predict(X)
    assert isinstance(pred, ShardedArray)
    # parity with running the inner estimator directly
    inner = SkLogistic(max_iter=500).fit(X.to_numpy(), y.to_numpy())
    np.testing.assert_array_equal(pred.to_numpy(), inner.predict(X.to_numpy()))
    proba = clf.predict_proba(X)
    np.testing.assert_allclose(
        proba.to_numpy(), inner.predict_proba(X.to_numpy()), atol=1e-7
    )
    assert clf.score(X, y) == pytest.approx(
        inner.score(X.to_numpy(), y.to_numpy()), abs=1e-6
    )
    np.testing.assert_array_equal(clf.classes_, inner.classes_)


def test_parallel_post_fit_numpy_passthrough(data):
    X, y = data
    clf = ParallelPostFit(SkLogistic(max_iter=500)).fit(X, y)
    pred = clf.predict(X.to_numpy())
    assert isinstance(pred, np.ndarray)


def test_parallel_post_fit_prefitted(data):
    X, y = data
    inner = SkLogistic(max_iter=500).fit(X.to_numpy(), y.to_numpy())
    clf = ParallelPostFit(inner)  # no fit call
    np.testing.assert_array_equal(
        clf.predict(X).to_numpy(), inner.predict(X.to_numpy())
    )


def test_parallel_post_fit_wraps_device_estimator(data):
    X, y = data
    from dask_ml_tpu.linear_model import LogisticRegression

    clf = ParallelPostFit(LogisticRegression(solver="lbfgs", max_iter=200))
    clf.fit(X, y)
    assert clf.score(X, y) > 0.7


def test_parallel_post_fit_regressor_score(data):
    X, y = data
    reg = ParallelPostFit(SkLinear()).fit(X, y)
    s = reg.score(X, y)
    assert -1.0 <= s <= 1.0


def test_incremental_fit(data):
    X, y = data
    inc = Incremental(SGDClassifier(random_state=0, max_iter=5, tol=None),
                      shuffle_blocks=False, random_state=0)
    inc.fit(X, y, classes=[0.0, 1.0])
    assert hasattr(inc, "estimator_")
    assert inc.score(X, y) > 0.6
    pred = inc.predict(X)
    assert isinstance(pred, ShardedArray)


def test_incremental_partial_fit_accumulates(data):
    X, y = data
    inc = Incremental(SGDClassifier(random_state=0, tol=None), random_state=0)
    inc.partial_fit(X, y, classes=[0.0, 1.0])
    c1 = inc.estimator_.coef_.copy()
    inc.partial_fit(X, y)
    assert not np.allclose(c1, inc.estimator_.coef_)  # continued training


def test_incremental_requires_partial_fit(data):
    X, y = data
    with pytest.raises(ValueError, match="partial_fit"):
        Incremental(SkLinear()).fit(X, y)


def test_incremental_scoring_param(data):
    X, y = data
    inc = Incremental(
        SGDClassifier(random_state=0, tol=None), scoring="accuracy",
        random_state=0,
    )
    inc.fit(X, y, classes=[0.0, 1.0])
    assert 0.0 <= inc.score(X, y) <= 1.0


def test_parallel_post_fit_partitioned_frame(data):
    """predict/predict_proba over PartitionedFrame partitions (the
    reference's dd map_partitions post-fit path)."""
    import pandas as pd
    from sklearn.linear_model import LogisticRegression as SkLR

    from dask_ml_tpu.parallel import from_pandas
    from dask_ml_tpu.wrappers import ParallelPostFit

    X, y = data
    Xh = X.to_numpy() if hasattr(X, "to_numpy") else np.asarray(X)
    yh = y.to_numpy() if hasattr(y, "to_numpy") else np.asarray(y)
    df = pd.DataFrame(np.asarray(Xh, np.float64))
    df.columns = [str(c) for c in df.columns]
    pf = from_pandas(df, npartitions=4)
    sk = SkLR(max_iter=200).fit(Xh, yh)
    wrapped = ParallelPostFit(estimator=sk)
    wrapped.estimator_ = sk
    pred = wrapped.predict(pf)
    np.testing.assert_array_equal(pred, sk.predict(Xh))
    proba = wrapped.predict_proba(pf)
    # f64 frame partitions vs the f32 fit matrix: tolerance is absolute
    np.testing.assert_allclose(proba, sk.predict_proba(Xh), atol=1e-6)


def test_incremental_shuffle_blocks_deterministic(data):
    """shuffle_blocks=True with a fixed random_state reproduces the same
    block order, hence identical fitted coefficients."""
    X, y = data
    a = Incremental(SGDClassifier(max_iter=2, random_state=0, tol=None),
                    shuffle_blocks=True, random_state=42).fit(
        X, y, classes=[0, 1])
    b = Incremental(SGDClassifier(max_iter=2, random_state=0, tol=None),
                    shuffle_blocks=True, random_state=42).fit(
        X, y, classes=[0, 1])
    np.testing.assert_array_equal(a.estimator_.coef_, b.estimator_.coef_)
    # contrast: a different shuffle seed yields a different block order,
    # hence different coefficients — proving the shuffle actually runs
    c = Incremental(SGDClassifier(max_iter=2, random_state=0, tol=None),
                    shuffle_blocks=True, random_state=7).fit(
        X, y, classes=[0, 1])
    assert not np.allclose(a.estimator_.coef_, c.estimator_.coef_)
