from . import families, regularizers
