"""Capability probe for REAL multi-process jax.distributed tests.

Some CPU jax builds bring the 2-process runtime up but refuse the first
cross-process collective with "Multiprocess computations aren't
implemented on the CPU backend". The subprocess tests in
test_distributed.py / test_multihost_streamed.py exercise exactly that
fabric, so on such a build they can only fail — the distribution LOGIC
they used to cover now lives in the single-process virtual-rank twins
(``parallel.distributed.run_virtual_processes``), and the real-fabric
tests skip with the probe's reason.

The probe is ONE cached 2-subprocess bring-up + psum barrier per pytest
session (the same shape every real test starts with), so a capable
backend pays it once and an incapable one skips all seven tests for the
price of one fast failure.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
    from dask_ml_tpu.parallel import distributed as dist
    total = dist.barrier()
    assert total == 4.0, total
    print("probe", pid, "OK", flush=True)
""")

_RESULT = None  # (ok: bool, reason: str)


def free_port():
    """One OS-assigned free TCP port — shared by every
    two-process harness in tests/ (the probe, test_distributed,
    test_multihost_streamed) so a bind-behavior fix lands once."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def multiprocess_capability():
    """(ok, reason): can this box run a real 2-process collective?"""
    global _RESULT
    if _RESULT is not None:
        return _RESULT
    port = str(free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE.format(repo=REPO), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out or "")
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        _RESULT = (False, "2-process collective probe timed out")
        return _RESULT
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if all(p.returncode == 0 for p in procs) and all(
        f"probe {i} OK" in out for i, out in enumerate(outs)
    ):
        _RESULT = (True, "")
        return _RESULT
    joined = "\n".join(outs)
    if "aren't implemented" in joined or "not implemented" in joined:
        # keep the backend's own words — they name the capability gap
        line = next(
            (ln.strip() for ln in joined.splitlines()
             if "implemented" in ln), "multiprocess not implemented"
        )
        _RESULT = (False, line[-160:])
    else:
        tail = joined.strip().splitlines()[-1] if joined.strip() else "?"
        _RESULT = (False,
                   f"2-process collective probe failed: {tail[-160:]}")
    return _RESULT


def require_multiprocess_backend():
    """Skip the calling test when the backend can't do real multiprocess
    collectives (probe runs once per session)."""
    ok, reason = multiprocess_capability()
    if not ok:
        pytest.skip(f"real multiprocess backend unavailable: {reason}")
