"""WarmupRegistry: process-wide, idempotent, attributable warmup.

Warming — executing a compiled entry point once per shape rung so
steady-state traffic pays zero XLA compiles — used to be three
unrelated mechanisms: ``ModelServer.warmup()``/``warmup_sparse()``
walking the serving grids, the search plane's module-level
``_COHORT_WARMED`` set, and ``rebuild_model``'s off-path rewarm. This
registry subsumes them:

- **idempotent**: a warm key covers everything that determines the
  compiled program's identity (the plan token of the entry point, the
  rung, the operand geometry). A second client asking to warm an
  already-warm key skips the execution — with the plan build cache on,
  a second server over the same-shaped model warms for free;
- **attributable**: every warm records (program, ladder, rung), so the
  ``plans`` table on ``/status`` and in the report CLI shows which
  ladder rung minted each specialization, and the
  ``plan_warmups`` / ``plan_cache_hits`` counters make warming cheap
  to assert in smokes;
- **overridable**: ``config.plan_rewarm`` forces every warm to
  re-execute (debugging aid; the executions are semantic no-ops).
"""

from __future__ import annotations

import threading

__all__ = ["WarmupRegistry", "warmups"]


class WarmupRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._warmed: dict = {}

    def warmed(self, key) -> bool:
        """True when ``key`` is already warm (always False under
        ``config.plan_rewarm``)."""
        from ..config import get_config

        if get_config().plan_rewarm:
            return False
        with self._lock:
            return key in self._warmed

    def note(self, key, program=None, ladder=None, rung=None,
             ran=False) -> None:
        """Register ``key`` as warm WITHOUT executing anything — for
        call sites whose real dispatch just compiled the program (the
        cohort scan's own round-width dispatch). ``ran=True`` marks a
        warm execution this registry is accounting for."""
        from ..observability._counters import record_plan_warmup
        from .plan import note_rung

        with self._lock:
            rec = self._warmed.get(key)
            if rec is None:
                rec = self._warmed[key] = {
                    "program": program, "ladder": ladder, "rung": rung,
                    "ran": bool(ran), "hits": 0,
                }
                fresh = True
            else:
                fresh = False
        if fresh:
            note_rung(program, rung)
            if ran:
                record_plan_warmup()

    def warm(self, key, thunk, program=None, ladder=None,
             rung=None) -> bool:
        """Execute ``thunk`` once per key: returns True when it ran,
        False when the key was already warm (counted as a
        ``plan_cache_hits`` — the compile it would have minted already
        exists)."""
        if self.warmed(key):
            from ..observability._counters import record_plan_warmup

            record_plan_warmup(hit=True)
            with self._lock:
                rec = self._warmed.get(key)
                if rec is not None:
                    rec["hits"] += 1
            return False
        thunk()
        self.note(key, program=program, ladder=ladder, rung=rung,
                  ran=True)
        return True

    def stats_by_program(self) -> dict:
        """{program: {"warmups": executed, "hits": skipped}} — the
        plans-table numbers."""
        out: dict = {}
        with self._lock:
            for rec in self._warmed.values():
                p = rec.get("program")
                if p is None:
                    continue
                e = out.setdefault(p, {"warmups": 0, "hits": 0})
                if rec.get("ran"):
                    e["warmups"] += 1
                e["hits"] += int(rec.get("hits", 0))
        return out

    def snapshot(self) -> list:
        """One row per warmed key family, aggregated by
        (program, ladder): the rungs warmed and the execution/skip
        counts."""
        groups: dict = {}
        with self._lock:
            for rec in self._warmed.values():
                gkey = (rec.get("program"), rec.get("ladder"))
                g = groups.setdefault(gkey, {"rungs": set(),
                                             "warmups": 0, "hits": 0})
                if rec.get("rung") is not None:
                    g["rungs"].add(rec["rung"])
                if rec.get("ran"):
                    g["warmups"] += 1
                g["hits"] += int(rec.get("hits", 0))
        rows = []
        for (program, ladder) in sorted(
                groups, key=lambda k: (str(k[0]), str(k[1]))):
            g = groups[(program, ladder)]
            rows.append({
                "program": program or "-",
                "ladder": ladder or "-",
                "rungs": ",".join(str(r) for r in sorted(g["rungs"]))
                         or "-",
                "warmups": g["warmups"],
                "warm_hits": g["hits"],
            })
        return rows

    def reset(self) -> None:
        with self._lock:
            self._warmed.clear()


# THE process-wide registry (like the program/counter registries in
# observability): warming is a property of the process's jit caches, so
# its bookkeeping must be too
warmups = WarmupRegistry()
