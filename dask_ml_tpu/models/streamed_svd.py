"""Streamed distributed randomized SVD (ISSUE 18 tentpole, layer 3).

Reference equivalent: ``da.linalg.svd_compressed`` (Halko) over
host-backed chunks (SURVEY.md §3.3) — the reference's range finder is a
task graph of blockwise matmuls + TSQR reductions. Here each range pass
is ONE streamed super-block scan (``BlockStream.superblocks()``: K
stacked blocks per XLA dispatch, DONATED carry, zero compiles after
pass 1) and the tall factor never materializes: the scan carries

- ``Z = Σ_b Xc_bᵀ Y_b``  (d, k') — the next subspace, and
- ``R``  (k', k') — the blocked-QR / TSQR R-factor of the tall
  ``Y = Xc @ Ω``, reduced over the mesh's "data" axis,

so device memory is O(d·k') while the resident ``ops.linalg`` path
holds the full (n, d) matrix. On a 2-D ("data", "model") mesh the X
super-blocks stage as (rows/D, d/M) per-device tiles and the programs
add "model" psums exactly where the math contracts over features
(``Y_b = Σ_m X_m @ Ω_m`` and the Z/moment reassembly) — the
``superblock.pca.*.model_psum`` flavor.

Pass structure (``n_iter`` power iterations, matching the resident
``randomized_svd``):

1. ``superblock.pca.moments`` — shift-centered (Σx, Σx²) for the mean
   and per-feature variance (explained-variance ratios);
2. ``n_iter + 1`` × ``superblock.pca.range`` — each pass applies XᵀX
   to the current basis in ONE sweep (Y_b and Xᵀ Y_b from the same
   staged block); between passes the host orthonormalizes
   ``Ω ← qr(Z R⁻¹).Q`` (Halko's re-orthonormalized power step; Z and
   R are (d,k')/(k',k') — client-sized, like the reference's small
   collect);
3. the LAST pass doubles as the extraction: ``Y = Xc Ω`` with Ω
   orthonormal gives ``svd(R) = U_r S V_rᵀ`` and
   ``components = (Ω V_r)ᵀ`` — no extra projection pass over the data.

Total passes: ``n_iter + 2``. Every dispatch of a pass hits one
compiled program (fixed [K, block_rows, d] operands, ragged tail
padded with zero counts — zero rows leave both Z and the R-factor
unchanged, the same invariant ``ops.linalg.tsqr`` relies on).
"""

from __future__ import annotations

import functools as _ft

import jax
import jax.numpy as jnp
import numpy as np

from ..plans import ProgramPlan, warmups

# d at which the streamed Gram path's d×d covariance (f64 host + f32
# device per block) stops being the cheap one-pass answer and the
# O(d·k') randomized path takes over for solver="auto" fits
STREAM_GRAM_MAX_D = 4096


def _qr_r(a):
    """R-factor of ``a`` (rows >= cols after stacking), shape-stable
    (k', k') — the one blocked-QR step both the scan chain and the
    cross-shard TSQR combine use."""
    return jnp.linalg.qr(a)[1]


@_ft.lru_cache(maxsize=64)
def _pca_reducer(kind, mesh=None, model_shards=1):
    """The donated-carry super-block program for one rSVD pass flavor.

    ``kind``:
      - "moments": ``run(acc=(s1, s2), shift, Xs, counts)`` —
        shift-centered per-feature (Σc, Σc²) sums;
      - "range":   ``run(acc=(Z, R), mean, omega, Xs, counts)`` —
        ``Z += Xc_bᵀ (Xc_b Ω)`` and the blocked-QR chain
        ``R ← qr([R; Y_b]).R`` per block.

    ``mesh`` selects the shard_map flavor (replicated carry, per-shard
    row slabs, TSQR combine of the per-shard R chains over "data");
    ``model_shards > 1`` the feature-sharded flavor (per-device
    (K, S/D, d/M) X tiles, "model" psums at the feature contractions).
    Cached per flavor — every pass of every fit reuses ONE jitted
    callable, so steady-state fits pay zero XLA compiles (asserted in
    perf_smoke)."""
    if mesh is not None:
        return _pca_reducer_sharded(kind, mesh, model_shards)

    if kind == "moments":
        def body(acc, shift, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])

            def step(a, Xb, c):
                mask = (r < c).astype(Xb.dtype)
                cb = (Xb - shift) * mask[:, None]
                return (a[0] + jnp.sum(cb, axis=0),
                        a[1] + jnp.sum(cb * cb, axis=0))

            if unrolled:
                for j in range(len(Xs)):
                    acc = step(acc, Xs[j], counts[j])
                return acc

            def scan_step(a, inp):
                return step(a, *inp), jnp.float32(0.0)

            acc, _ = jax.lax.scan(scan_step, acc, (Xs, counts))
            return acc
    else:
        def body(acc, mean, omega, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])

            def step(a, Xb, c):
                Z, R = a
                mask = (r < c).astype(Xb.dtype)
                cb = (Xb - mean) * mask[:, None]
                Yb = cb @ omega
                return (Z + cb.T @ Yb,
                        _qr_r(jnp.concatenate([R, Yb], axis=0)))

            if unrolled:
                for j in range(len(Xs)):
                    acc = step(acc, Xs[j], counts[j])
                return acc

            def scan_step(a, inp):
                return step(a, *inp), jnp.float32(0.0)

            acc, _ = jax.lax.scan(scan_step, acc, (Xs, counts))
            return acc

    return ProgramPlan(
        name=f"superblock.pca.{kind}", body=body, donate=(0,),
        key=("pca-stream", kind, None, 1), group="superblock",
    ).build()


def _pca_reducer_sharded(kind, mesh, model_shards):
    """shard_map flavor of :func:`_pca_reducer`: each device scans its
    own row slab (and, feature-sharded, its own d/M feature tile) of
    every block; carries and the Ω/mean operands stay REPLICATED. Per
    super-block the "data" collectives are exactly two psums — the
    local Z/moment delta and the TSQR gather of the per-shard R
    chains; "model" psums appear only where the math contracts over
    features (the per-block feature-dot ``Y_b = Σ_m X_m Ω_m`` and the
    final slice reassembly), mirroring the GLM
    ``_sb_reducer_feature_sharded`` structure."""
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    M = int(model_shards)

    def _x_spec(a, lead):
        # X tiles: rows over "data", features (last axis) over "model"
        return P(*((None,) * lead + (DATA_AXIS,)
                   + (None,) * (a.ndim - lead - 2)
                   + (MODEL_AXIS if M > 1 else None,)))

    def _feat_slice(full, dm):
        # this device's feature slice of a replicated (d, ...) operand
        mi = jax.lax.axis_index(MODEL_AXIS)
        if full.ndim == 1:
            return jax.lax.dynamic_slice(full, (mi * dm,), (dm,))
        return jax.lax.dynamic_slice(
            full, (mi * dm, 0), (dm, full.shape[1])
        )

    def _scatter_feat(t):
        # feature-tile -> replicated full width: scatter into a zero
        # (d, ...) buffer at this device's offset, psum over "model"
        # (exact — adds zeros — and the replication checker infers the
        # psum output replicated, unlike all_gather)
        mi = jax.lax.axis_index(MODEL_AXIS)
        dm = t.shape[0]
        full = (dm * M,) + t.shape[1:]
        start = (mi * dm,) + (0,) * (t.ndim - 1)
        z = jax.lax.dynamic_update_slice(jnp.zeros(full, t.dtype), t,
                                         start)
        return jax.lax.psum(z, MODEL_AXIS)

    def _gather_data(t):
        # per-shard (k', k') R chains -> replicated (D*k', k') stack:
        # the TSQR combine's scatter+psum over "data"
        di = jax.lax.axis_index(DATA_AXIS)
        k = t.shape[0]
        D = mesh.shape[DATA_AXIS]
        z = jax.lax.dynamic_update_slice(
            jnp.zeros((D * k,) + t.shape[1:], t.dtype), t,
            (di * k,) + (0,) * (t.ndim - 1),
        )
        return jax.lax.psum(z, DATA_AXIS)

    if kind == "moments":
        def body(acc, shift, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])
            cts = counts[0]
            dm = (Xs[0].shape[-1] if unrolled else Xs.shape[-1])
            sh = _feat_slice(shift, dm) if M > 1 else shift
            local = (jnp.zeros((dm,), jnp.float32),
                     jnp.zeros((dm,), jnp.float32))

            def step(a, Xb, c):
                mask = (r < c).astype(Xb.dtype)
                cb = (Xb - sh) * mask[:, None]
                return (a[0] + jnp.sum(cb, axis=0),
                        a[1] + jnp.sum(cb * cb, axis=0))

            if unrolled:
                for j in range(len(Xs)):
                    local = step(local, Xs[j], cts[j])
            else:
                def scan_step(a, inp):
                    return step(a, *inp), jnp.float32(0.0)

                local, _ = jax.lax.scan(scan_step, local, (Xs, cts))
            local = jax.lax.psum(local, DATA_AXIS)
            if M > 1:
                local = tuple(_scatter_feat(t) for t in local)
            return tuple(a + l for a, l in zip(acc, local))

        def run_body(acc, shift, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            xs_spec = (tuple(_x_spec(a, 0) for a in Xs) if unrolled
                       else _x_spec(Xs, 1))
            f = shard_map(
                body, mesh,
                in_specs=(P(), P(), xs_spec, P(DATA_AXIS, None)),
                out_specs=P(),
            )
            return f(acc, shift, Xs, counts)
    else:
        def body(acc, mean, omega, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])
            cts = counts[0]
            dm = (Xs[0].shape[-1] if unrolled else Xs.shape[-1])
            kp = omega.shape[1]
            if M > 1:
                mn, om = _feat_slice(mean, dm), _feat_slice(omega, dm)
            else:
                mn, om = mean, omega
            Z0 = jnp.zeros((dm, kp), jnp.float32)
            R0 = jnp.zeros((kp, kp), jnp.float32)

            def step(a, Xb, c):
                Zl, Rl = a
                mask = (r < c).astype(Xb.dtype)
                cb = (Xb - mn) * mask[:, None]
                Yb = cb @ om
                if M > 1:  # the feature-dot: eta-style psum over model
                    Yb = jax.lax.psum(Yb, MODEL_AXIS)
                return (Zl + cb.T @ Yb,
                        _qr_r(jnp.concatenate([Rl, Yb], axis=0)))

            local = (Z0, R0)
            if unrolled:
                for j in range(len(Xs)):
                    local = step(local, Xs[j], cts[j])
            else:
                def scan_step(a, inp):
                    return step(a, *inp), jnp.float32(0.0)

                local, _ = jax.lax.scan(scan_step, local, (Xs, cts))
            Zl, Rl = local
            Zd = jax.lax.psum(_scatter_feat(Zl) if M > 1 else Zl,
                              DATA_AXIS)
            # TSQR combine over "data": the replicated running R chain
            # stacked on every shard's local chain, one small QR
            Rs = _gather_data(Rl)
            Rn = _qr_r(jnp.concatenate([acc[1], Rs], axis=0))
            return (acc[0] + Zd, Rn)

        def run_body(acc, mean, omega, Xs, counts):
            unrolled = isinstance(Xs, (tuple, list))
            xs_spec = (tuple(_x_spec(a, 0) for a in Xs) if unrolled
                       else _x_spec(Xs, 1))
            f = shard_map(
                body, mesh,
                in_specs=(P(), P(), P(), xs_spec, P(DATA_AXIS, None)),
                out_specs=P(),
            )
            return f(acc, mean, omega, Xs, counts)

    from ..parallel.mesh import mesh_str

    suffix = ".model_psum" if M > 1 else ".psum"
    return ProgramPlan(
        name=f"superblock.pca.{kind}{suffix}", body=run_body,
        donate=(0,), key=("pca-stream", kind, mesh, M),
        group="superblock", mesh=mesh_str(mesh),
    ).build()


def _orth_next(Z, R):
    """Host half-iteration: ``Ω_next = qr(Z R⁻¹).Q`` — the
    re-orthonormalized power step (span(Z R⁻¹) = span(Xᵀ Q_y)). Falls
    back to the pseudo-inverse when the chain's R is rank-deficient
    (degenerate spectra); qr still returns a full orthonormal basis."""
    import scipy.linalg as sla

    try:
        w = sla.solve_triangular(R.T, Z.T, lower=True).T
    except Exception:
        w = None
    if w is None or not np.all(np.isfinite(w)):
        w = Z @ np.linalg.pinv(R)
    return np.linalg.qr(w)[0]


def streamed_randomized_svd(X, block_rows, size, n_iter, key, *,
                            center=True, n_rows_global=None):
    """Run the streamed rSVD passes over ``X`` (see module docstring).

    Returns a dict: ``s`` (size,) singular values (desc), ``vt``
    (size, d) right singular vectors, ``mean`` (d,) f64 data mean,
    ``var0``/``var1`` (d,) f64 per-feature variance (ddof 0 / 1),
    ``n`` global rows, ``passes`` data passes consumed, ``stream``
    (for ``profile_snapshot``). ``center=False`` (TruncatedSVD) keeps
    the SVD uncentered but still returns the moment statistics.
    Multi-process: moments/Z merge via ``psum_host``, the R chains via
    a host TSQR combine, so every process sees the identical global
    decomposition."""
    from ..parallel import distributed as dist
    from ..parallel.streaming import BlockStream, _slice_dense

    n_local, d = int(X.shape[0]), int(X.shape[1])
    multi = dist.process_count() > 1
    n = int(n_rows_global) if n_rows_global is not None else (
        int(dist.psum_host(np.asarray(float(n_local)))) if multi
        else n_local
    )
    stream = BlockStream((X,), block_rows=block_rows)
    sharded = stream.sb_sharded()
    D = stream.sb_data_shards()
    M = stream.sb_model_shards()
    mesh = stream.mesh if sharded else None

    def _put(acc):
        if not sharded:
            return acc
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(acc, NamedSharding(stream.mesh, P()))

    def _note(kind, run):
        suffix = (".model_psum" if M > 1 else ".psum") if sharded \
            else ""
        warmups.note(
            ("pca-stream", kind, d, int(size), D, M),
            program=f"superblock.pca.{kind}{suffix}", ran=True,
        )
        return run

    # shift estimate (identical on every process — see PCA._fit_streamed)
    head = _slice_dense(X, 0, min(4096, n_local), np.float64)
    if multi:
        hs, hn = dist.psum_host(head.sum(axis=0),
                                np.asarray(float(len(head))))
        shift = hs / max(float(hn), 1.0)
    else:
        shift = head.mean(axis=0) if len(head) else np.zeros(d)

    # pass 0: moments (mean + per-feature variance)
    run = _note("moments", _pca_reducer("moments", mesh=mesh,
                                        model_shards=M))
    acc = _put((jnp.zeros((d,), jnp.float32),
                jnp.zeros((d,), jnp.float32)))
    shift_dev = jnp.asarray(shift, jnp.float32)
    for sb in stream.superblocks():
        cts = sb.shard_counts if sharded else sb.counts
        acc = run(acc, shift_dev, sb.arrays[0], cts)
    s1 = np.asarray(acc[0], np.float64)
    s2 = np.asarray(acc[1], np.float64)
    if multi:
        s1, s2 = dist.psum_host(s1, s2)
    mean_c = s1 / n
    mean = shift + mean_c
    var0 = np.maximum(s2 / n - mean_c * mean_c, 0.0)
    var1 = np.maximum((s2 - s1 * s1 / n) / max(n - 1, 1), 0.0)

    # range passes: n_iter power iterations + the extraction pass
    mean_dev = jnp.asarray(mean if center else np.zeros(d), jnp.float32)
    omega = np.asarray(
        jax.random.normal(key, (d, int(size)), jnp.float32)
    )
    n_range = max(int(n_iter), 1) + 1
    run = _note("range", _pca_reducer("range", mesh=mesh,
                                      model_shards=M))
    Z = R = None
    for p in range(n_range):
        acc = _put((jnp.zeros((d, int(size)), jnp.float32),
                    jnp.zeros((int(size), int(size)), jnp.float32)))
        omega_dev = jnp.asarray(omega, jnp.float32)
        for sb in stream.superblocks():
            cts = sb.shard_counts if sharded else sb.counts
            acc = run(acc, mean_dev, omega_dev, sb.arrays[0], cts)
        Z = np.asarray(acc[0], np.float64)
        R = np.asarray(acc[1], np.float64)
        if multi:
            Z = dist.psum_host(Z)
            rs = dist.allgather_object(np.asarray(R))
            R = np.linalg.qr(np.concatenate(rs, axis=0))[1]
        if p < n_range - 1:
            omega = _orth_next(Z, R).astype(np.float32)

    # extraction: Y = Xc Ω (Ω orthonormal) = Q R, svd(R) = U_r S V_rᵀ
    # → X ≈ (Q U_r) S (Ω V_r)ᵀ; the small factors are client-sized
    _, s, vt_r = np.linalg.svd(R)
    vt = (omega.astype(np.float64) @ vt_r.T).T
    return {
        "s": s, "vt": vt, "mean": mean, "var0": var0, "var1": var1,
        "n": n, "passes": 1 + n_range, "stream": stream,
    }


def flip_signs_vt(vt):
    """Deterministic component signs, V-based (the ``linalg.svd_flip``
    convention on host f64): each row's largest-|.| entry positive."""
    max_abs = np.argmax(np.abs(vt), axis=1)
    signs = np.sign(vt[np.arange(vt.shape[0]), max_abs])
    return vt * np.where(signs == 0, 1.0, signs)[:, None]
