"""Multiclass SGDClassifier (one-vs-rest weight stack, per-class targets
derived inside the jitted step). Multiclass models take the solo path in
adaptive-search cohorts (weights are (C, d+1)); binary cohort batching is
untouched."""

import numpy as np
import pytest

from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.parallel import as_sharded


@pytest.fixture(scope="module")
def data3():
    rng = np.random.RandomState(0)
    n, d = 900, 8
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(3, d)
    y = np.argmax(X @ W.T + 0.3 * rng.randn(n, 3), axis=1).astype(
        np.float32
    )
    return X, y


def test_multiclass_fit_and_shapes(data3):
    X, y = data3
    clf = SGDClassifier(max_iter=20, random_state=0).fit(X, y)
    assert clf.coef_.shape == (3, X.shape[1])
    assert clf.intercept_.shape == (3,)
    np.testing.assert_array_equal(clf.classes_, [0.0, 1.0, 2.0])
    assert (clf.predict(X) == y).mean() > 0.8
    assert clf.score(X, y) > 0.8


def test_multiclass_partial_fit_contract(data3):
    X, y = data3
    clf = SGDClassifier(random_state=0)
    with pytest.raises(ValueError, match="classes"):
        clf.partial_fit(X[:100], y[:100])
    clf.partial_fit(X[:300], y[:300], classes=[0.0, 1.0, 2.0])
    for s in range(300, 900, 300):
        clf.partial_fit(X[s:s + 300], y[s:s + 300])
    assert clf.coef_.shape == (3, X.shape[1])
    proba = clf.predict_proba(X)
    assert proba.shape == (len(X), 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    eta = clf.decision_function(X)
    assert eta.shape == (len(X), 3)


@pytest.mark.slow
def test_multiclass_sharded_fit(data3):
    X, y = data3
    dev = SGDClassifier(max_iter=10, random_state=0, shuffle=False).fit(
        as_sharded(X), as_sharded(y)
    )
    assert dev.coef_.shape == (3, X.shape[1])
    assert (dev.predict(as_sharded(X)) == y).mean() > 0.8
    # the device fit is deterministic: identical reruns, identical weights
    dev2 = SGDClassifier(max_iter=10, random_state=0, shuffle=False).fit(
        as_sharded(X), as_sharded(y)
    )
    np.testing.assert_array_equal(dev.coef_, dev2.coef_)


def test_multiclass_not_cohort_batchable(data3):
    X, y = data3
    clf = SGDClassifier(random_state=0)
    clf._batch_prepare({"classes": np.array([0.0, 1.0, 2.0])})
    assert clf._batch_key() is None  # solo path in adaptive searches
    binary = SGDClassifier(random_state=0)
    binary._batch_prepare({"classes": np.array([0.0, 1.0])})
    assert binary._batch_key() is not None


def test_multiclass_in_incremental_search(data3):
    from dask_ml_tpu.model_selection import IncrementalSearchCV

    X, y = data3
    search = IncrementalSearchCV(
        SGDClassifier(random_state=0),
        {"alpha": [1e-5, 1e-3], "eta0": [0.05, 0.2]},
        n_initial_parameters="grid", decay_rate=1.0, max_iter=5,
        random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0, 2.0])
    assert search.best_score_ > 0.5
    assert search.best_estimator_.coef_.shape == (3, X.shape[1])


@pytest.mark.slow
def test_multiclass_in_incremental_wrapper(data3):
    from dask_ml_tpu.wrappers import Incremental

    X, y = data3
    inc = Incremental(SGDClassifier(max_iter=3, random_state=0)).fit(
        as_sharded(X), as_sharded(y)
    )
    assert set(np.asarray(inc.estimator_.classes_)) == {0.0, 1.0, 2.0}
    assert inc.score(as_sharded(X), as_sharded(y)) > 0.6


def test_multiclass_string_labels(data3):
    """Non-numeric labels work: codes map on host in native dtype (a
    float32 label pipeline would crash on strings)."""
    X, y = data3
    names = np.array(["ant", "bee", "cat"])
    ys = names[y.astype(int)]
    clf = SGDClassifier(max_iter=15, random_state=0).fit(X, ys)
    np.testing.assert_array_equal(clf.classes_, ["ant", "bee", "cat"])
    pred = clf.predict(X)
    assert set(pred) <= set(names)
    assert (pred == ys).mean() > 0.8


def test_multiclass_in_hyperband(data3):
    from dask_ml_tpu.model_selection import HyperbandSearchCV

    X, y = data3
    search = HyperbandSearchCV(
        SGDClassifier(tol=1e-3, random_state=0),
        {"alpha": [1e-5, 1e-3], "eta0": [0.05, 0.2]},
        max_iter=6, aggressiveness=3, random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0, 2.0])
    assert search.best_estimator_.coef_.shape == (3, X.shape[1])
    assert search.best_score_ > 0.6
    # multiclass trials ran on the solo paths — sequential or concurrent
    # submesh placement — never as a vmapped cohort (the (C, d+1) weight
    # shape has no batch key)
    assert {r["executor"] for r in search.history_} <= {
        "sequential", "threads", "submesh"
    }
