"""Checkpoint / resume.

Reference: **none** — dask-ml keeps search models as in-memory futures and
a killed search restarts from scratch (SURVEY.md §5 checkpoint row).
Built anyway, deliberately exceeding the reference: TPU slices fail whole
(no lineage recompute), so recovery = checkpoint-restart at iteration
granularity for solvers and trial granularity for searches.

Device pytrees go through orbax; host objects (sklearn estimators inside
wrappers/searches) go through pickle in the same directory.
"""

from __future__ import annotations

import os
import pickle
import shutil

import jax
import numpy as np


def _orbax():
    import orbax.checkpoint as ocp

    return ocp


def _fsync_tree(root):
    """Best-effort fsync of every file (and directory) under ``root``
    so the atomic rename below publishes DURABLE bytes — a rename of
    unflushed data can survive a process kill but not a power cut."""
    try:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                try:
                    fd = os.open(os.path.join(dirpath, name), os.O_RDONLY)
                    try:
                        os.fsync(fd)
                    finally:
                        os.close(fd)
                except OSError:
                    pass
            try:
                fd = os.open(dirpath, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass
    except OSError:
        pass


def checkpoint_exists(path) -> bool:
    """Is there a restorable checkpoint at ``path``? Covers the
    atomic-writer's crash window: after a kill between "retire the old
    checkpoint" and "publish the new one", the state lives at
    ``path + '.old'`` and restore falls back to it."""
    path = os.path.abspath(path)
    return os.path.exists(path) or os.path.exists(path + ".old")


def save_pytree(path, tree, force=True):
    """Save a jax pytree (solver/optimizer state) with orbax —
    ATOMICALLY. Orbax (and the previous implementation's
    ``force=True``) deletes the live target before writing, so a kill
    mid-save used to corrupt the very checkpoint the restart needed.
    Now the write lands in a temp sibling (fsynced), the previous
    checkpoint retires to ``path + '.old'``, and one rename publishes:
    at EVERY kill point either the old or the new state restores."""
    ocp = _orbax()
    path = os.path.abspath(path)
    tmp, old = path + ".tmp", path + ".old"
    shutil.rmtree(tmp, ignore_errors=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(tmp, tree, force=force)
    _fsync_tree(tmp)
    if os.path.exists(path):
        # retire the live checkpoint to .old (replacing a stale one)
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    # else: a previous crash may have left the ONLY good state at .old
    # — it must survive until the new checkpoint has PUBLISHED, or a
    # kill right here would leave nothing restorable
    os.rename(tmp, path)
    shutil.rmtree(old, ignore_errors=True)


def restore_pytree(path, like=None):
    ocp = _orbax()
    path = os.path.abspath(path)

    def _restore(p):
        import logging

        with ocp.StandardCheckpointer() as ckptr:
            if like is not None:
                return ckptr.restore(p, like)
            # template-less restore is the stream-checkpoint contract
            # (the token check rejects foreign topologies) — silence
            # orbax's per-call UNSAFE warning for the duration
            absl = logging.getLogger("absl")
            prev = absl.level
            absl.setLevel(logging.ERROR)
            try:
                return ckptr.restore(p)
            finally:
                absl.setLevel(prev)

    try:
        return _restore(path)
    except Exception:
        # the atomic writer's crash window: the previous checkpoint
        # retired to .old but the new one never published
        old = path + ".old"
        if os.path.isdir(old):
            return _restore(old)
        raise


def save_host(path, obj, dump=None):
    """Pickle host-side state (search history, sklearn models) —
    atomically: temp sibling, flush+fsync, rename. A kill mid-save
    leaves the previous file intact, never a truncated pickle.

    ``dump`` swaps the serializer: a ``dump(obj, fileobj)`` callable
    writing to a binary file (the incident plane passes a JSON dumper
    here so bundles ride the same atomic-publish contract)."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            (dump or pickle.dump)(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def restore_host(path):
    with open(path, "rb") as f:
        return pickle.load(f)


class SearchCheckpoint:
    """Controller-state persistence for adaptive searches: history,
    per-model metadata, and model states, written every round so a killed
    search resumes at trial granularity (SURVEY.md §5 failure row)."""

    def __init__(self, directory):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_round(self, round_idx, history, meta, models, extra=None):
        state = {
            "round": round_idx,
            "history": history,
            "meta": meta,
            "models": models,
        }
        if extra:
            state.update(extra)
        save_host(self._path("controller.pkl"), state)

    def load(self):
        p = self._path("controller.pkl")
        if not os.path.exists(p):
            return None
        return restore_host(p)

    def clear(self):
        """Remove the controller state — called on successful completion so
        a finished search is never resumed into a new one."""
        p = self._path("controller.pkl")
        if os.path.exists(p):
            os.remove(p)
