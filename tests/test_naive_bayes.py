"""GaussianNB parity vs sklearn (SURVEY.md §4 oracle pattern;
ref: dask_ml/naive_bayes.py)."""

import numpy as np
import pytest
from sklearn.naive_bayes import GaussianNB as SkGNB

from dask_ml_tpu.naive_bayes import GaussianNB


@pytest.fixture(scope="module")
def data():
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=600, n_features=8, n_informative=5, n_classes=3,
        random_state=0,
    )
    return X.astype(np.float64), y.astype(np.float64)


def test_fit_attribute_parity(data):
    X, y = data
    ours = GaussianNB().fit(X, y)
    sk = SkGNB().fit(X, y)
    np.testing.assert_array_equal(np.asarray(ours.classes_), sk.classes_)
    np.testing.assert_allclose(
        np.asarray(ours.class_count_), sk.class_count_
    )
    np.testing.assert_allclose(
        np.asarray(ours.class_prior_), sk.class_prior_, rtol=1e-6
    )
    np.testing.assert_allclose(np.asarray(ours.theta_), sk.theta_, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(ours.var_), sk.var_,
                               rtol=1e-3, atol=1e-6)


def test_predict_parity(data):
    X, y = data
    ours = GaussianNB().fit(X, y)
    sk = SkGNB().fit(X, y)
    pred = np.asarray(
        ours.predict(X).to_numpy()
        if hasattr(ours.predict(X), "to_numpy") else ours.predict(X)
    )
    agree = (pred == sk.predict(X)).mean()
    assert agree > 0.99, agree
    assert abs(ours.score(X, y) - sk.score(X, y)) < 0.01


def test_predict_proba_rows_sum_to_one(data):
    X, y = data
    ours = GaussianNB().fit(X, y)
    proba = ours.predict_proba(X)
    proba = proba.to_numpy() if hasattr(proba, "to_numpy") else np.asarray(proba)
    np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-5)
    assert (proba >= 0).all()


def test_unfitted_raises(data):
    X, _ = data
    with pytest.raises(Exception):
        GaussianNB().predict(X)


def test_explicit_priors_honored(data):
    X, y = data
    Xh = X.to_numpy() if hasattr(X, "to_numpy") else np.asarray(X)
    yh = y.to_numpy() if hasattr(y, "to_numpy") else np.asarray(y)
    priors = [0.6, 0.3, 0.1]
    ours = GaussianNB(priors=priors).fit(X, y)
    ref = SkGNB(priors=priors).fit(Xh, yh)
    np.testing.assert_allclose(ours.class_prior_, ref.class_prior_)
    np.testing.assert_array_equal(
        np.asarray(ours.predict(X)), ref.predict(Xh)
    )


def test_var_smoothing_effect(data):
    X, y = data
    small = GaussianNB(var_smoothing=1e-9).fit(X, y)
    big = GaussianNB(var_smoothing=10.0).fit(X, y)
    # heavier smoothing inflates every variance
    assert (big.var_ > small.var_).all()
