"""BlockStream overlap instrumentation + epoch-boundary block autotune
(VERDICT r4 weak #2 / next-round #7): the double buffer is measured, not
assumed, and transfer-dominated epochs grow their blocks."""

import numpy as np
import pytest

import dask_ml_tpu.config as config
from dask_ml_tpu.parallel.streaming import BlockStream

X = np.random.RandomState(0).rand(4096, 8).astype(np.float32)


def test_pass_stats_populated():
    stream = BlockStream((X,), block_rows=256)
    for blk in stream:
        pass
    st = stream.stats
    for key in ("host_s", "put_s", "wait_s", "consume_s", "pass_s",
                "n_blocks", "block_rows"):
        assert key in st, key
    assert st["n_blocks"] == stream.n_blocks
    assert st["pass_s"] > 0

def test_autotune_grows_transfer_bound_blocks():
    # no compute at all between blocks: moving time dominates, and with
    # 32 blocks the autotune has room to double (twice at most)
    stream = BlockStream((X,), block_rows=128)
    assert stream.n_blocks == 32
    for blk in stream.epochs(3, autotune=True):
        pass
    assert stream.block_rows > 128
    assert stream.n_blocks < 32


def test_autotune_respects_flag_and_small_streams():
    s1 = BlockStream((X,), block_rows=128)
    for blk in s1.epochs(3, autotune=False):
        pass
    assert s1.block_rows == 128
    # <16 blocks: never resized even when transfer-bound
    s2 = BlockStream((X,), block_rows=512)
    assert s2.n_blocks == 8
    for blk in s2.epochs(3, autotune=True):
        pass
    assert s2.block_rows == 512


def test_plain_iteration_never_resizes():
    # per-block solver state (ADMM) iterates the stream directly; the
    # partition must be stable across passes
    stream = BlockStream((X,), block_rows=128)
    for _ in range(3):
        for blk in stream:
            pass
    assert stream.block_rows == 128
    assert stream.n_blocks == 32


def test_all_rows_seen_after_resize():
    stream = BlockStream((X,), block_rows=128)
    seen = 0
    for blk in stream.epochs(3, autotune=True):
        seen += blk.n_rows
    assert seen == 3 * len(X)  # every epoch covers every row exactly


def test_grid_partition_single_device():
    """A 1-device mesh must still yield multiple minibatch steps per
    epoch — a D-only split once collapsed host fits to one block."""
    from dask_ml_tpu.parallel.streaming import grid_partition

    B, S = grid_partition(100_000, 1)
    assert B >= 8
    assert S * B >= 100_000
    B8, S8 = grid_partition(100_000, 8)
    assert B8 == 8 and S8 == 12504  # unchanged on the 8-device mesh


def test_wait_measured_only_when_consumed(monkeypatch):
    """No logger bound and no autotune: the readiness sync (which costs
    overlap) is skipped; wait_s stays zero."""
    stream = BlockStream((X,), block_rows=256)
    for blk in stream:
        pass
    assert stream.stats["wait_s"] == 0.0
    for blk in stream.epochs(2, autotune=True):
        pass
    assert "wait_s" in stream.stats  # measured (possibly ~0) when tuning


def test_config_env_parsing(monkeypatch):
    monkeypatch.setenv("DASK_ML_TPU_STREAM_BLOCK_ROWS", "123")
    monkeypatch.setenv("DASK_ML_TPU_STREAM_AUTOTUNE", "false")
    cfg = config._from_env()
    assert cfg.stream_block_rows == 123
    assert cfg.stream_autotune is False


def test_stats_logged_to_ambient_logger(tmp_path):
    import json

    from dask_ml_tpu.utils.observability import MetricsLogger, active_logger

    path = tmp_path / "m.jsonl"
    with MetricsLogger(str(path)) as lg, active_logger(lg):
        stream = BlockStream((X,), block_rows=512)
        for blk in stream:
            pass
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert any("stream_pass" in r for r in recs)
