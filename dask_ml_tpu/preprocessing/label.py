"""LabelEncoder.

Reference: ``dask_ml/preprocessing/label.py`` (SURVEY.md §2a encoders
row): classes from data (or a pandas categorical fast path via
``use_categorical``), transform = map values to ordinal codes. Here the
mapping is a device ``searchsorted`` over the sorted class vector.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..base import BaseEstimator, TransformerMixin, to_host
from ..parallel.sharded import ShardedArray, as_sharded
from ..utils.validation import check_is_fitted


class LabelEncoder(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/preprocessing/label.py::LabelEncoder."""

    def __init__(self, use_categorical=True):
        self.use_categorical = use_categorical

    def fit(self, y):
        if isinstance(y, pd.Series) and self.use_categorical and \
                isinstance(y.dtype, pd.CategoricalDtype):
            self.classes_ = np.asarray(y.cat.categories)
            self.dtype_ = y.dtype
            return self
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        self.classes_ = np.unique(yh)
        self.dtype_ = None
        return self

    def fit_transform(self, y):
        return self.fit(y).transform(y)

    def transform(self, y):
        check_is_fitted(self, "classes_")
        if isinstance(y, pd.Series) and self.dtype_ is not None and \
                y.dtype == self.dtype_:
            return np.asarray(y.cat.codes)
        if isinstance(y, ShardedArray):
            classes = jnp.asarray(self.classes_, y.dtype)
            codes = jnp.searchsorted(classes, y.data)
            self._check_membership(y.to_numpy())
            return ShardedArray(codes, y.n_rows, y.mesh)
        yh = np.asarray(y)
        self._check_membership(yh)
        return np.searchsorted(self.classes_, yh)

    def _check_membership(self, yh):
        extra = np.setdiff1d(yh, self.classes_)
        if len(extra):
            raise ValueError(f"y contains previously unseen labels: {extra}")

    def inverse_transform(self, y):
        check_is_fitted(self, "classes_")
        if isinstance(y, ShardedArray):
            y = y.to_numpy()
        return self.classes_[np.asarray(y).astype(int)]
