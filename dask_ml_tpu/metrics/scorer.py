"""Dask-aware scorers. Ref: ``dask_ml/metrics/scorer.py`` (SURVEY.md §2a
Metrics row): SCORERS / get_scorer / check_scoring working on sharded
inputs."""

from __future__ import annotations

from .classification import accuracy_score, log_loss
from .regression import (
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)


def _make_scorer(metric, greater_is_better=True, needs_proba=False):
    sign = 1.0 if greater_is_better else -1.0

    def scorer(estimator, X, y):
        if needs_proba:
            pred = estimator.predict_proba(X)
            # proba columns align to estimator.classes_ — forward them so
            # a CV fold missing a class still scores (sklearn's scorer
            # does the same); log_loss would otherwise raise
            classes = getattr(estimator, "classes_", None)
            if classes is not None:
                import numpy as _np

                return sign * metric(y, pred, labels=_np.asarray(classes))
        else:
            pred = estimator.predict(X)
        return sign * metric(y, pred)

    return scorer


SCORERS = {
    "accuracy": _make_scorer(accuracy_score),
    "neg_mean_squared_error": _make_scorer(mean_squared_error,
                                           greater_is_better=False),
    "neg_mean_absolute_error": _make_scorer(mean_absolute_error,
                                            greater_is_better=False),
    "neg_log_loss": _make_scorer(log_loss, greater_is_better=False,
                                 needs_proba=True),
    "r2": _make_scorer(r2_score),
}


import collections as _collections

# host copies of recently-scored folds, keyed by id. The ShardedArray is
# pinned in the value so a GC'd-and-reused id can never alias a stale
# copy; bounded FIFO so memory stays ≈ a handful of test folds. Without
# this, a search with N candidates gathers the SAME cached fold N times.
_HOST_FOLD_CACHE: "_collections.OrderedDict" = _collections.OrderedDict()
_HOST_FOLD_CACHE_MAX = 16


def _to_host_cached(a):
    key = id(a)
    hit = _HOST_FOLD_CACHE.get(key)
    if hit is not None and hit[0] is a:
        return hit[1]
    h = a.to_numpy()
    _HOST_FOLD_CACHE[key] = (a, h)
    while len(_HOST_FOLD_CACHE) > _HOST_FOLD_CACHE_MAX:
        _HOST_FOLD_CACHE.popitem(last=False)
    return h


def _host_adapting(scorer):
    """Wrap an EXTERNAL scorer callable (sklearn make_scorer object, user
    function). The raw call runs first — sharded-aware scorers (built on
    this package's metrics) keep their device-resident path untouched.
    Only if the scorer rejects the inputs (sklearn's validation raises on
    ShardedArray) is it retried with host-converted folds."""

    def wrapped(estimator, X, y=None, **kwargs):
        from ..parallel.sharded import ShardedArray

        sharded = isinstance(X, ShardedArray) or isinstance(y, ShardedArray)
        try:
            return scorer(estimator, X, y, **kwargs)
        except (ValueError, TypeError, AttributeError):
            if not sharded:
                raise
        Xh = _to_host_cached(X) if isinstance(X, ShardedArray) else X
        yh = _to_host_cached(y) if isinstance(y, ShardedArray) else y
        return scorer(estimator, Xh, yh, **kwargs)

    return wrapped


def get_scorer(scoring, compute=True):
    if callable(scoring):
        return _host_adapting(scoring)
    try:
        return SCORERS[scoring]
    except KeyError:
        raise ValueError(
            f"{scoring!r} is not a valid scoring value; options: "
            f"{sorted(SCORERS)}"
        )


def check_scoring(estimator, scoring=None, **kwargs):
    if scoring is None:
        if not hasattr(estimator, "score"):
            raise TypeError(
                f"estimator {estimator!r} has no score method; pass scoring="
            )
        return lambda est, X, y: est.score(X, y)
    return get_scorer(scoring)
