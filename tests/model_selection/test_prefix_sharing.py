"""Work de-duplication tests: the controller prefix memo must fit shared
pipeline prefixes ONCE per (fold, params) — the TPU analog of the
reference's tokenized-graph de-dup (ref: dask_ml/model_selection/
_normalize.py + _search.py build_graph; SURVEY.md §3.4, §4
"graph-determinism tests")."""

import numpy as np
import pytest
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.linear_model import Ridge
from sklearn.pipeline import Pipeline

from dask_ml_tpu.model_selection import GridSearchCV
from dask_ml_tpu.model_selection._normalize import estimator_token

FIT_CALLS = {"n": 0}


class CountingScaler(TransformerMixin, BaseEstimator):
    """Transformer that counts fit calls (de-dup oracle)."""

    def __init__(self, with_mean=True):
        self.with_mean = with_mean

    def fit(self, X, y=None):
        FIT_CALLS["n"] += 1
        self.mean_ = np.asarray(X).mean(0) if self.with_mean else 0.0
        return self

    def transform(self, X):
        return np.asarray(X) - self.mean_


@pytest.fixture()
def data():
    rng = np.random.RandomState(0)
    X = rng.randn(200, 6)
    y = X @ rng.randn(6) + 0.1 * rng.randn(200)
    return X, y


def test_shared_prefix_fit_once_per_fold(data):
    """Grid over ONLY the final step: the scaler must fit n_folds times
    total, not n_folds * n_candidates (reference's headline optimization)."""
    X, y = data
    FIT_CALLS["n"] = 0
    pipe = Pipeline([("scale", CountingScaler()), ("reg", Ridge())])
    search = GridSearchCV(
        pipe, {"reg__alpha": [0.01, 0.1, 1.0, 10.0]}, cv=3, refit=False
    )
    search.fit(X, y)
    assert FIT_CALLS["n"] == 3, FIT_CALLS["n"]  # one per fold
    hits, misses = search._memo_stats
    assert hits > 0


def test_prefix_params_partition_the_memo(data):
    """Grid over scaler AND ridge params: scaler fits = n_folds *
    n_scaler_settings."""
    X, y = data
    FIT_CALLS["n"] = 0
    pipe = Pipeline([("scale", CountingScaler()), ("reg", Ridge())])
    search = GridSearchCV(
        pipe,
        {"scale__with_mean": [True, False], "reg__alpha": [0.1, 1.0, 10.0]},
        cv=2, refit=False,
    )
    search.fit(X, y)
    assert FIT_CALLS["n"] == 2 * 2, FIT_CALLS["n"]


def test_search_results_unaffected_by_memo(data):
    """De-dup must not change scores: same cv_results_ as a memo-less run
    (non-pipeline estimator takes the plain path)."""
    X, y = data
    pipe = Pipeline([("scale", CountingScaler()), ("reg", Ridge())])
    grid = {"reg__alpha": [0.1, 1.0]}
    a = GridSearchCV(pipe, grid, cv=3, refit=False).fit(X, y)
    # plain sklearn as the no-sharing oracle
    from sklearn.model_selection import GridSearchCV as SkGrid

    b = SkGrid(pipe, grid, cv=3, refit=False).fit(X, y)
    np.testing.assert_allclose(
        a.cv_results_["mean_test_score"], b.cv_results_["mean_test_score"],
        rtol=1e-10,
    )
    assert a.best_params_ == b.best_params_


def test_estimator_token_stability():
    """Same params => same token; different params / class => different.
    (The reference's tokenize-determinism contract.)"""
    assert estimator_token(Ridge(alpha=1.0)) == estimator_token(Ridge(alpha=1.0))
    assert estimator_token(Ridge(alpha=1.0)) != estimator_token(Ridge(alpha=2.0))
    assert estimator_token(Ridge()) != estimator_token(CountingScaler())
    # ndarray-valued params hash by content
    w1 = np.arange(4.0)
    assert (
        estimator_token(CountingScaler(with_mean=w1))
        == estimator_token(CountingScaler(with_mean=np.arange(4.0)))
    )
    assert (
        estimator_token(CountingScaler(with_mean=w1))
        != estimator_token(CountingScaler(with_mean=w1 + 1))
    )
    # nested estimator params recurse
    assert (
        estimator_token(CountingScaler(with_mean=Ridge(alpha=1.0)))
        == estimator_token(CountingScaler(with_mean=Ridge(alpha=1.0)))
    )
