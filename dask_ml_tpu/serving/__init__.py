"""Online inference serving for fitted estimators.

The inference side of the ROADMAP north star ("serves heavy traffic from
millions of users"): where ``wrappers.ParallelPostFit`` parallelizes ONE
big offline predict over blocks, this package answers MANY small
concurrent requests without paying a fresh XLA compile per novel shape
or a host→device parameter transfer per call.

- ``_buckets``  — the geometric shape-bucket ladder bounding the
  compiled-program set;
- ``_batching`` — request records, the bounded admission queue,
  ping-pong staging buffers, pack/demux, the deadline-aware batch
  release rule;
- ``_server``   — :class:`ModelServer`: micro-batching worker, warmup,
  backpressure (:class:`ServerOverloaded` / :class:`RequestTimeout`),
  zero-recompile hot-swap (:meth:`ModelServer.swap_model`), graceful
  drain;
- ``registry``  — :class:`ModelRegistry`: named, versioned fitted-model
  snapshots with publish/rollback notification;
- ``policy``    — windowed execution-latency prediction + SLO admission
  verdicts;
- ``fleet``     — :class:`FleetServer`: N replica workers (per-device
  placement), least-loaded routing, SLO-aware admission, rolling
  hot-swap, failover, and :func:`serve_while_training`;
- ``metrics``   — per-batch spans + serving counters through
  ``dask_ml_tpu/observability``, and the latency-quantile window;
- ``federation`` — :class:`FederatedFleet`: predicted-completion
  routing over N fleet PROCESSES, zero-lost failover with
  ``rerouted_from_process`` trace tags, cross-process publish fan-out
  with pinned version convergence;
- ``autoscale`` — :class:`ReplicaAutoscaler`: the SLO admission signal
  ADDS/RETIRES replicas under hysteresis bands (plans-warm spin-up);
- ``loadtest``  — :func:`replay_load_test`: recorded-traffic replay
  with a pass/fail SLO verdict (chaos- and canary-aware).

Quick start::

    from dask_ml_tpu.serving import FleetServer, ModelServer

    with ModelServer(fitted_clf,
                     methods=("predict", "predict_proba")).warmup() as srv:
        fut = srv.submit(x_small)        # Future
        proba = srv.predict_proba(x)     # blocking convenience

    with FleetServer(fitted_clf, replicas=2).warmup() as fleet:
        y = fleet.predict(x)
        fleet.publish(retrained_clf)     # zero-recompile rolling swap
"""

from ._buckets import BucketLadder
from ._server import (
    ModelServer,
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    SloShed,
)
from .autoscale import ReplicaAutoscaler
from .federation import (
    FederatedFleet,
    FleetEndpoint,
    HttpEndpoint,
    LocalEndpoint,
    NoLiveProcesses,
    ProcessDown,
)
from .fleet import FleetServer, NoHealthyReplicas, serve_while_training
from .loadtest import replay_load_test, synthesize_records
from .registry import (
    ModelRegistry,
    ModelVersion,
    RegistryError,
    UnknownModelError,
)

__all__ = [
    "BucketLadder",
    "FederatedFleet",
    "FleetEndpoint",
    "FleetServer",
    "HttpEndpoint",
    "LocalEndpoint",
    "ModelRegistry",
    "ModelServer",
    "ModelVersion",
    "NoHealthyReplicas",
    "NoLiveProcesses",
    "ProcessDown",
    "RegistryError",
    "ReplicaAutoscaler",
    "RequestTimeout",
    "ServerClosed",
    "ServerOverloaded",
    "ServingError",
    "SloShed",
    "UnknownModelError",
    "replay_load_test",
    "serve_while_training",
    "synthesize_records",
]
