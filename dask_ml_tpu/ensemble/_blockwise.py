"""Blockwise ensembles: one sub-estimator per data block, vote/average to
predict.

Reference: ``dask_ml/ensemble/_blockwise.py`` (SURVEY.md §2a Blockwise
ensembles row). Blocks map to mesh shards: each member trains on one
shard's rows. Members are host estimators (sklearn contract); voting /
averaging of their predictions is a host reduction over the (small)
per-member outputs.
"""

from __future__ import annotations

import numpy as np

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin, clone
from ..metrics import accuracy_score, r2_score
from ..parallel.mesh import data_shards
from ..parallel.sharded import ShardedArray, as_sharded


class _BlockwiseBase(BaseEstimator):
    def __init__(self, estimator):
        self.estimator = estimator

    def _shard_blocks(self, X, y):
        Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        n_blocks = (
            data_shards(X.mesh) if isinstance(X, ShardedArray) else 8
        )
        bs = int(np.ceil(len(Xh) / n_blocks))
        for i in range(0, len(Xh), bs):
            yield Xh[i:i + bs], yh[i:i + bs]

    def _fit(self, X, y, **kwargs):
        self.estimators_ = []
        for Xb, yb in self._shard_blocks(X, y):
            if len(Xb) == 0:
                continue
            est = clone(self.estimator)
            est.fit(Xb, yb, **kwargs)
            self.estimators_.append(est)
        if not self.estimators_:
            raise ValueError("no non-empty blocks to fit on")
        return self

    def _member_predictions(self, X, method="predict"):
        Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
        return np.stack(
            [getattr(est, method)(Xh) for est in self.estimators_], axis=0
        )

    def _wrap_like(self, out, X):
        if isinstance(X, ShardedArray):
            return as_sharded(out, mesh=X.mesh)
        return out


class BlockwiseVotingClassifier(ClassifierMixin, _BlockwiseBase):
    """Ref: dask_ml/ensemble/_blockwise.py::BlockwiseVotingClassifier."""

    def __init__(self, estimator, voting="hard", classes=None):
        self.estimator = estimator
        self.voting = voting
        self.classes = classes

    def fit(self, X, y, **kwargs):
        if self.voting not in ("hard", "soft"):
            raise ValueError(f"voting must be 'hard' or 'soft', got "
                             f"{self.voting!r}")
        self._fit(X, y, **kwargs)
        if self.classes is not None:
            self.classes_ = np.asarray(self.classes)
        else:
            self.classes_ = np.unique(
                y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
            )
        return self

    def predict(self, X):
        if self.voting == "soft":
            proba = self._member_predictions(X, "predict_proba").mean(axis=0)
            out = self.classes_[np.argmax(proba, axis=1)]
        else:
            preds = self._member_predictions(X)  # (members, n)
            # majority vote via per-class counts
            votes = np.stack(
                [(preds == c).sum(axis=0) for c in self.classes_], axis=1
            )
            out = self.classes_[np.argmax(votes, axis=1)]
        return self._wrap_like(out, X)

    def predict_proba(self, X):
        if self.voting != "soft":
            raise AttributeError(
                "predict_proba is only available when voting='soft'"
            )
        proba = self._member_predictions(X, "predict_proba").mean(axis=0)
        return self._wrap_like(proba, X)

    def score(self, X, y):
        return accuracy_score(y, self.predict(X))


class BlockwiseVotingRegressor(RegressorMixin, _BlockwiseBase):
    """Ref: dask_ml/ensemble/_blockwise.py::BlockwiseVotingRegressor."""

    def fit(self, X, y, **kwargs):
        return self._fit(X, y, **kwargs)

    def predict(self, X):
        return self._wrap_like(self._member_predictions(X).mean(axis=0), X)

    def score(self, X, y):
        return r2_score(y, self.predict(X))
