"""Serving fleet (dask_ml_tpu/serving/{registry,policy,fleet}.py +
the wrappers param-swap contract): versioned registry, zero-recompile
hot-swap, deadline-aware batch release, SLO admission, replica
failover, serve-while-training.

The compile-bound assertions ride the observability recompile counter,
same as test_serving.py: a warmed fleet answering ragged ladder traffic
across ANY number of same-shape swaps pays ZERO new XLA compiles —
compiled entry points close over shapes, not values.
"""

import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import observability as obs
from dask_ml_tpu.serving import (
    BucketLadder,
    FleetServer,
    ModelRegistry,
    ModelServer,
    NoHealthyReplicas,
    ServerClosed,
    ServingError,
    SloShed,
    UnknownModelError,
    serve_while_training,
)
from dask_ml_tpu.wrappers import ParamSwapError, compiled_batch_fn


@pytest.fixture(scope="module")
def two_logregs():
    """Two same-shape fitted models (the swap pair) + host data."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=0
    )
    X2, y2 = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=7
    )
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
    return a, b, X.to_numpy().astype(np.float32)


def _ladder():
    return BucketLadder(8, 128, 2.0)


# -- registry ----------------------------------------------------------------

def test_registry_publish_get_versions(two_logregs):
    a, b, Xh = two_logregs
    reg = ModelRegistry(keep=4)
    assert reg.publish("clf", a) == 1
    assert reg.publish("clf", b) == 2
    assert reg.current_version("clf") == 2
    assert reg.versions("clf") == (1, 2)
    assert reg.names() == ("clf",)
    # archived versions stay addressable
    np.testing.assert_array_equal(
        reg.get("clf", 1).estimator.predict(Xh[:20]),
        a.predict(Xh[:20]),
    )
    with pytest.raises(UnknownModelError):
        reg.get("nope")
    with pytest.raises(UnknownModelError):
        reg.get("clf", 99)


def test_registry_rollback_and_eviction(two_logregs):
    a, b, _ = two_logregs
    reg = ModelRegistry(keep=2)
    for est in (a, b, a, b):
        reg.publish("m", est)
    # keep=2: only the newest two survive
    assert reg.versions("m") == (3, 4)
    assert reg.rollback("m") == 3
    assert reg.current_version("m") == 3
    # explicit rollback target must be a KEPT version
    with pytest.raises(UnknownModelError):
        reg.rollback("m", version=1)
    # rollback with nothing older fails typed
    reg2 = ModelRegistry()
    reg2.publish("m", a)
    with pytest.raises(UnknownModelError):
        reg2.rollback("m")


def test_registry_snapshot_isolates_training(two_logregs):
    """publish() deep-copies: mutating the live estimator afterwards
    must not rewrite the archive (rollback depends on this)."""
    a, _, Xh = two_logregs
    import copy

    live = copy.deepcopy(a)
    reg = ModelRegistry()
    reg.publish("m", live)
    want = np.asarray(live.predict(Xh[:20]))
    live.coef_ = np.asarray(live.coef_) * -1.0  # "training" mutates it
    np.testing.assert_array_equal(
        reg.get("m").estimator.predict(Xh[:20]), want
    )


def test_registry_subscribe_fires_immediately_and_on_publish(
    two_logregs,
):
    a, b, _ = two_logregs
    reg = ModelRegistry()
    reg.publish("m", a)
    seen = []
    reg.subscribe("m", lambda mv: seen.append(mv.version))
    assert seen == [1]          # late joiner sees the current version
    reg.publish("m", b)
    reg.rollback("m")
    assert seen == [1, 2, 1]


# -- swap contract (wrappers) ------------------------------------------------

def test_swap_parity_exact(two_logregs):
    """Swap parity: after swapping to version B, the compiled path's
    outputs EXACTLY match a fresh entry point built from B (same
    program, same params — bitwise), and match B's direct method within
    the compiled path's usual float tolerance (predict labels exactly)."""
    a, b, Xh = two_logregs
    for method in ("predict", "predict_proba", "decision_function"):
        fn = compiled_batch_fn(a, method)
        for est in (b, a, b):
            fn.swap_params(est)
            got = fn(Xh[:37])
            fresh = compiled_batch_fn(est, method)(Xh[:37])
            np.testing.assert_array_equal(got, fresh)
            want = np.asarray(getattr(est, method)(Xh[:37]))
            if method == "predict":
                np.testing.assert_array_equal(got, want)
            else:
                np.testing.assert_allclose(got, want, atol=1e-5)


def test_swap_rejects_structural_mismatch(two_logregs):
    a, _, _ = two_logregs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    Xw, yw = make_classification(
        n_samples=300, n_features=9, n_informative=5, random_state=0
    )
    wider = LogisticRegression(solver="lbfgs", max_iter=10).fit(Xw, yw)
    fn = compiled_batch_fn(a, "predict")
    with pytest.raises(ParamSwapError):
        fn.swap_params(wider)          # 9 features vs 12
    # a refused swap leaves the old params serving
    assert fn.version == 0


def test_swap_kmeans_and_pca_parity():
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.datasets import make_blobs
    from dask_ml_tpu.decomposition import PCA

    X, _ = make_blobs(n_samples=300, n_features=6, centers=4,
                      random_state=0)
    X2, _ = make_blobs(n_samples=300, n_features=6, centers=4,
                       random_state=5)
    Xh = X.to_numpy().astype(np.float32)
    km1 = KMeans(n_clusters=4, random_state=0).fit(X)
    km2 = KMeans(n_clusters=4, random_state=3).fit(X2)
    fn = compiled_batch_fn(km1, "predict")
    fn.swap_params(km2)
    np.testing.assert_array_equal(
        fn(Xh[:50]), km2.predict(Xh[:50]).to_numpy()
    )
    p1 = PCA(n_components=3, random_state=0).fit(X)
    p2 = PCA(n_components=3, random_state=1).fit(X2)
    fnp = compiled_batch_fn(p1, "transform")
    fnp.swap_params(p2)
    np.testing.assert_allclose(
        fnp(Xh[:50]), p2.transform(Xh[:50]).to_numpy(), atol=1e-4
    )
    # k changed -> structural refusal
    km3 = KMeans(n_clusters=3, random_state=0).fit(X)
    with pytest.raises(ParamSwapError):
        fn.swap_params(km3)


def test_server_swap_is_all_or_nothing(two_logregs):
    """A multi-method server validates EVERY method before mutating
    any: a swap that works for predict but not for the server's other
    methods must leave all of them on the old version."""
    a, _, Xh = two_logregs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.models.sgd import SGDClassifier

    Xs, ys = make_classification(
        n_samples=300, n_features=12, n_informative=6, random_state=2
    )
    hinge = SGDClassifier(loss="hinge", max_iter=3, random_state=0)
    hinge.fit(Xs, ys)
    srv = ModelServer(a, methods=("predict", "predict_proba"),
                      ladder=_ladder())
    with pytest.raises(ParamSwapError):
        srv.swap_model(hinge)  # predict would swap; predict_proba can't
    with srv:
        np.testing.assert_array_equal(
            srv.predict(Xh[:10]), np.asarray(a.predict(Xh[:10]))
        )


def test_pipeline_swap_parity_and_all_or_nothing():
    """Pipeline entry points (host prefix + compiled final step) honor
    the same swap contract as bare compiled ones: exact parity after a
    swap (the NEW scaler feeds the NEW weights — never a torn mix), and
    a refusal for ONE method leaves every method on the old version
    (pipeline fns have no _extract, so the guard must run through
    prepare_swap, not the extract-only validation)."""
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.models.sgd import SGDClassifier

    Xs, ys = make_classification(
        n_samples=400, n_features=10, n_informative=5, random_state=0
    )
    X2, y2 = make_classification(
        n_samples=400, n_features=10, n_informative=5, random_state=9
    )
    Xh = Xs.to_numpy().astype(np.float32)
    mk = lambda loss: Pipeline([  # noqa: E731
        ("sc", StandardScaler()),
        ("clf", SGDClassifier(loss=loss, max_iter=3, random_state=0)),
    ])
    p1 = mk("log_loss").fit(Xh, np.asarray(ys.to_numpy()))
    p2 = mk("log_loss").fit(X2.to_numpy().astype(np.float32),
                            np.asarray(y2.to_numpy()))
    p_hinge = mk("hinge").fit(Xh, np.asarray(ys.to_numpy()))

    srv = ModelServer(p1, methods=("predict", "predict_proba"),
                      ladder=_ladder())
    with srv:
        srv.warmup()
        np.testing.assert_array_equal(
            srv.predict(Xh[:16]), np.asarray(p1.predict(Xh[:16]))
        )
        srv.swap_model(p2, version=2)
        np.testing.assert_array_equal(
            srv.predict(Xh[:16]), np.asarray(p2.predict(Xh[:16]))
        )
        # hinge has no predict_proba -> the whole swap must refuse,
        # with BOTH methods still serving v2
        with pytest.raises(ParamSwapError):
            srv.swap_model(p_hinge, version=3)
        assert srv.model_version == 2
        np.testing.assert_array_equal(
            srv.predict(Xh[:16]), np.asarray(p2.predict(Xh[:16]))
        )
        proba = np.asarray(srv.predict_proba(Xh[:16]))
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)


# -- fleet: compile bounds across swaps --------------------------------------

def test_fleet_zero_compiles_across_swaps_under_traffic(two_logregs):
    """The acceptance gate: a warmed 2-replica fleet under randomized
    ragged traffic pays ZERO new XLA compiles across >= 3 hot-swaps,
    and no request is lost or answered wrongly across the flips."""
    a, b, Xh = two_logregs
    preds = {0: np.asarray(a.predict(Xh)), 1: np.asarray(b.predict(Xh))}
    fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0).warmup()
    errs = []
    stop = threading.Event()
    swap_log = []

    with fleet:
        before = obs.counters_snapshot().get("recompiles", 0)

        def client(seed):
            rng = np.random.RandomState(seed)
            while not stop.is_set():
                n = rng.randint(1, 100)
                i = rng.randint(0, Xh.shape[0] - n)
                req = Xh[i:i + n]
                try:
                    got = fleet.predict(req)
                except ServingError as exc:
                    errs.append(repr(exc))
                    continue
                # the answer must match ONE of the published versions
                # exactly (a batch in flight during a swap serves the
                # version it was packed under)
                if not any(
                    np.array_equal(got, preds[v][i:i + n])
                    for v in (0, 1)
                ):
                    errs.append(f"mismatch at n={n} i={i}")

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for k, est in enumerate((b, a, b, a)):   # 4 swaps under load
            time.sleep(0.15)
            swap_log.append(fleet.publish(est))
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join()
        after = obs.counters_snapshot().get("recompiles", 0)
        stats = fleet.stats()
    assert not errs, errs[:3]
    assert after - before == 0, (
        f"{after - before} recompiles across {len(swap_log)} hot-swaps"
    )
    assert len(swap_log) == 4 and stats["version"] == swap_log[-1]
    assert stats["swaps"] >= 4
    assert all(p["version"] == swap_log[-1]
               for p in stats["replicas"])


def test_fleet_routes_least_loaded(two_logregs):
    """Requests land on the replica with the fewest queued rows."""
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0)
    with fleet:
        r0, r1 = fleet.replicas
        r0.pause()
        r1.pause()
        # first request -> either (both empty); then the OTHER must get
        # the next one, and so on — queue rows stay balanced within one
        # request's rows
        futs = [fleet.submit(Xh[:4]) for _ in range(6)]
        assert r0._queue.depth == 3 and r1._queue.depth == 3
        r0.resume()
        r1.resume()
        for f in futs:
            assert f.result(timeout=30).shape == (4,)


def test_fleet_replica_failure_drains_to_survivors(two_logregs):
    """Kill one replica mid-run: its queued requests resolve with the
    typed ServerClosed, new traffic reroutes to the survivor, and the
    fleet stays correct; with every replica down the door raises
    NoHealthyReplicas."""
    a, _, Xh = two_logregs
    want = np.asarray(a.predict(Xh[:6]))
    fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0)
    with fleet:
        r0, r1 = fleet.replicas
        r0.pause()
        # stack requests onto r0, then kill it without drain: typed
        # errors for ITS queue, not lost futures
        doomed = []
        while r0._queue.depth == 0:
            f = fleet.submit(Xh[:4])
            if r0._queue.depth:
                doomed.append(f)
        r0.stop(drain=False)
        with pytest.raises(ServerClosed):
            doomed[-1].result(timeout=30)
        assert not r0.healthy and r1.healthy
        # new traffic drains to the survivor
        reroutes0 = obs.counters_snapshot().get("serving_reroutes", 0)
        for _ in range(5):
            np.testing.assert_array_equal(fleet.predict(Xh[:6]), want)
        assert fleet.stats()["healthy_replicas"] == 1
        # a swap while degraded still reaches the survivor
        r1_version = r1.model_version
        fleet.publish(a)
        assert r1.model_version == r1_version + 1
        # all replicas down -> typed fleet-level error
        r1.stop(drain=False)
        with pytest.raises(NoHealthyReplicas):
            fleet.submit(Xh[:4])
        assert obs.counters_snapshot().get(
            "serving_reroutes", 0) >= reroutes0


# -- deadline-aware release / SLO ---------------------------------------------

def test_deadline_release_honors_slo(two_logregs):
    """With an SLO configured, a partial batch releases EARLY: a fixed
    200ms window would blow a 60ms SLO on a lone request; the
    deadline-aware rule dispatches in time instead."""
    from dask_ml_tpu import config

    a, _, Xh = two_logregs
    with config.set(serving_slo_ms=60.0):
        srv = ModelServer(a, ladder=_ladder(), batch_window_ms=200.0,
                          timeout_ms=0).warmup()
        with srv:
            srv.predict(Xh[:4])   # seed the exec histogram
            t0 = time.perf_counter()
            srv.predict(Xh[:4])
            lat = time.perf_counter() - t0
        assert lat < 0.12, (
            f"deadline release did not fire: lone request took "
            f"{lat * 1e3:.0f}ms against a 60ms SLO (window 200ms)"
        )
    # control: without the SLO the fixed window holds the batch
    srv2 = ModelServer(a, ladder=_ladder(), batch_window_ms=200.0,
                       timeout_ms=0).warmup()
    with srv2:
        t0 = time.perf_counter()
        srv2.predict(Xh[:4])
        lat2 = time.perf_counter() - t0
    assert lat2 >= 0.15, f"fixed window not honored: {lat2 * 1e3:.0f}ms"


def test_release_deadline_rule_unit():
    from dask_ml_tpu.serving._batching import release_deadline

    # no SLO -> the fixed window from first dequeue
    assert release_deadline(10.0, 11.0, 0.005, 0.0, None) == 11.005
    # SLO but no prediction yet -> fixed window
    assert release_deadline(10.0, 11.0, 0.005, 0.1, None) == 11.005
    # SLO + prediction: oldest enqueue + slo - exec - 15% margin
    got = release_deadline(10.0, 10.0, 0.005, 0.100, 0.020)
    assert abs(got - (10.0 + 0.100 - 0.020 - 0.015)) < 1e-9
    # already doomed -> release immediately (never before dequeue)
    assert release_deadline(10.0, 11.0, 0.005, 0.05, 0.04) == 11.0


def test_slo_admission_sheds_before_collapse(two_logregs):
    """A fleet whose every replica's predicted completion exceeds the
    SLO sheds at the door with the typed SloShed — before the queue
    builds the violation."""
    from dask_ml_tpu import config

    a, _, Xh = two_logregs
    with config.set(serving_slo_ms=30.0):
        fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                            batch_window_ms=1.0, timeout_ms=0).warmup()
        with fleet:
            # seed execution history so the predictor has mass
            for _ in range(10):
                fleet.predict(Xh[:64])
            from dask_ml_tpu.serving._batching import Request

            for r in fleet.replicas:
                r.pause()
                # fake a slow measured bucket: predicted exec >> SLO
                for _ in range(13):
                    r._exec.observe("predict", 128, 0.5)
                # pile queued rows so completion prediction blows up
                for _ in range(8):
                    r._queue.put(Request(Xh[:100], "predict"))
            with pytest.raises(SloShed):
                fleet.submit(Xh[:100])
            assert obs.counters_snapshot().get("serving_slo_shed",
                                               0) >= 1
            # drain the fakes so shutdown is clean
            for r in fleet.replicas:
                r._queue.drain_all()
                r.resume()


def test_slo_admission_never_sheds_on_ignorance(two_logregs):
    """No execution history -> no prediction -> admission stays open
    (shed only on a confident miss)."""
    from dask_ml_tpu import config

    a, _, Xh = two_logregs
    with config.set(serving_slo_ms=1.0):   # absurdly tight
        fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                            batch_window_ms=1.0, timeout_ms=0)
        with fleet:
            assert fleet.predict(Xh[:4]).shape == (4,)


# -- windowed stats -----------------------------------------------------------

def test_stats_windowed_quantiles(two_logregs):
    """stats() windows: the second call's latency_window_s covers only
    the requests since the first, so a fresh slowdown dominates it
    while the lifetime p99 stays diluted."""
    a, _, Xh = two_logregs
    srv = ModelServer(a, ladder=_ladder(), batch_window_ms=1.0,
                      timeout_ms=0)
    with srv:
        for _ in range(20):
            srv.predict(Xh[:8])
        s1 = srv.stats()
        assert s1["requests"] == 20
        assert s1["latency_window_s"]["p50"] > 0
        # no traffic since the cursor -> empty window, NaN quantiles
        s2 = srv.stats()
        assert np.isnan(s2["latency_window_s"]["p50"])
        assert s2["latency_s"]["p50"] > 0      # lifetime unaffected
        # window sees only the new requests
        for _ in range(5):
            srv.predict(Xh[:8])
        s3 = srv.stats()
        assert s3["latency_window_s"]["p50"] > 0
        assert s3["requests"] == 25
        assert s3["exec_s"], "exec predictor snapshot missing"


def test_histogram_delta_quantiles_unit():
    from dask_ml_tpu.observability._hist import (
        Histogram,
        percentiles_from,
        snapshot_delta,
    )

    h = Histogram()
    for _ in range(100):
        h.observe(0.001)
    prev = h.snapshot()
    for _ in range(50):
        h.observe(1.0)                  # the fresh degradation
    delta = snapshot_delta(h.snapshot(), prev)
    assert delta["count"] == 50
    win = percentiles_from(delta, (50,))["p50"]
    life = h.percentiles((50,))["p50"]
    assert win > 0.4                    # window sees the slowdown
    assert life < 0.1                   # lifetime still diluted


# -- serve-while-training -----------------------------------------------------

def test_serve_while_training_publishes_each_pass(two_logregs):
    """The Incremental partial_fit driver publishes a snapshot per
    pass; the fleet serves the freshest version under traffic and the
    final served outputs match the trained model exactly."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.wrappers import Incremental

    X, y = make_classification(
        n_samples=2000, n_features=12, n_informative=6, random_state=3
    )
    Xh = X.to_numpy().astype(np.float32)
    yh = y.to_numpy()
    classes = np.unique(yh)

    # v1: TWO warm passes so the fleet has something to serve AND the
    # trainer's programs are fully specialized (the first pass compiles
    # at the fresh-zeros weight placement, the second at steady state —
    # same double-warmup the bench does); the measured passes below
    # must then be compile-free
    inc = Incremental(
        SGDClassifier(max_iter=1, random_state=0, shuffle=False),
        shuffle_blocks=False,
    )
    inc.partial_fit(Xh, yh, classes=classes)
    inc.partial_fit(Xh, yh, classes=classes)
    fleet = FleetServer(inc.estimator_, name="online", replicas=2,
                        ladder=_ladder(), batch_window_ms=1.0,
                        timeout_ms=0).warmup()
    flips = []
    with fleet:
        before = obs.counters_snapshot().get("recompiles", 0)
        stop = threading.Event()
        errs = []

        def client():
            rng = np.random.RandomState(0)
            while not stop.is_set():
                n = rng.randint(1, 50)
                i = rng.randint(0, Xh.shape[0] - n)
                try:
                    out = fleet.predict(Xh[i:i + n])
                except ServingError as exc:
                    errs.append(repr(exc))
                    continue
                if out.shape != (n,):
                    errs.append(f"bad shape {out.shape}")

        t = threading.Thread(target=client)
        t.start()
        serve_while_training(
            fleet, inc, Xh, yh, passes=3, classes=classes,
            on_pass=lambda p, v: flips.append((p, v)),
        )
        stop.set()
        t.join()
        after = obs.counters_snapshot().get("recompiles", 0)
        # the served model IS the final trained snapshot (checked after
        # the counter read: the DIRECT predict below may compile its
        # own program at this shape — that is not serving's bill)
        want = np.asarray(inc.estimator_.predict(Xh[:64]))
        np.testing.assert_array_equal(fleet.predict(Xh[:64]), want)
    assert not errs, errs[:3]
    assert [p for p, _ in flips] == [1, 2, 3]
    vs = [v for _, v in flips]
    assert vs == sorted(vs) and len(set(vs)) == 3
    assert fleet.version == vs[-1]
    assert after - before == 0, (
        f"{after - before} recompiles while serving-while-training"
    )
    assert fleet.registry.versions("online")[-1] == vs[-1]


# -- fleet on a pipeline / rebuild path ---------------------------------------

def test_fleet_rebuild_on_incompatible_publish(two_logregs):
    """A shape-incompatible publish cannot hot-swap; the fleet rebuilds
    entry points (paying compiles, counted) and keeps serving."""
    a, _, Xh = two_logregs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X3, y3 = make_classification(
        n_samples=500, n_features=12, n_informative=6, n_classes=3,
        random_state=1,
    )
    multi = LogisticRegression(solver="lbfgs", max_iter=20).fit(X3, y3)
    fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0).warmup()
    with fleet:
        rebuilds0 = obs.counters_snapshot().get("serving_swap_rebuilds",
                                                0)
        fleet.publish(multi)   # (3, 12) coef vs (1, 12): rebuild path
        np.testing.assert_array_equal(
            fleet.predict(Xh[:30]), np.asarray(multi.predict(Xh[:30]))
        )
        assert obs.counters_snapshot().get(
            "serving_swap_rebuilds", 0
        ) == rebuilds0 + 2     # one rebuild per replica
