"""Dask-aware scorers. Ref: ``dask_ml/metrics/scorer.py`` (SURVEY.md §2a
Metrics row): SCORERS / get_scorer / check_scoring working on sharded
inputs."""

from __future__ import annotations

from .classification import (accuracy_score, average_precision_score,
                             balanced_accuracy_score, f1_score, log_loss,
                             precision_score, recall_score,
                             roc_auc_score)
from .regression import (
    explained_variance_score,
    max_error,
    mean_absolute_error,
    mean_squared_error,
    mean_squared_log_error,
    median_absolute_error,
    r2_score,
)


class _MetricScorer:
    """Picklable scorer (fitted searches store ``scorer_``; a closure
    would make every fitted search unpicklable).

    ``needs_threshold``: score on continuous outputs —
    ``decision_function`` first, ``predict_proba[:, 1]`` as fallback
    (sklearn's threshold-scorer contract, used by roc_auc).
    ``forward_labels``: pass ``labels=estimator.classes_`` through so a
    CV fold missing a class still scores, and label→code mapping needs
    no host unique of the fold."""

    def __init__(self, metric, sign, needs_proba, needs_threshold=False,
                 forward_labels=False, kwargs=None):
        self.metric = metric
        self.sign = sign
        self.needs_proba = needs_proba
        self.needs_threshold = needs_threshold
        self.forward_labels = forward_labels
        self.kwargs = kwargs or {}

    def __call__(self, estimator, X, y):
        kw = dict(self.kwargs)
        classes = getattr(estimator, "classes_", None)
        if (self.needs_proba or self.forward_labels) \
                and classes is not None:
            import numpy as _np

            kw["labels"] = _np.asarray(classes)
        if self.needs_proba:
            pred = estimator.predict_proba(X)
        elif self.needs_threshold:
            try:
                pred = estimator.decision_function(X)
            except (AttributeError, NotImplementedError):
                pred = estimator.predict_proba(X)
        else:
            pred = estimator.predict(X)
        return self.sign * self.metric(y, pred, **kw)


def _make_scorer(metric, greater_is_better=True, needs_proba=False,
                 needs_threshold=False, forward_labels=False, **kwargs):
    return _MetricScorer(metric, 1.0 if greater_is_better else -1.0,
                         needs_proba, needs_threshold, forward_labels,
                         kwargs)


SCORERS = {
    "accuracy": _make_scorer(accuracy_score),
    "neg_mean_squared_error": _make_scorer(mean_squared_error,
                                           greater_is_better=False),
    "neg_mean_absolute_error": _make_scorer(mean_absolute_error,
                                            greater_is_better=False),
    "neg_root_mean_squared_error": _make_scorer(
        mean_squared_error, greater_is_better=False, squared=False),
    "neg_mean_squared_log_error": _make_scorer(
        mean_squared_log_error, greater_is_better=False),
    "neg_median_absolute_error": _make_scorer(
        median_absolute_error, greater_is_better=False),
    "explained_variance": _make_scorer(explained_variance_score),
    "max_error": _make_scorer(max_error, greater_is_better=False),
    "neg_log_loss": _make_scorer(log_loss, greater_is_better=False,
                                 needs_proba=True),
    "r2": _make_scorer(r2_score),
    # device-resident scorers for the most common classification
    # strings (VERDICT r4 missing #4). Unknown STRINGS raise (sklearn
    # behavior); only user CALLABLES get the host-adapting interop that
    # gathers test folds — so every string here scores fold-resident
    "roc_auc": _make_scorer(roc_auc_score, needs_threshold=True,
                            forward_labels=True),
    "average_precision": _make_scorer(average_precision_score,
                                      needs_threshold=True,
                                      forward_labels=True),
    "balanced_accuracy": _make_scorer(balanced_accuracy_score,
                                      forward_labels=True),
    "f1": _make_scorer(f1_score, forward_labels=True),
    "f1_macro": _make_scorer(f1_score, forward_labels=True,
                             average="macro"),
    "f1_micro": _make_scorer(f1_score, forward_labels=True,
                             average="micro"),
    "f1_weighted": _make_scorer(f1_score, forward_labels=True,
                                average="weighted"),
    "precision": _make_scorer(precision_score, forward_labels=True),
    "precision_macro": _make_scorer(precision_score, forward_labels=True,
                                    average="macro"),
    "precision_micro": _make_scorer(precision_score, forward_labels=True,
                                    average="micro"),
    "precision_weighted": _make_scorer(precision_score,
                                       forward_labels=True,
                                       average="weighted"),
    "recall": _make_scorer(recall_score, forward_labels=True),
    "recall_macro": _make_scorer(recall_score, forward_labels=True,
                                 average="macro"),
    "recall_micro": _make_scorer(recall_score, forward_labels=True,
                                 average="micro"),
    "recall_weighted": _make_scorer(recall_score, forward_labels=True,
                                    average="weighted"),
}


import collections as _collections
import threading as _threading

# host copies of recently-scored folds, keyed by id. The ShardedArray is
# pinned in the value so a GC'd-and-reused id can never alias a stale
# copy; bounded by BYTES (folds vary wildly in size — a count bound
# could pin GBs) and evicted LRU. Searches score folds from worker
# threads concurrently, hence the lock. Without the cache, a search
# with N candidates gathers the SAME fold N times.
_HOST_FOLD_CACHE: "_collections.OrderedDict" = _collections.OrderedDict()
_HOST_FOLD_CACHE_MAX_BYTES = 256 * 1024 * 1024
_HOST_FOLD_CACHE_LOCK = _threading.Lock()


def _to_host_cached(a):
    key = id(a)
    with _HOST_FOLD_CACHE_LOCK:
        hit = _HOST_FOLD_CACHE.get(key)
        if hit is not None and hit[0] is a:
            _HOST_FOLD_CACHE.move_to_end(key)
            return hit[1]
    h = a.to_numpy()
    with _HOST_FOLD_CACHE_LOCK:
        _HOST_FOLD_CACHE[key] = (a, h)
        total = sum(v[1].nbytes for v in _HOST_FOLD_CACHE.values())
        while total > _HOST_FOLD_CACHE_MAX_BYTES and len(_HOST_FOLD_CACHE) > 1:
            _, (_, ev) = _HOST_FOLD_CACHE.popitem(last=False)
            total -= ev.nbytes
    return h


def clear_host_fold_cache():
    """Drop all pinned fold copies (device buffers + host arrays).

    Searches call this when a fit completes so fold memory doesn't
    outlive the search."""
    with _HOST_FOLD_CACHE_LOCK:
        _HOST_FOLD_CACHE.clear()


class _HostAdaptingScorer:
    """Wrap an EXTERNAL scorer callable (sklearn make_scorer object, user
    function). The raw call runs first — sharded-aware scorers (built on
    this package's metrics) keep their device-resident path untouched.
    Only if the scorer rejects the inputs (sklearn's validation raises on
    ShardedArray) is it retried with host-converted folds. A class (not a
    closure) so fitted searches holding it stay picklable when the
    wrapped scorer itself pickles (sklearn scorer objects do)."""

    def __init__(self, scorer):
        self.scorer = scorer

    def __call__(self, estimator, X, y=None, **kwargs):
        from ..parallel.sharded import ShardedArray

        sharded = isinstance(X, ShardedArray) or isinstance(y, ShardedArray)
        try:
            return self.scorer(estimator, X, y, **kwargs)
        except (ValueError, TypeError, AttributeError):
            if not sharded:
                raise
        Xh = _to_host_cached(X) if isinstance(X, ShardedArray) else X
        yh = _to_host_cached(y) if isinstance(y, ShardedArray) else y
        return self.scorer(estimator, Xh, yh, **kwargs)


def _host_adapting(scorer):
    return _HostAdaptingScorer(scorer)


def get_scorer(scoring, compute=True):
    if callable(scoring):
        return _host_adapting(scoring)
    try:
        return SCORERS[scoring]
    except KeyError:
        raise ValueError(
            f"{scoring!r} is not a valid scoring value; options: "
            f"{sorted(SCORERS)}"
        )


def _default_scorer(estimator, X, y):
    """Module-level (hence PICKLABLE — fitted searches store scorer_)
    delegation to the estimator's own score method."""
    return estimator.score(X, y)


def check_scoring(estimator, scoring=None, **kwargs):
    if scoring is None:
        if not hasattr(estimator, "score"):
            raise TypeError(
                f"estimator {estimator!r} has no score method; pass scoring="
            )
        return _default_scorer
    return get_scorer(scoring)
