"""Quality observability: training profiles, drift scores, a hot-swap
canary — watching the DATA, not just the system.

The live plane (`examples/09`) shows where time goes; this example
shows what the quality plane sees when production data misbehaves:

1. a streamed fit attaches a per-feature **training profile**
   (``training_profile_``: moments + fixed-boundary histograms, folded
   on the host staging path — zero device syncs);
2. a served model folds admitted rows into **serving sketches**, and
   the drift engine scores serve-vs-train PSI/KS per feature —
   in-distribution traffic scores near zero;
3. a **hot swap** scores a shadow sample of recent traffic against
   both versions through the warmed entry points (zero new compiles):
   the canary's disagreement rate says how differently the new version
   answers the SAME requests;
4. a **+3σ covariate shift** in the request stream pushes the drift
   score over ``config.obs_drift_threshold`` and latches
   ``drift_alerts_total`` — the page an operator gets BEFORE accuracy
   quietly collapses.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.observability import drift
from dask_ml_tpu.serving import BucketLadder, FleetServer

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 40_000))
rng = np.random.RandomState(0)
X = rng.randn(n, 8).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
y2 = (X[:, 1] > 0).astype(np.float32)   # v2 learns a DIFFERENT concept

# 1) streamed fits attach training profiles (obs_drift defaults on)
with config.set(stream_block_rows=max(n // 8, 512)):
    v1 = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
    v2 = SGDClassifier(max_iter=2, random_state=7).fit(X, y2)
from dask_ml_tpu.observability.sketch import profile_from_dict

prof = v1.training_profile_
stats = profile_from_dict(prof).stats()
print(f"training profile: {prof['rows']} rows x "
      f"{prof['n_features']} features; "
      f"feature means {np.round(stats['mean'], 3)}")

drift.reset()
threshold = config.get_config().obs_drift_threshold

with config.set(obs_shadow_fraction=1.0, obs_drift_interval_s=0.0):
    fleet = FleetServer(v1, name="demo", replicas=1,
                        ladder=BucketLadder(8, 128, 2.0),
                        batch_window_ms=0.5, timeout_ms=0).warmup()
    with fleet:
        # 2) in-distribution traffic: drift stays quiet
        for i in range(150):
            lo = (i * 60) % (n - 60)
            fleet.predict(X[lo:lo + 50])
        quiet = [r for r in drift.compute()
                 if r["pair"] == "train_serve"]
        print(f"control  max PSI = {max(r['psi'] for r in quiet):.4f} "
              f"(threshold {threshold})")

        # 3) hot swap -> shadow canary against both versions
        before = obs.counters_snapshot().get("recompiles", 0)
        fleet.publish(v2)
        minted = obs.counters_snapshot().get("recompiles", 0) - before
        can = drift.status_block()["canaries"][0]
        print(f"canary   v{can['version_from']}->v{can['version_to']}: "
              f"disagreement {can['disagreement']:.2f} on "
              f"{can['n_rows']} shadow rows, {minted} new compiles")

        # 4) covariate shift: the page fires
        for i in range(150):
            lo = (i * 60) % (n - 60)
            fleet.predict(X[lo:lo + 50] + 3.0)
        loud = [r for r in drift.compute()
                if r["pair"] == "train_serve" and r["version"] == 2]
        worst = max(loud, key=lambda r: r["psi"])
        alerts = obs.counters_snapshot().get("drift_alerts", 0)
        print(f"shifted  max PSI = {worst['psi']:.2f} on "
              f"{worst['feature']} -> drift_alerts_total = {alerts}")

assert max(r["psi"] for r in quiet) < threshold
assert worst["psi"] > threshold and alerts >= 1 and minted == 0
drift.reset()
print("quality plane OK: quiet control, loud shift, free canary")
