"""Wide features on a 2-D ("data", "model") mesh (ISSUE 18).

When d gets wide, the 1-D streamed path stages full (block_rows, d)
slabs per device — per-chip staging grows linearly in d until it no
longer fits. `config.mesh_shape = "DxM"` reshapes the streamed pool
into a 2-D hybrid mesh: each device stages a (rows/D, ceil(d/M))
feature TILE, the GLM reducers and streamed randomized PCA run their
feature-sharded flavors (one psum over "model" exactly where the math
contracts over features), and per-chip staging stays flat in d.

`config.stream_device_byte_budget` makes that capacity story concrete
off-TPU: with a budget set, the 1-D fit refuses TYPED
(`StreamBudgetExceeded`, pointing at `mesh_shape`) and the identical
fit completes on a 2-D mesh. This example walks that refusal-then-lift
for LogisticRegression and streamed randomized PCA.

Run anywhere: on a TPU VM this uses every chip; on a CPU host set
XLA_FLAGS=--xla_force_host_platform_device_count=8 to simulate an
8-device pool.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 65_536))
D = 512  # wide: the whole point

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.models.pca import PCA
from dask_ml_tpu.parallel.mesh import mesh_str, model_shards, stream_data_mesh
from dask_ml_tpu.parallel.streaming import StreamBudgetExceeded

import jax

if len(jax.devices()) < 2:
    print("needs >= 2 devices for a 2-D mesh "
          "(set XLA_FLAGS=--xla_force_host_platform_device_count=8); skipping")
    sys.exit(0)

rng = np.random.RandomState(0)
# decaying column spectrum: keeps the randomized-SVD range finder
# well-posed AND gives PCA something to explain (flat Gaussian noise
# has no preferred subspace)
scales = (100.0 * 0.8 ** np.arange(D)).astype(np.float32)
Z = rng.randn(N, D).astype(np.float32)
X = Z * scales + 1.5
w = (rng.randn(D) / np.sqrt(D)).astype(np.float32)
y = (Z @ w + 0.1 * rng.randn(N).astype(np.float32) > 0).astype(np.float32)
# standardized view for the GLM (same shape, same staging bytes — the
# budget story below is about geometry, not values)
_std = X.std(axis=0)
_std[_std == 0] = 1.0  # tail columns underflow to constant
Xg = ((X - X.mean(axis=0)) / _std).astype(np.float32)

# Per-device staged super-block bytes are K x block_rows/D x ceil(d/M) x 4.
# At K=4, block_rows=512, d=512: single-device 1-D stages ~4.2 MB;
# a "-1x4" mesh stages ~0.5 MB per device. A 2 MB budget sits between.
BUDGET = 2_000_000
base = dict(dtype="float32", stream_block_rows=512, superblock_k=4,
            stream_autotune=False, stream_device_byte_budget=BUDGET)

# -- 1. the 1-D path refuses, typed -----------------------------------------
try:
    with config.set(stream_mesh=1, **base):
        LogisticRegression(solver="lbfgs", max_iter=5).fit(Xg, y)
    raise SystemExit("expected StreamBudgetExceeded on the 1-D path")
except StreamBudgetExceeded as e:
    print(f"1-D refusal (typed): {str(e)[:110]}...")

# -- 2. the same fit completes on the 2-D mesh ------------------------------
with config.set(mesh_shape="-1x4", **base):
    mesh = stream_data_mesh()
    print(f"2-D mesh: {mesh_str(mesh)} "
          f"({model_shards(mesh)} feature shards per row slab)")
    clf = LogisticRegression(solver="lbfgs", max_iter=20)
    clf.fit(Xg, y)
    acc = clf.score(Xg, y)
    before = obs.counters_snapshot().get("recompiles", 0)
    clf.fit(Xg, y)  # refit: warm jit caches only
    recompiles = obs.counters_snapshot().get("recompiles", 0) - before
print(f"feature-sharded GLM: acc={acc:.3f}, "
      f"refit recompiles={recompiles} (contract: 0)")
assert recompiles == 0

# -- 3. streamed randomized PCA through the same mesh -----------------------
with config.set(mesh_shape="-1x4", **base):
    pca = PCA(n_components=8, svd_solver="randomized", random_state=0)
    pca.fit(X)

# cross-check the top singular values against a resident eigendecomposition
# of the (cheap, d x d) covariance — parity is the contract, not a demo
Xc = X - X.mean(axis=0)
evals = np.linalg.eigvalsh((Xc.T @ Xc).astype(np.float64))[::-1]
sv_ref = np.sqrt(np.maximum(evals[:8], 0.0))
rel = np.max(np.abs(pca.singular_values_ - sv_ref) / sv_ref)
print(f"streamed randomized PCA: evr_sum={pca.explained_variance_ratio_.sum():.4f}, "
      f"top-8 singular-value rel err vs resident = {rel:.2e}")
assert rel < 1e-3

# -- 4. where to see it ------------------------------------------------------
# The report CLI / /status show mesh=DxM on every streamed pass and a
# `mesh` column on the feature-sharded programs; program names carry the
# flavor: superblock.glm.*.model_psum, superblock.pca.{moments,range}.*.
from dask_ml_tpu import plans

names = [r["program"] for r in plans.plans_snapshot()
         if ".model_psum" in r["program"]]
print("feature-sharded programs:", ", ".join(sorted(set(names))))
