"""Blockwise ensemble tests (ref: tests for dask_ml/ensemble/_blockwise.py)."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression as SkLinear
from sklearn.linear_model import LogisticRegression as SkLogistic

from dask_ml_tpu.datasets import make_classification, make_regression
from dask_ml_tpu.ensemble import (
    BlockwiseVotingClassifier,
    BlockwiseVotingRegressor,
)
from dask_ml_tpu.parallel import ShardedArray, default_mesh


def test_voting_classifier_hard():
    X, y = make_classification(n_samples=400, n_features=8, random_state=0)
    clf = BlockwiseVotingClassifier(SkLogistic(max_iter=300)).fit(X, y)
    assert len(clf.estimators_) == default_mesh().devices.size
    pred = clf.predict(X)
    assert isinstance(pred, ShardedArray)
    assert clf.score(X, y) > 0.7
    with pytest.raises(AttributeError, match="soft"):
        clf.predict_proba(X)


def test_voting_classifier_soft():
    X, y = make_classification(n_samples=400, n_features=8, random_state=0)
    clf = BlockwiseVotingClassifier(
        SkLogistic(max_iter=300), voting="soft"
    ).fit(X, y)
    proba = clf.predict_proba(X).to_numpy()
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert clf.score(X, y) > 0.7


def test_voting_classifier_bad_voting():
    X, y = make_classification(n_samples=100, n_features=4, random_state=0)
    with pytest.raises(ValueError, match="voting"):
        BlockwiseVotingClassifier(SkLogistic(), voting="mean").fit(X, y)


def test_voting_regressor():
    X, y = make_regression(n_samples=400, n_features=8, random_state=0)
    reg = BlockwiseVotingRegressor(SkLinear()).fit(X, y)
    assert len(reg.estimators_) == default_mesh().devices.size
    assert reg.score(X, y) > 0.8


def test_soft_voting_is_member_average():
    """Soft voting = the mean of the per-block members' predict_proba
    (the reference's blockwise averaging), verified against a manual
    average over estimators_."""
    X, y = make_classification(n_samples=400, n_features=8, random_state=1)
    clf = BlockwiseVotingClassifier(
        SkLogistic(max_iter=300), voting="soft"
    ).fit(X, y)
    Xh = X.to_numpy() if hasattr(X, "to_numpy") else np.asarray(X)
    manual = np.mean(
        [m.predict_proba(Xh) for m in clf.estimators_], axis=0
    )
    got = clf.predict_proba(X)
    got = got.to_numpy() if hasattr(got, "to_numpy") else np.asarray(got)
    np.testing.assert_allclose(got, manual, atol=1e-6)


def test_hard_voting_majority():
    """Hard voting picks the majority label across members."""
    X, y = make_classification(n_samples=300, n_features=6, random_state=2)
    clf = BlockwiseVotingClassifier(
        SkLogistic(max_iter=200), classes=[0, 1]
    ).fit(X, y)
    Xh = X.to_numpy() if hasattr(X, "to_numpy") else np.asarray(X)
    votes = np.stack([m.predict(Xh) for m in clf.estimators_])
    majority = (votes.mean(axis=0) > 0.5).astype(float)
    got = clf.predict(X)
    got = got.to_numpy() if hasattr(got, "to_numpy") else np.asarray(got)
    # ties (exact .5) may break either way; compare only clear majorities
    clear = votes.mean(axis=0) != 0.5
    np.testing.assert_array_equal(got[clear], majority[clear])
