"""Ref: dask_ml/feature_extraction/__init__.py."""
from . import text
