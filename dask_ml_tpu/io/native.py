"""ctypes bindings for the native data loader (native/fast_loader.cpp).

Compiled on demand with g++ (the image has the toolchain but no
pybind11 — SURVEY.md environment notes); falls back to numpy text parsing
when compilation is unavailable. The loader feeds
``parallel/streaming.BlockStream`` — parse into pinned host memory, then
stream blocks to the mesh.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_lib_failed = False

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "fast_loader.cpp")
_SO = os.path.join(_ROOT, "native", "_fast_loader.so")


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", _SO, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def load_library():
    """The compiled library, building it if needed; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.csv_dims.restype = ctypes.c_int64
            lib.csv_dims.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_int64)]
            lib.csv_parse_f32.restype = ctypes.c_int64
            lib.csv_parse_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def read_csv_f32(path, n_threads=None) -> np.ndarray:
    """Parse a numeric CSV (comma/space/tab separated, no header) into a
    float32 array with the native multithreaded parser; numpy fallback."""
    path = os.path.abspath(path)
    lib = load_library()
    if lib is None:
        return np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 16)
    n_cols = ctypes.c_int64(0)
    n_rows = lib.csv_dims(path.encode(), ctypes.byref(n_cols))
    if n_rows < 0:
        raise IOError(f"cannot read {path!r} (code {n_rows})")
    out = np.empty((n_rows, n_cols.value), np.float32)
    got = lib.csv_parse_f32(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_rows, n_cols.value, n_threads,
    )
    if got < 0:
        raise ValueError(
            f"malformed CSV {path!r} (code {got}); expected "
            f"{n_cols.value} numeric columns per row"
        )
    return out[:got]


def read_csv_sharded(path, mesh=None, n_threads=None):
    """CSV straight onto the mesh: native parse -> ShardedArray."""
    from ..parallel.sharded import as_sharded

    return as_sharded(read_csv_f32(path, n_threads=n_threads), mesh=mesh)
