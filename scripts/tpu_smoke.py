"""Whole-surface smoke on the REAL TPU backend.

The test suite runs on a virtual CPU mesh (tests/conftest.py); Mosaic/XLA
TPU lowering differs (tiling constraints, layout rules), so every
estimator gets exercised here on the actual chip. Run manually or from CI
with a TPU attached:

    python scripts/tpu_smoke.py
"""

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# Resumable runs: with TPU_SMOKE_STATE=<path>, every passing surface is
# recorded write-through, and a rerun skips surfaces already green —
# so a tunnel that wedges mid-suite only costs the surface it died in,
# not the ones before it. Delete the state file for a full rerun.
_STATE_PATH = os.environ.get("TPU_SMOKE_STATE", "")


def _load_state():
    if _STATE_PATH and os.path.exists(_STATE_PATH):
        try:
            with open(_STATE_PATH) as f:
                return set(json.load(f))
        except (ValueError, OSError):
            return set()
    return set()


def _record_pass(passed):
    if _STATE_PATH:
        with open(_STATE_PATH, "w") as f:
            json.dump(sorted(passed), f)


def run(name, fn, passed):
    if name in passed:
        print(f"  SKIP {name} (passed in an earlier resumable run)")
        return True
    t0 = time.perf_counter()
    try:
        fn()
        print(f"  OK   {name} ({time.perf_counter() - t0:.1f}s)")
        passed.add(name)
        _record_pass(passed)
        return True
    except Exception:
        print(f"  FAIL {name}")
        traceback.print_exc()
        return False


def main():
    import jax

    print("backend:", jax.default_backend(), jax.devices())
    from dask_ml_tpu import datasets

    X, y = datasets.make_classification(
        n_samples=20_000, n_features=32, random_state=0
    )
    Xr, yr = datasets.make_regression(
        n_samples=20_000, n_features=32, random_state=0
    )
    Xc, yc = datasets.make_counts(
        n_samples=10_000, n_features=16, random_state=0
    )
    results = []

    def glms():
        from dask_ml_tpu.linear_model import (
            LinearRegression, LogisticRegression, PoissonRegression,
        )

        for solver in ("lbfgs", "newton", "admm", "gradient_descent",
                       "proximal_grad"):
            clf = LogisticRegression(solver=solver, max_iter=20).fit(X, y)
            assert 0.5 < clf.score(X, y) <= 1.0, (solver, clf.score(X, y))
        LinearRegression(solver="lbfgs", max_iter=30).fit(Xr, yr)
        PoissonRegression(solver="lbfgs", max_iter=30).fit(Xc, yc)

    def sgd():
        from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor

        SGDClassifier(max_iter=5).fit(X, y).score(X, y)
        SGDRegressor(max_iter=5).fit(Xr, yr).predict(Xr)

    def kmeans():
        from dask_ml_tpu.cluster import KMeans

        km = KMeans(n_clusters=8, random_state=0, max_iter=30).fit(X)
        assert km.inertia_ > 0
        km.predict(X); km.transform(X)

    def spectral():
        from dask_ml_tpu.cluster import SpectralClustering

        Xs, _ = datasets.make_blobs(n_samples=3000, n_features=5, centers=3,
                                    random_state=0)
        sc = SpectralClustering(n_clusters=3, n_components=100,
                                random_state=0).fit(Xs)
        assert len(sc.labels_.to_numpy()) == 3000

    def decomposition():
        from dask_ml_tpu.decomposition import (
            IncrementalPCA, PCA, TruncatedSVD,
        )

        for solver in ("tsqr", "randomized"):
            p = PCA(n_components=5, svd_solver=solver, random_state=0).fit(X)
            assert p.components_.shape == (5, 32)
            p.transform(X)
        TruncatedSVD(n_components=5, random_state=0).fit(X).transform(X)
        IncrementalPCA(n_components=5).fit(X).transform(X)

    def preprocessing():
        from dask_ml_tpu.preprocessing import (
            MinMaxScaler, PolynomialFeatures, QuantileTransformer,
            RobustScaler, StandardScaler,
        )

        for T in (StandardScaler, MinMaxScaler, RobustScaler):
            T().fit_transform(X)
        QuantileTransformer(n_quantiles=100).fit_transform(X)
        PolynomialFeatures(degree=2).fit_transform(
            datasets.make_classification(n_samples=2000, n_features=6,
                                         random_state=0)[0]
        )

    def naive_bayes_impute():
        from dask_ml_tpu.impute import SimpleImputer
        from dask_ml_tpu.naive_bayes import GaussianNB

        GaussianNB().fit(X, y).score(X, y)
        Xn = X.to_numpy().copy()
        Xn[::7, 0] = np.nan
        SimpleImputer().fit_transform(Xn)

    def metrics_pairwise():
        from dask_ml_tpu import metrics as m

        Yc = np.random.RandomState(0).randn(16, 32).astype(np.float32)
        m.pairwise_distances(X, Yc)
        m.pairwise_distances_argmin_min(X, Yc)
        m.euclidean_distances(X, Yc)
        m.rbf_kernel(X, Yc)

    def search():
        from sklearn.linear_model import SGDClassifier as SkSGD

        from dask_ml_tpu.model_selection import (
            GridSearchCV, HyperbandSearchCV, train_test_split,
        )
        from dask_ml_tpu.linear_model import LogisticRegression

        train_test_split(X, y, test_size=0.2, random_state=0)
        gs = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=10),
            {"C": [0.1, 1.0]}, cv=2,
        ).fit(X, y)
        # a pure-C grid must take the stacked-lam fast path (one
        # compiled solve for the whole grid per fold)
        assert getattr(gs, "_c_grid_vmapped_", None) == 2, \
            "C-grid fast path not taken"
        # and through a Pipeline (prefix once per fold + stacked solve)
        from sklearn.pipeline import Pipeline

        from dask_ml_tpu.preprocessing import StandardScaler

        gp = GridSearchCV(
            Pipeline([("scale", StandardScaler()),
                      ("clf", LogisticRegression(solver="lbfgs",
                                                 max_iter=10))]),
            {"clf__C": [0.1, 1.0]}, cv=2,
        ).fit(X, y)
        assert getattr(gp, "_c_grid_vmapped_", None) == 2, \
            "pipeline C-grid fast path not taken"
        HyperbandSearchCV(
            SkSGD(tol=1e-3), {"alpha": [1e-4, 1e-3, 1e-2]},
            max_iter=4, aggressiveness=2, random_state=0,
        ).fit(X, y, classes=[0, 1])

    def wrappers_ensemble():
        from sklearn.linear_model import SGDClassifier as SkSGD

        from dask_ml_tpu.ensemble import BlockwiseVotingClassifier
        from dask_ml_tpu.wrappers import Incremental, ParallelPostFit

        ParallelPostFit(SkSGD(tol=1e-3)).fit(X, y).predict(X)
        Incremental(SkSGD(tol=1e-3)).fit(X, y, classes=[0, 1]).predict(X)
        BlockwiseVotingClassifier(SkSGD(tol=1e-3), classes=[0, 1]).fit(
            X, y
        ).predict(X)

    def streaming():
        from dask_ml_tpu.parallel.streaming import BlockStream

        Xh, yh = X.to_numpy(), y.to_numpy()
        total = 0
        for blk in BlockStream((Xh, yh), block_rows=4096):
            total += blk.n_rows
        assert total == len(Xh), total

    def round5_surfaces():
        """Round-5 surfaces on the real chip: sparse CSR streaming
        bridge, device roc_auc/f1 scorers, bf16 matmul policy
        (KMeans distances + fused SGD epoch grid at bf16/f32-acc),
        streamed-SGD overlap stats."""
        import scipy.sparse as sp

        import dask_ml_tpu.config as config
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.metrics import f1_score, roc_auc_score
        from dask_ml_tpu.metrics.scorer import get_scorer
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        rng = np.random.RandomState(11)
        Xcsr = sp.random(20_000, 512, density=0.05, format="csr",
                         random_state=rng)
        rowsum = np.asarray(Xcsr.sum(axis=1)).ravel()
        ycsr = (rowsum > np.median(rowsum)).astype(np.float32)
        with config.set(stream_block_rows=4096):
            spc = LogisticRegression(solver="lbfgs", max_iter=20).fit(
                Xcsr, ycsr
            )
        assert np.isfinite(spc.coef_).all()
        clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
        auc = get_scorer("roc_auc")(clf, X, y)
        assert 0.5 < auc <= 1.0, auc
        yh, ph = y.to_numpy(), clf.predict(X)
        import sklearn.metrics as skm

        assert abs(f1_score(yh, ph) - skm.f1_score(yh, ph)) < 1e-6
        df = clf.decision_function(X)
        assert abs(roc_auc_score(yh, df) - skm.roc_auc_score(yh, df)) \
            < 1e-5
        with config.set(dtype="bfloat16"):
            km16 = KMeans(n_clusters=4, random_state=0, max_iter=5,
                          use_pallas=False).fit(X)
            assert np.isfinite(km16.cluster_centers_).all()
            inc = Incremental(SGDClassifier(max_iter=1, random_state=0),
                              shuffle_blocks=False)
            inc.fit(X, y)
            assert np.isfinite(inc.estimator_.coef_).all()
        # streamed SGD with overlap stats on host blocks
        Xh2 = np.asarray(X.to_numpy(), np.float32)
        s2 = SGDClassifier(max_iter=2, random_state=0, shuffle=False)
        s2.fit(Xh2, y.to_numpy())
        st = s2._last_stream_stats
        assert st and st["pass_s"] > 0

    def multiclass_round4():
        """Round-4 surfaces: multiclass in-core AND streamed OvR GLM,
        multiclass SGD submesh trials, OneHotEncoder(drop), sketched
        QuantileTransformer subsample — all Mosaic-lowered here."""
        from dask_ml_tpu import config
        from dask_ml_tpu.linear_model import (
            LogisticRegression, SGDClassifier,
        )
        from dask_ml_tpu.model_selection import IncrementalSearchCV
        from dask_ml_tpu.preprocessing import (
            OneHotEncoder, QuantileTransformer,
        )

        Xm, ym = datasets.make_classification(
            n_samples=6000, n_features=16, n_classes=3, n_informative=8,
            random_state=3,
        )
        clf = LogisticRegression(solver="lbfgs", max_iter=40).fit(Xm, ym)
        assert clf.coef_.shape == (3, 16)
        if jax.default_backend() == "tpu":
            # auto-gate: the multi-target fused kernel (one X pass for
            # all classes) must have carried the compiled solve
            assert clf.solver_info_.get("fused_multi") is True, \
                clf.solver_info_
        lp = clf.predict_log_proba(Xm)
        assert lp.shape == (6000, 3) and (lp <= 0).all()
        Xh, yh = Xm.to_numpy(), ym.to_numpy()
        with config.set(stream_block_rows=1500):
            st = LogisticRegression(solver="lbfgs", max_iter=40).fit(Xh, yh)
        assert st.solver_info_.get("n_classes") == 3
        assert np.mean(st.predict(Xh) == clf.predict(Xh)) > 0.98
        s = IncrementalSearchCV(
            SGDClassifier(random_state=0), {"alpha": [1e-4, 1e-3]},
            n_initial_parameters="grid", decay_rate=None, max_iter=3,
            random_state=0,
        )
        s.fit(Xm, ym, classes=[0.0, 1.0, 2.0])
        assert s.best_estimator_.coef_.shape == (3, 16)
        Xcat = np.array([[0.0, 1.0], [1.0, 2.0], [0.0, 1.0]])
        o = OneHotEncoder(drop="first").fit(Xcat)
        assert o.transform(Xcat).shape == (3, 2)
        QuantileTransformer(n_quantiles=50, subsample=3000,
                            random_state=0).fit_transform(Xm)
        # fused GLM value+grad Pallas kernel: on TPU the auto-gate runs
        # it COMPILED in every smooth-solver fit above; assert parity
        # against the XLA loss explicitly
        interp = jax.default_backend() != "tpu"  # CPU dry-runs interpret
        # pinned f32: this 5e-3 parity band is the f32 kernels' — the
        # "auto" policy would run both fits bf16 on TPU and compare
        # bf16 rounding noise against it
        xla = LogisticRegression(solver="lbfgs", max_iter=30, tol=1e-8,
                                 fit_dtype="float32",
                                 solver_kwargs={"use_pallas": False})
        pal = LogisticRegression(solver="lbfgs", max_iter=30, tol=1e-8,
                                 fit_dtype="float32",
                                 solver_kwargs={"use_pallas": True,
                                                "pallas_interpret": interp})
        yb2 = (ym.to_numpy() > 1).astype(np.float32)
        xla.fit(Xm, yb2)
        pal.fit(Xm, yb2)
        assert np.allclose(pal.coef_, xla.coef_, atol=5e-3), (
            np.abs(pal.coef_ - xla.coef_).max()
        )

    def fused_stream_round8():
        """ISSUE 8 surfaces on the real chip: the stacked-lax.scan
        super-block flavor (ROADMAP item 1 flags it as never run on
        real hardware — on TPU it IS the streamed layout), the fused
        Pallas streamed kernels (pallas.sgd_step / pallas.glm_* /
        pallas.kmeans_stream engage via the auto-gate at 128-multiple
        block heights), the bf16 "auto" default fit path, and the int8
        serving flavor — all at tiny shapes so Mosaic lowering and
        parity are exercised even on a short tunnel."""
        import dask_ml_tpu.config as config
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.ops.pallas_fused import use_stream_kernels
        from dask_ml_tpu.wrappers import compiled_batch_fn

        on_tpu = jax.default_backend() == "tpu"
        rng = np.random.RandomState(8)
        Xh = rng.randn(16_384, 32).astype(np.float32)
        yh = (Xh[:, 0] > 0).astype(np.float32)
        # bf16 "auto" default: on TPU the policy must resolve to bf16
        if on_tpu:
            assert config.mxu_dtype() is not None, \
                "auto dtype policy did not resolve to bf16 on TPU"
        # 2048-row blocks: a 128-multiple, so the fused kernels' grid
        # gate passes and the stacked (K, S, d) scan flavor runs
        with config.set(stream_block_rows=2048):
            assert use_stream_kernels() == on_tpu
            sgd = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(Xh, yh)
            assert np.isfinite(sgd.coef_).all()
            assert sgd.score(Xh, yh) > 0.7
            st = dict(sgd._last_stream_stats or {})
            assert st.get("superblock_k", 0) > 1, st
            glm = LogisticRegression(solver="lbfgs",
                                     max_iter=20).fit(Xh, yh)
            assert np.isfinite(glm.coef_).all()
            if on_tpu:
                assert glm.solver_info_.get("fused_stream") is True, \
                    glm.solver_info_
            km = KMeans(n_clusters=4, random_state=0, max_iter=5,
                        init="random").fit(Xh)
            assert np.isfinite(km.cluster_centers_).all()
        # parity vs the per-block XLA path on the same partition
        with config.set(stream_block_rows=2048, stream_superblock=False,
                        pallas_stream=False, dtype="float32"):
            ref = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(Xh, yh)
        assert np.mean(sgd.predict(Xh) == ref.predict(Xh)) > 0.99
        # int8 serving flavor compiles + agrees on the real chip
        q8 = compiled_batch_fn(glm, "predict", quantize="int8")
        f32 = compiled_batch_fn(glm, "predict")
        assert np.mean(q8(Xh[:4096]) == f32(Xh[:4096])) >= 0.995

    def sharded_stream_round9():
        """ISSUE 9 surfaces on a real multi-chip slice: the streamed
        superblock hot loop sharded over the mesh — per-shard staging,
        shard_map/psum scan programs, replicated carries. Parity vs
        the single-chip path to 1e-5 (bf16 stays off: f32 pin) and
        per-chip throughput within 0.8x of single-chip — the
        data-parallel plumbing must not eat the chip it runs on. On a
        1-chip attach (or the CPU dry-run) the sharded flavor must
        simply never engage."""
        import time as _time

        from dask_ml_tpu import config
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.sgd import SGDClassifier

        n_dev = len(jax.devices())
        rng = np.random.RandomState(9)
        n, d = 131_072, 64
        Xh = rng.randn(n, d).astype(np.float32)
        yh = (Xh[:, 0] > 0).astype(np.float32)
        # 2048-row blocks: a 128-multiple (single-chip fused kernels)
        # that also splits per shard on any power-of-two slice
        base = dict(stream_block_rows=2048, stream_autotune=False,
                    dtype="float32")

        def timed_fit(stream_mesh):
            with config.set(stream_mesh=stream_mesh, **base):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(Xh, yh)  # warm
                clf = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=False)
                t0 = _time.perf_counter()
                clf.fit(Xh, yh)
                return clf, _time.perf_counter() - t0

        single, t1 = timed_fit(1)
        st1 = dict(single._last_stream_stats or {})
        assert st1.get("sb_shards", 1) == 1, st1
        if n_dev == 1:
            return  # nothing to shard on a 1-chip attach
        sharded, tN = timed_fit(0)
        stN = dict(sharded._last_stream_stats or {})
        assert stN.get("sb_shards") == n_dev, stN
        # one dispatch per super-block, never per shard
        assert stN["dispatches_per_pass"] == \
            -(-stN["n_blocks"] // stN["superblock_k"]), stN
        # parity: same minibatches, psum-reassociated float sums only
        assert np.allclose(sharded.coef_, single.coef_, atol=1e-5), \
            np.abs(sharded.coef_ - single.coef_).max()
        # GLM reducer + KMeans assign-stats flavors run + agree
        with config.set(stream_mesh=0, **base):
            glm = LogisticRegression(solver="lbfgs",
                                     max_iter=15).fit(Xh, yh)
            assert glm.solver_info_.get("stream_shards") == n_dev, \
                glm.solver_info_
            km = KMeans(n_clusters=4, random_state=0, max_iter=5,
                        init="random").fit(Xh)
            assert np.isfinite(km.cluster_centers_).all()
        with config.set(stream_mesh=1, **base):
            glm1 = LogisticRegression(solver="lbfgs",
                                      max_iter=15).fit(Xh, yh)
        assert np.allclose(glm.coef_, glm1.coef_, atol=1e-4), \
            np.abs(glm.coef_ - glm1.coef_).max()
        if jax.default_backend() != "tpu":
            return  # forced virtual devices share silicon: parity and
            # dispatch shape hold above, but the per-chip throughput
            # criterion is a real-chip claim
        # scaling: per-chip throughput within 0.8x of single-chip
        per_chip = (n * 2 / tN) / n_dev
        single_chip = n * 2 / t1
        assert per_chip >= 0.8 * single_chip, (
            f"sharded per-chip throughput {per_chip:.0f} samples/s < "
            f"0.8x single-chip {single_chip:.0f}"
        )
        print(f"    round-9: {n_dev} chips, single {single_chip:.0f} "
              f"samples/s, sharded {per_chip:.0f} samples/s/chip")

    def chaos_round10():
        """ISSUE 11 surfaces on real hardware: one injected-fault
        streamed resume and one supervised replica restart. Auto-
        degrades like round-9 — every leg runs identically on a 1-chip
        attach (thread replicas; the sharded flavor simply never
        engages), so the round gates correctness, not scale."""
        import tempfile
        import time as _time

        from dask_ml_tpu import config
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.observability import (counters_reset,
                                               counters_snapshot)
        from dask_ml_tpu.reliability import FaultInjected, reset_plans
        from dask_ml_tpu.serving.fleet import FleetServer

        rng = np.random.RandomState(11)
        n, d = 65_536, 32
        Xh = rng.randn(n, d).astype(np.float32)
        yh = (Xh[:, 0] > 0).astype(np.float32)
        base = dict(stream_block_rows=2048, stream_autotune=False,
                    dtype="float32")
        # (a) injected staging IO fault absorbed by retry, bit-parity
        counters_reset()
        reset_plans()
        with config.set(**base):
            clean = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=True).fit(Xh, yh)
        with config.set(fault_plan="staging_read:io@5",
                        stream_io_retries=3, **base):
            faulted = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=True).fit(Xh, yh)
        assert counters_snapshot().get("stream_retries", 0) >= 1
        assert np.allclose(faulted.coef_, clean.coef_, atol=1e-6)
        # (b) kill-mid-pass resume parity (crash at the dispatch
        # boundary, then rerun with the same knobs auto-resumes)
        tmp = tempfile.mkdtemp(prefix="tpu_chaos_")
        reset_plans()
        n_sb = -(-((n + 2047) // 2048) // 8)   # dispatches per pass
        with config.set(stream_checkpoint_path=tmp,
                        fault_plan=f"superblock_dispatch:crash@{n_sb}",
                        **base):
            try:
                SGDClassifier(max_iter=2, random_state=0,
                              shuffle=True).fit(Xh, yh)
                raise AssertionError("injected crash never fired")
            except FaultInjected:
                pass
        reset_plans()
        with config.set(stream_checkpoint_path=tmp, **base):
            resumed = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=True).fit(Xh, yh)
        assert counters_snapshot().get("stream_resumes", 0) >= 1
        assert np.allclose(resumed.coef_, clean.coef_, atol=1e-6), \
            np.abs(resumed.coef_ - clean.coef_).max()
        # (c) supervised replica restart under live traffic
        counters_reset()
        reset_plans()
        with config.set(serving_min_batch=8, serving_max_batch=64,
                        serving_supervise=True, obs_drift=False,
                        serving_supervise_interval_s=0.1,
                        fault_plan="replica_worker:crash@60",
                        dtype="float32"):
            fleet = FleetServer(clean, replicas=2,
                                timeout_ms=20000).warmup()
            with fleet:
                served = 0
                deadline = _time.time() + 60
                while _time.time() < deadline:
                    p = fleet.predict(Xh[: int(rng.randint(1, 64))])
                    served += len(p)
                    snap = counters_snapshot()
                    if snap.get("serving_replica_restarts", 0) >= 1 \
                            and sum(1 for r in fleet.replicas
                                    if r.healthy) == 2:
                        break
                assert counters_snapshot().get(
                    "serving_replica_restarts", 0) >= 1, \
                    counters_snapshot()
                assert len(fleet.predict(Xh[:32])) == 32
        print(f"    round-10: resume parity "
              f"{np.abs(resumed.coef_ - clean.coef_).max():.1e}, "
              f"retries absorbed, replica restarted under load")

    def fused_sharded_round11():
        """ISSUE 12 surfaces: the fused Pallas kernels INSIDE the
        shard_map scan programs (real multi-chip: compiled Mosaic; the
        parity legs also run on a 1-chip attach, where the sharded
        flavor simply never engages and the fused single-device flavor
        carries them), plus the grad-accum streamed-SGD flavor.
        Criteria: fused x sharded parity vs the unfused sharded flavor,
        fused actually ENGAGED (solver_info_ reasons, not just absence
        of errors), per-chip throughput >= the unfused sharded flavor,
        and grad-accum A=1 exactly matching the sequential fit."""
        import time as _time

        from dask_ml_tpu import config
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.sgd import SGDClassifier

        on_tpu = jax.default_backend() == "tpu"
        n_dev = len(jax.devices())
        rng = np.random.RandomState(12)
        n, d = 131_072, 64
        Xh = rng.randn(n, d).astype(np.float32)
        yh = (Xh[:, 0] > 0).astype(np.float32)
        # 2048-row blocks divide into 128-multiple slabs on any
        # power-of-two slice up to 16 chips
        base = dict(stream_block_rows=2048, stream_autotune=False,
                    dtype="float32", stream_mesh=0)
        interp = {} if on_tpu else {"pallas_stream_interpret": True}

        def timed_sgd(**kw):
            with config.set(**base, **kw):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(Xh, yh)  # warm
                clf = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=False)
                t0 = _time.perf_counter()
                clf.fit(Xh, yh)
                return clf, _time.perf_counter() - t0

        fused, t_f = timed_sgd(**interp)
        plain, t_p = timed_sgd(pallas_stream=False)
        info = dict(fused.solver_info_)
        assert info.get("fused_stream") is True, info
        assert info.get("fused_stream_reason") is None, info
        st = dict(fused._last_stream_stats or {})
        assert st.get("sb_shards") == n_dev, st
        assert st["dispatches_per_pass"] == \
            -(-st["n_blocks"] // st["superblock_k"]), st
        assert np.allclose(fused.coef_, plain.coef_, atol=1e-5), \
            np.abs(fused.coef_ - plain.coef_).max()
        # GLM + KMeans fused x sharded flavors run + agree + engage
        with config.set(**base, **interp):
            glm = LogisticRegression(solver="lbfgs",
                                     max_iter=15).fit(Xh, yh)
            assert glm.solver_info_.get("fused_stream") is True, \
                glm.solver_info_
            km = KMeans(n_clusters=4, random_state=0, max_iter=5,
                        init="random").fit(Xh)
        with config.set(**base, pallas_stream=False):
            glm0 = LogisticRegression(solver="lbfgs",
                                      max_iter=15).fit(Xh, yh)
            km0 = KMeans(n_clusters=4, random_state=0, max_iter=5,
                         init="random").fit(Xh)
        assert np.allclose(glm.coef_, glm0.coef_, atol=1e-4), \
            np.abs(glm.coef_ - glm0.coef_).max()
        assert np.allclose(np.sort(km.cluster_centers_, axis=0),
                           np.sort(km0.cluster_centers_, axis=0),
                           atol=1e-4)
        # grad-accum flavor: A=1 exactly the sequential fit (bit-exact
        # vs the single-device sequential flavor — the sharded scan
        # normalizes after its psum, so exactness pins stream_mesh=1);
        # A=2 sane
        ga = dict(base, stream_mesh=1)
        with config.set(**ga):
            seq = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(Xh, yh)
        with config.set(**ga, stream_grad_accum=1):
            a1 = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=False).fit(Xh, yh)
        assert a1.solver_info_.get("grad_accum") == 1
        assert np.array_equal(a1.coef_, seq.coef_), \
            np.abs(a1.coef_ - seq.coef_).max()
        with config.set(**ga, stream_grad_accum=2):
            a2 = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=False).fit(Xh, yh)
        # documented tolerance: larger effective batch, same model to
        # ~10% relative (predict would re-stage on the full mesh
        # against the stream_mesh=1-committed weights, so compare coef)
        assert np.isfinite(a2.coef_).all()
        assert np.abs(a2.coef_ - seq.coef_).max() \
            <= 0.1 * max(np.abs(seq.coef_).max(), 1e-6)
        if not on_tpu:
            return  # interpreter-speed kernels: throughput claims are
            # real-chip claims only
        # the fused bodies must not be SLOWER than the XLA bodies they
        # replace (per-chip throughput >= the unfused sharded flavor)
        assert t_f <= t_p * 1.05, (
            f"fused sharded pass slower than unfused: {t_f:.3f}s vs "
            f"{t_p:.3f}s"
        )
        print(f"    round-11: {n_dev} chips, fused "
              f"{n * 2 / t_f:.0f} rows/s vs unfused "
              f"{n * 2 / t_p:.0f} rows/s, grad-accum A=1 exact")

    def sparse_stream_round12():
        """ISSUE 13 surfaces: device-resident bucketed-nnz sparse
        streaming on real chips — the superblock.sparse.* scan programs
        (single-chip AND sharded: a >1-chip attach stages per-shard nnz
        segments and psums once per super-block), the serving
        (rows, nnz) grid, and the >= 2x-vs-densify claim at the
        hashed-text shape. Degrades to a 1-chip attach like rounds
        9/10/11 (the sharded flavor simply never engages)."""
        import time as _time

        import scipy.sparse as sp_

        from dask_ml_tpu import config
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.serving import ModelServer

        on_tpu = jax.default_backend() == "tpu"
        n_dev = len(jax.devices())
        rng = np.random.RandomState(13)
        n, d = 65_536, 2 ** 14
        npr = d // 100                        # density ~1%
        indices = rng.randint(0, d, size=n * npr).astype(np.int32)
        data = rng.rand(n * npr).astype(np.float32)
        indptr = np.arange(0, n * npr + 1, npr, dtype=np.int64)
        Xs = sp_.csr_matrix((data, indices, indptr), shape=(n, d))
        eta = Xs @ rng.randn(d).astype(np.float32)
        yh = (eta > np.median(eta)).astype(np.float64)
        base = dict(stream_block_rows=2048, stream_autotune=False,
                    dtype="float32", stream_mesh=0)

        def timed(sparse_on):
            with config.set(**base, stream_sparse=sparse_on):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(Xs, yh)  # warm
                clf = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=False)
                t0 = _time.perf_counter()
                clf.fit(Xs, yh)
                return clf, _time.perf_counter() - t0

        sp_clf, t_s = timed(True)
        info = dict(sp_clf.solver_info_)
        assert info.get("sparse_stream") is True, info
        assert info.get("sparse_stream_reason") is None, info
        st = dict(sp_clf._last_stream_stats or {})
        assert st.get("sb_shards") == n_dev, st
        assert st["dispatches_per_pass"] == \
            -(-st["n_blocks"] // st["superblock_k"]), st
        dn_clf, t_d = timed(False)
        assert np.allclose(sp_clf.coef_, dn_clf.coef_, atol=1e-5), \
            np.abs(sp_clf.coef_ - dn_clf.coef_).max()
        # GLM sparse reducers agree with the densify path
        with config.set(**base, stream_sparse=True):
            glm = LogisticRegression(solver="gradient_descent",
                                     max_iter=3).fit(Xs, yh)
            assert glm.solver_info_.get("sparse_stream") is True, \
                glm.solver_info_
        # serving (rows, nnz) grid: warmed sparse predictions agree
        with config.set(serving_min_batch=8, serving_max_batch=256,
                        serving_sparse_nnz_per_row=2 * npr):
            srv = ModelServer(sp_clf, methods=("predict",))
            srv.warmup()
            srv.warmup_sparse()
            with srv:
                q = Xs[:100].tocsr()
                got = srv.submit(q, method="predict").result(60)
            want = sp_clf.predict(q.toarray())
            assert np.array_equal(got, want)
        if on_tpu:
            assert t_s * 2 <= t_d, (
                f"sparse streamed SGD {t_s:.3f}s not >= 2x faster than "
                f"densify {t_d:.3f}s at density ~1%, d=2**14"
            )
        print(f"    round-12: {n_dev} chips, sparse "
              f"{n * 2 / t_s:.0f} rows/s vs densify "
              f"{n * 2 / t_d:.0f} rows/s "
              f"({t_d / t_s:.2f}x), serving grid OK")

    def search_round13():
        """ISSUE 14 surfaces: the adaptive-search cohort as a client
        of the streamed superblock plane on real chips — one
        BlockStream pass per round (slot-rung cohort scans, sharded
        psum twins on >1-chip attaches, fused Pallas cohort bodies
        engaged), score parity with the device-resident cohort path on
        the same partition, and the >= 2x wall-clock claim measured
        where it belongs (on-chip HBM copies vs zero re-staging).
        Degrades to a 1-chip attach like rounds 8-12."""
        import time as _time

        from dask_ml_tpu import config
        from dask_ml_tpu.model_selection import HyperbandSearchCV
        from dask_ml_tpu.models.sgd import SGDClassifier

        on_tpu = jax.default_backend() == "tpu"
        n_dev = len(jax.devices())
        rng = np.random.RandomState(14)
        n, d = 262_144, 128
        X = rng.randn(n, d).astype(np.float32)
        yh = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(np.float64)
        params = {"alpha": [1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 1e-2],
                  "eta0": [0.01, 0.03, 0.05, 0.1, 0.3, 0.5]}
        # 2048-row blocks -> 128-multiple per-shard slabs on
        # power-of-two slices: the fused cohort tile gate passes
        base = dict(stream_block_rows=2048, stream_autotune=False,
                    dtype="float32", stream_mesh=0)

        def timed(streamed):
            with config.set(**base, search_stream=streamed):
                def run():
                    h = HyperbandSearchCV(
                        SGDClassifier(tol=1e-3, random_state=0),
                        params, max_iter=27, aggressiveness=3,
                        random_state=0,
                    )
                    h.fit(X, yh, classes=[0.0, 1.0])
                    return h

                run()                      # warm
                t0 = _time.perf_counter()
                h = run()
                return h, _time.perf_counter() - t0

        hs, t_s = timed(True)
        meta = hs.metadata_["stream"]
        assert meta["streamed"] is True, meta
        assert meta["shards"] == n_dev, meta
        if on_tpu:
            # the fused Pallas cohort bodies (pallas.sgd_cohort[.psum])
            # must ENGAGE on chips at these block shapes
            assert meta["fused"] is True, meta
        hd, t_d = timed(False)
        key = lambda r: (r["model_id"], r["partial_fit_calls"])  # noqa: E731
        a = np.asarray([r["score"] for r in
                        sorted(hs.history_, key=key)])
        b = np.asarray([r["score"] for r in
                        sorted(hd.history_, key=key)])
        assert a.shape == b.shape and np.abs(a - b).max() <= 1e-6, \
            np.abs(a - b).max()
        assert hs.best_params_ == hd.best_params_
        if on_tpu:
            assert t_s * 2 <= t_d, (
                f"streamed-cohort Hyperband {t_s:.3f}s not >= 2x "
                f"faster than the device-resident cohort path "
                f"{t_d:.3f}s on {n_dev} chips"
            )
        # sparse cohort engagement: the search must ride the
        # bucketed-nnz scans without densify
        import scipy.sparse as sp_

        Xsp = sp_.random(65_536, 2 ** 12, density=0.01, format="csr",
                         random_state=rng, dtype=np.float64)
        ssum = np.asarray(Xsp.sum(axis=1)).ravel()
        ysp = (ssum > np.median(ssum)).astype(np.float64)
        with config.set(**base):
            hsp = HyperbandSearchCV(
                SGDClassifier(tol=1e-3, random_state=0), params,
                max_iter=9, aggressiveness=3, random_state=0,
            )
            hsp.fit(Xsp, ysp, classes=[0.0, 1.0])
        assert hsp.metadata_["stream"]["sparse"] is True, \
            hsp.metadata_["stream"]
        print(f"    round-13: {n_dev} chips, streamed bracket "
              f"{t_s:.3f}s vs device-resident {t_d:.3f}s "
              f"({t_d / t_s:.2f}x), fused={meta['fused']}, "
              f"sparse cohort OK")

    def plans_round14():
        """ISSUE 15 surfaces: the plans subsystem on real chips — a
        plan-built serving grid, a C-grid search and a streamed fit all
        warmed in ONE process pay zero XLA compiles afterward (the
        cross-client contract perf_smoke gates on CPU), donation is
        honored on the serving path (TPU donates the batch operand),
        and the plans table renders with ladder:rung attribution.
        Degrades to a 1-chip attach like rounds 8-13."""
        from dask_ml_tpu import config, plans
        from dask_ml_tpu import observability as obs
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.serving import BucketLadder, ModelServer

        on_tpu = jax.default_backend() == "tpu"
        n_dev = len(jax.devices())
        rng = np.random.RandomState(15)
        n, d = 65_536, 64
        X = rng.randn(n, d).astype(np.float32)
        yh = (X[:, 0] > 0).astype(np.float64)

        def run_search():
            GridSearchCV(
                LogisticRegression(solver="lbfgs", max_iter=5,
                                   tol=0.0),
                {"C": [0.1, 1.0, 10.0]}, cv=2, refit=False,
                scheduler="synchronous",
            ).fit(X, yh)

        with config.set(stream_block_rows=4096, stream_autotune=False,
                        dtype="float32", stream_mesh=0):
            clf = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False)
            clf.fit(X, yh)             # warms the streamed scans
            run_search()               # warms the stacked solves
            srv = ModelServer(clf, methods=("predict",),
                              ladder=BucketLadder(8, 256, 2.0),
                              batch_window_ms=1.0, timeout_ms=0)
            srv.warmup()               # warms the serving grid
            obs.counters_reset()
            with srv:
                SGDClassifier(max_iter=2, random_state=0,
                              shuffle=False).fit(X, yh)
                run_search()
                r2 = np.random.RandomState(7)
                for _ in range(30):
                    k = r2.randint(1, 256)
                    i = r2.randint(0, n - k)
                    srv.predict(X[i:i + k])
            snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0, snap.get("recompiles")
        if on_tpu:
            # the plan layer wired batch donation (TPU/GPU only)
            assert snap.get("donated_buffers_reused", 0) > 0, snap
        rows = {r["program"]: r for r in plans.plans_snapshot()}
        srow = rows.get("serving.SGDClassifier.predict")
        assert srow and srow["warmups"] >= 1 \
            and srow["ladder"] == "serving-rows" \
            and "256" in srow["rungs"], srow
        assert "glm.lbfgs_lam_grid" in rows, sorted(rows)
        print(f"    round-14: {n_dev} chips, cross-client "
              f"recompiles=0, plans table rows={len(rows)}, "
              f"serving rungs {srow['rungs']}")

    def mesh2d_round15():
        """ISSUE 18 surfaces: 2-D ("data", "model") hybrid meshes on
        real chips — hybrid-mesh bring-up, feature-sharded GLM parity
        vs the 1-D path, streamed randomized PCA parity vs the
        resident solve, and the Dx1 auto-degrade that keeps
        single-slice attaches on the untouched 1-D programs. Degrades
        to a 1-chip (or odd) attach like rounds 8-14."""
        from dask_ml_tpu import config
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.pca import PCA
        from dask_ml_tpu.parallel.mesh import (
            DATA_AXIS, MODEL_AXIS, data_shards, default_mesh,
            mesh_str, model_shards, stream_data_mesh,
        )

        n_dev = len(jax.devices())
        rng = np.random.RandomState(18)

        # Dx1 auto-degrade: a trivial model axis must resolve to the
        # SAME cached 1-D mesh object — single-slice attaches stay on
        # the byte-identical 1-D programs
        with config.set(stream_mesh=0, mesh_shape=f"{n_dev}x1"):
            m_deg = stream_data_mesh()
        assert m_deg is default_mesh(), (m_deg, default_mesh())
        assert model_shards(m_deg) == 1

        if n_dev < 2 or n_dev % 2:
            print(f"    round-15: {n_dev} chip(s) — 1-D auto-degrade "
                  "verified; 2-D bring-up needs an even multi-chip "
                  "attach")
            return

        # hybrid-mesh bring-up: ("data", "model") axes over the real
        # chips (multi-slice topologies route through
        # create_hybrid_device_mesh inside device_mesh's topology
        # arranging — DCN outer on the data axis, ICI inner)
        with config.set(stream_mesh=0, mesh_shape="-1x2"):
            m2 = stream_data_mesh()
        assert m2.axis_names == (DATA_AXIS, MODEL_AXIS), m2.axis_names
        assert model_shards(m2) == 2
        assert data_shards(m2) == n_dev // 2
        shape = mesh_str(m2)

        # feature-sharded GLM parity vs the 1-D path
        n, d = 32_768, 64
        Xg = rng.randn(n, d).astype(np.float32)
        yg = (Xg[:, 0] > 0).astype(np.float64)
        fits = {}
        for label, knobs in (
            ("1d", dict(stream_mesh=1)),
            ("2d", dict(stream_mesh=0, mesh_shape="-1x2")),
        ):
            with config.set(stream_block_rows=4096,
                            stream_autotune=False, dtype="float32",
                            **knobs):
                fits[label] = LogisticRegression(
                    solver="lbfgs", max_iter=15).fit(Xg, yg)
        drift = np.abs(np.asarray(fits["2d"].coef_, np.float64)
                       - np.asarray(fits["1d"].coef_, np.float64)).max()
        assert drift <= 5e-4, drift

        # streamed randomized PCA parity vs the resident solve
        # (decaying spectrum so the range capture is well-posed)
        u = np.linalg.qr(rng.standard_normal((4096, d)))[0]
        v = np.linalg.qr(rng.standard_normal((d, d)))[0]
        sv = 100.0 * (0.7 ** np.arange(d))
        Xs = ((u * sv) @ v.T
              + 0.01 * rng.standard_normal((4096, d))
              + 1.5).astype(np.float32)
        with config.set(stream_block_rows=512, stream_autotune=False,
                        dtype="float32", stream_mesh=0,
                        mesh_shape="-1x2"):
            stp = PCA(n_components=8, svd_solver="randomized",
                      random_state=0).fit(Xs)
        res = PCA(n_components=8, svd_solver="full").fit(Xs)
        np.testing.assert_allclose(
            np.asarray(stp.singular_values_),
            np.asarray(res.singular_values_), rtol=1e-3,
        )
        align = np.linalg.svd(
            np.asarray(stp.components_, np.float64)
            @ np.asarray(res.components_, np.float64).T,
            compute_uv=False,
        )
        assert align.min() > 1 - 1e-4, align
        print(f"    round-15: mesh {shape}, GLM 1-D/2-D coef drift "
              f"{drift:.2e}, streamed PCA parity vs resident OK")

    def fleet_obs_round16():
        """ISSUE 19 surfaces: fleet-scope observability on real chips
        — cross-process trace propagation over a federated fleet
        (every routed request is ONE trace: router leg + full-stage
        worker leg on the same id), the federated
        ``dask_ml_tpu_fleet_*`` /metrics families off the shared
        status scrape, and ZERO post-warmup recompiles with the whole
        plane on. Runs a 2-process (virtual transport) fleet; degrades
        to 1 process on a 1-chip attach."""
        from dask_ml_tpu import config, observability as obs
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.observability import _requests as rtrace
        from dask_ml_tpu.observability.live import render_prometheus
        from dask_ml_tpu.serving import (
            BucketLadder, FederatedFleet, FleetServer, LocalEndpoint,
        )

        n_dev = len(jax.devices())
        n_proc = 2 if n_dev >= 2 else 1
        rng = np.random.RandomState(19)
        n, d = 8192, 32
        Xf = rng.randn(n, d).astype(np.float32)
        yf = (Xf[:, 0] > 0).astype(np.float64)
        clf = LogisticRegression(solver="lbfgs", max_iter=15).fit(Xf, yf)
        ladder = BucketLadder(8, 256, 2.0)
        rtrace.traces_reset()
        with config.set(obs_trace_sample=1.0, obs_fleet_federate=True):
            fleets = [
                FleetServer(clf, name="smoke16", replicas=1,
                            ladder=ladder, batch_window_ms=1.0,
                            timeout_ms=0).warmup().start()
                for _ in range(n_proc)
            ]
            try:
                eps = [LocalEndpoint(f, f"p{i}")
                       for i, f in enumerate(fleets)]
                with FederatedFleet(eps, name="smoke16", ladder=ladder,
                                    poll_s=0.2) as fed:
                    c0 = obs.counters_snapshot().get("recompiles", 0)
                    for _ in range(16):
                        k = rng.randint(1, 200)
                        j = rng.randint(0, n - k)
                        fed.predict(Xf[j:j + k])
                    recompiles = obs.counters_snapshot() \
                        .get("recompiles", 0) - c0
                    assert recompiles == 0, recompiles
                    recs = rtrace.traces_data()["traces"]
                    router = [r for r in recs
                              if r.get("federation") == "smoke16"]
                    assert len(router) == 16, len(router)
                    for rt in router:
                        legs = [r for r in recs
                                if r["trace_id"] == rt["trace_id"]
                                and r is not rt]
                        assert legs and {"queue_pop", "execute_done"} \
                            <= set(legs[0]["stages"]), (rt, legs)
                    fed._poll_once()
                    page = render_prometheus()
                    procs = [ln for ln in page.splitlines()
                             if ln.startswith(
                                 "dask_ml_tpu_fleet_processes ")]
                    assert procs \
                        and int(float(procs[0].split()[1])) == n_proc, \
                        procs
                    # LocalEndpoints federate no counters BY DESIGN
                    # (in-process endpoints share the router's own
                    # registry — shipping them would double-count;
                    # federation_smoke asserts the counter aggregate
                    # over real HTTP processes), so the built-in
                    # scrape gauge is the honest surface here
                    assert "dask_ml_tpu_fleet_scrape_seconds" in page
            finally:
                for f in fleets:
                    f.stop(drain=False)
        rtrace.traces_reset()
        print(f"    round-16: {n_proc}-process fleet, 16 routed "
              "traces all joined cross-process, federated /metrics "
              "OK, recompiles=0")

    def incidents_round17():
        """ISSUE 20 surfaces: the incident plane on real chips — a
        firing alert rule freezes one atomic bundle (open spans +
        registry snapshots + device memory of the actual TPUs), the
        engine's ticker pays ZERO XLA compiles, and
        ``incidents.deep_profile`` runs a REAL ``jax.profiler`` window
        into the incident dir on TPU (the no-op-with-reason contract
        is asserted off-TPU instead)."""
        import tempfile
        import time as _time

        from dask_ml_tpu import config, observability as obs
        from dask_ml_tpu.observability import alerts, incidents
        from dask_ml_tpu.observability.live import gauge_set

        workdir = tempfile.mkdtemp(prefix="tpu_smoke_incidents_")
        idir = os.path.join(workdir, "incidents")
        alerts.reset()
        incidents.reset()
        try:
            with config.set(
                obs_alert_rules="smoke17_depth:gauge>10",
                incident_dir=idir, obs_alert_interval_s=0.1,
                trace_dir=os.path.join(workdir, "trace"),
            ):
                assert alerts.ensure_engine() is not None
                c0 = obs.counters_snapshot().get("recompiles", 0)
                with obs.span("tpu_smoke.incident17"):
                    gauge_set("smoke17_depth", 99.0)
                    deadline = _time.time() + 15
                    while not (os.path.isdir(idir) and any(
                            f.startswith("incident_")
                            and f.endswith(".json")
                            for f in os.listdir(idir))):
                        assert _time.time() < deadline, "no bundle"
                        _time.sleep(0.05)
                assert "smoke17_depth:gauge>10.0" \
                    in alerts.alerts_data()["firing"]
                compiles = obs.counters_snapshot() \
                    .get("recompiles", 0) - c0
                assert compiles == 0, compiles
                bundle = incidents.load_bundles(idir)[0]
                assert bundle["reason"] == \
                    "alert:smoke17_depth:gauge>10.0", bundle["reason"]
                assert any(s["span"] == "tpu_smoke.incident17"
                           for s in bundle["open_spans"])
                assert bundle["config"]["fingerprint"]
                # device_memory froze the REAL per-chip gauges here
                devmem = bundle["device_memory"]
                assert isinstance(devmem, dict), devmem

                out = incidents.deep_profile(seconds=1)
                if jax.default_backend() == "tpu":
                    assert out["profiled"] is True, out
                    trace_files = [
                        os.path.join(dp, f)
                        for dp, _dn, fns in os.walk(out["log_dir"])
                        for f in fns
                    ]
                    assert trace_files, "profiler window wrote nothing"
                    profiled = (f"{out['seconds']}s window, "
                                f"{len(trace_files)} trace files")
                else:
                    assert out["profiled"] is False \
                        and "TPU" in out["reason"], out
                    profiled = "no-op off-TPU (reason documented)"
        finally:
            alerts.reset()
            incidents.reset()
        print(f"    round-17: alert fired -> 1 bundle "
              f"(open span + device memory frozen), recompiles=0, "
              f"deep profile: {profiled}")

    passed = _load_state()
    for name, fn in [
        ("glm solvers x3 families", glms),
        ("device sgd", sgd),
        ("kmeans (pallas)", kmeans),
        ("spectral clustering", spectral),
        ("pca/tsvd/ipca", decomposition),
        ("preprocessing scalers", preprocessing),
        ("naive bayes + imputer", naive_bayes_impute),
        ("pairwise metrics", metrics_pairwise),
        ("grid + hyperband search", search),
        ("wrappers + ensemble", wrappers_ensemble),
        ("block streaming", streaming),
        ("round-4 multiclass/drop/subsample", multiclass_round4),
        ("round-5 sparse/scorers/bf16/overlap", round5_surfaces),
        ("round-8 fused-stream/bf16-auto/int8", fused_stream_round8),
        ("round-9 sharded superblock streaming", sharded_stream_round9),
        ("round-10 chaos/resume/supervision", chaos_round10),
        ("round-11 fused-x-sharded + grad-accum", fused_sharded_round11),
        ("round-12 device-resident sparse streaming",
         sparse_stream_round12),
        ("round-13 streamed-cohort adaptive search", search_round13),
        ("round-14 execution plans (plans/)", plans_round14),
        ("round-15 2-D hybrid meshes", mesh2d_round15),
        ("round-16 fleet observability", fleet_obs_round16),
        ("round-17 incident plane", incidents_round17),
    ]:
        results.append(run(name, fn, passed))

    n_fail = results.count(False)
    print(f"{len(results) - n_fail}/{len(results)} surfaces OK")
    sys.exit(1 if n_fail else 0)


if __name__ == "__main__":
    main()
