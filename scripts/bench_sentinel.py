"""Bench regression sentinel: gate verify.sh on the recorded BENCH
history.

Compares the LATEST ``BENCH_r*.json`` round against per-metric budget
floors derived from the recorded history and fails (exit 1) on a
CONFIRMED >20% regression — the "throughput quietly rotted" failure
mode the numeric test suite cannot see.

Noise-robust gating (ISSUE 8 recalibration): single-reference-round
floors false-alarmed on this box — interleaved A/B runs of identical
code showed per-round swings of 25-45% on metrics whose code had not
changed in several PRs (the box's sustained throughput drifts between
recording windows), tripping floors recorded in a fast window. Two
rules fix that without letting real rot through:

- the floor BASIS for a metric is the most permissive of its last
  (up to) 3 recorded same-backend values before the gated round — a
  trailing window tracks box drift, and a regression must undercut the
  WORST recent round by >tol to breach, not an all-time-best sample;
- a breach only FAILS when the PREVIOUS round that measured the metric
  ALSO breached its own (window-before-it) floor — genuine code rot
  persists and fails one round later; a one-round box blip lands as a
  loud WARN ("unconfirmed — fails if it persists") and self-clears.

Other rules (unchanged):

- throughput-like metrics (samples/s, rows/s, iterations/s — anything
  whose unit is not seconds) must stay >= basis * (1 - tol);
- latency-like metrics (unit "s") must stay <= basis * (1 + tol);
- a metric is only compared on the SAME backend — a CPU-fallback round
  is not a regression of a TPU round, it's a different machine;
- error/null entries in the latest round for historically-measured
  metrics are reported but only WARN;
- metrics no recorded round carries yet seed their basis from the
  freshest BENCH_metrics.jsonl ``kind="bench_metric"`` records (bench
  appends one per successful metric), so new flavors land gated from
  their first round.

Env knob: ``BENCH_SENTINEL_TOL`` (default 0.20).
"""

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOL = float(os.environ.get("BENCH_SENTINEL_TOL", "0.20"))
WINDOW = 3  # trailing same-backend samples forming a metric's basis


def _load(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _tail_metrics(tail):
    """Recover metric entries from a TRUNCATED stdout tail: the driver
    keeps only the last ~2000 chars of bench.py's output, which cuts the
    headline open-brace but leaves the extra_metrics entries as complete
    ``{"metric": ...}`` objects — raw_decode each occurrence."""
    dec = json.JSONDecoder()
    out = {}
    for m in re.finditer(r'\{"metric"', tail or ""):
        try:
            obj, _ = dec.raw_decode(tail, m.start())
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out[obj["metric"]] = obj
    return out


def _rounds():
    """(usable rounds, all round numbers on disk). A round that yields
    no metrics at all is still REPORTED via the second set — the newest
    round silently producing nothing is itself the failure mode this
    gate exists for."""
    out = {}
    on_disk = set()
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            on_disk.add(int(m.group(1)))
        data = _load(path)
        if not (m and isinstance(data, dict)):
            continue
        # the driver wraps bench.py's JSON line as {"parsed": {...}}
        # (null when the line outgrew the driver's tail buffer); a raw
        # bench doc carries "metric" at top level — accept both, and
        # fall back to recovering entries from the truncated tail
        doc = data.get("parsed") if isinstance(data.get("parsed"),
                                               dict) else (
            data if "metric" in data else None)
        if doc is None:
            recovered = _tail_metrics(data.get("tail"))
            if recovered:
                doc = {"metric": None,
                       "extra_metrics": list(recovered.values())}
        if isinstance(doc, dict):
            out[int(m.group(1))] = (path, doc)
    return out, on_disk


def _jsonl_seeds():
    """Floor seeds from the append-only ``BENCH_floors.jsonl`` history
    (bench.py appends a ``bench_run_start`` marker plus one
    ``bench_metric`` record per successful metric, every run, and the
    file is never truncated): a metric that no recorded BENCH_r*.json
    round carries yet gets its budget basis from the runs BEFORE the
    newest one — the newest run block is presumed to BE the latest
    round's own recording, and a round must never gate against itself.
    Per metric: the most permissive of its last <= WINDOW surviving
    values."""
    runs = [[]]
    path = os.path.join(REPO, "BENCH_floors.jsonl")
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "bench_run_start":
                    runs.append([])
                elif rec.get("kind") == "bench_metric" \
                        and rec.get("metric"):
                    runs[-1].append(rec)
    except OSError:
        return {}
    out = {}
    for run in runs[:-1] if len(runs) > 1 else []:
        for rec in run:
            e = {"value": rec.get("value"), "unit": rec.get("unit", ""),
                 "backend": rec.get("backend")}
            if not isinstance(e["value"], (int, float)) or e["value"] <= 0:
                continue
            out.setdefault(rec["metric"], []).append(e)
    seeds = {}
    for name, entries in out.items():
        entries = entries[-WINDOW:]
        unit = entries[-1]["unit"]
        vals = [e["value"] for e in entries]
        seeds[name] = {
            "value": max(vals) if unit == "s" else min(vals),
            "unit": unit, "backend": entries[-1]["backend"],
        }
    return seeds


def _metrics(doc):
    """Flatten a bench doc into {metric: {"value", "unit", "backend"}}
    (headline + extra_metrics; error entries keep value=None)."""
    out = {}
    for entry in [doc] + list(doc.get("extra_metrics") or []):
        if not isinstance(entry, dict) or not entry.get("metric"):
            continue
        out[entry["metric"]] = {
            "value": entry.get("value"),
            "unit": entry.get("unit", ""),
            "backend": entry.get("backend"),
        }
    return out


def _usable(entry, backend):
    return (entry is not None and entry.get("backend") == backend
            and isinstance(entry.get("value"), (int, float))
            and entry["value"] > 0)


def _metric_timeline(history, name, backend, seed=None):
    """Walk ``name``'s same-backend samples in round order, gating each
    against the basis of the ACCEPTED samples before it: a sample that
    itself breached is recorded (``breached=True``) but EXCLUDED from
    every later basis — a persistent one-step regression therefore
    keeps breaching the pre-rot basis round after round instead of
    becoming the new normal after a single unconfirmed warning.
    Returns [(round, value, breached, basis, unit, srcs)] ascending;
    ``seed`` (value, unit, label) primes the accepted window for
    metrics with pre-round history (BENCH_floors.jsonl)."""
    accepted = []          # [(round-or-label, value)]
    unit0 = ""
    if seed is not None:
        accepted.append((seed[2], seed[0]))
        unit0 = seed[1]
    out = []
    for num in sorted(history):
        e = history[num].get(name)
        if not _usable(e, backend):
            continue
        unit = e.get("unit", unit0)
        window = accepted[-WINDOW:]
        if window:
            vals = [v for _, v in window]
            basis = max(vals) if unit == "s" else min(vals)
            srcs = [s for s, _ in window]
            breached = _breach(e["value"], basis, unit) is not None
        else:
            basis, srcs, breached = None, [], False
        out.append((num, e["value"], breached, basis, unit, srcs))
        if not breached:
            accepted.append((num, e["value"]))
    return out


def _breach(value, basis, unit):
    """The over-budget description when ``value`` breaches ``basis`` at
    the tolerance, else None."""
    if unit == "s":
        budget = basis * (1.0 + TOL)
        if value > budget:
            return (f"{value:.4g}s vs budget {budget:.4g}s "
                    f"(+{(value / basis - 1) * 100:.1f}%)")
        return None
    floor = basis * (1.0 - TOL)
    if value < floor:
        return (f"{value:.4g} vs floor {floor:.4g} "
                f"({(value / basis - 1) * 100:.1f}%)")
    return None


def main():
    rounds, on_disk = _rounds()
    if not on_disk:
        print("bench sentinel: no BENCH_r*.json recorded yet — skipping")
        return 0
    if not rounds or max(on_disk) > max(rounds):
        # the newest round on disk yielded NO metrics (hung/killed bench
        # with nothing recoverable) — exactly the silent-rot failure
        # this gate exists to catch; gating an older round as "latest"
        # would report OK over it
        print(
            f"  SENTINEL FAIL BENCH_r{max(on_disk):02d}.json exists but "
            "yields no metrics (bench hung or was killed?) — the newest "
            "round cannot be gated", file=sys.stderr,
        )
        return 1
    latest_num = max(rounds)
    if len(rounds) == 1:
        print(f"bench sentinel: only one recorded round "
              f"(r{latest_num:02d}) exists — nothing to gate it against")
        return 0
    history = {num: _metrics(doc) for num, (_, doc) in rounds.items()}
    latest = history[latest_num]
    # metrics in NO round before the latest seed a basis from the
    # BENCH_floors.jsonl run history (_jsonl_seeds already excludes the
    # newest run block — the latest round's own recording — so the
    # round never gates against itself)
    jsonl = {}
    for name, entry in _jsonl_seeds().items():
        if entry["value"] is None:
            continue
        if any(name in history[num] for num in history
               if num != latest_num):
            continue
        jsonl[name] = entry
        print(f"bench sentinel: {name} basis seeded from "
              "BENCH_floors.jsonl (absent from every earlier round)")
    gated = set(jsonl)
    for num in history:
        if num != latest_num:
            gated.update(history[num])
    failures, warnings_, checked = [], [], 0
    for name in sorted(gated):
        cur = latest.get(name)
        backend = (cur or {}).get("backend") \
            or (jsonl.get(name) or {}).get("backend")
        if backend is None:
            # metric absent (or an error entry, which carries no
            # backend) in the latest round: resolve the comparison
            # backend from the newest earlier round that measured it,
            # so the ABSENT/null warning below can still fire
            for num in sorted((n for n in history if n != latest_num),
                              reverse=True):
                e = history[num].get(name)
                if e is not None and e.get("backend"):
                    backend = e["backend"]
                    break
        seed = jsonl.get(name)
        seed_t = (seed["value"], seed.get("unit", ""), "jsonl") \
            if _usable(seed, backend) else None
        timeline = _metric_timeline(history, name, backend, seed=seed_t)
        past = [t for t in timeline if t[0] != latest_num]
        src_hint = "+".join(
            f"r{t[0]:02d}" for t in past[-WINDOW:]
        ) or ("jsonl" if seed_t else "")
        if cur is None or not _usable(cur, backend):
            if not past and seed_t is None:
                continue
            if not past and seed_t is not None:
                # a metric bench records but no round carries yet is
                # EXPECTED to be missing from a pre-existing latest
                # round — it gates from its first recorded round on
                continue
            # absent/null (crashed bench section, truncated tail) —
            # the common partial-rot mode; surface it, don't skip it
            kind = "null/error in" if cur is not None else "ABSENT from"
            warnings_.append(
                f"{name}: in recorded history ({src_hint}) but {kind} "
                f"r{latest_num:02d}"
            )
            continue
        entry = next((t for t in timeline if t[0] == latest_num), None)
        if entry is None or entry[3] is None:
            continue  # no accepted same-backend history to gate against
        _, value, breached, basis, unit, srcs = entry
        src = "+".join(f"r{s:02d}" if isinstance(s, int) else str(s)
                       for s in srcs)
        checked += 1
        if not breached:
            continue
        over = _breach(value, basis, unit)
        # first occurrence vs confirmed: the previous round that
        # measured this metric must ALSO have breached (breaching
        # samples are EXCLUDED from later bases, so a persistent
        # regression keeps breaching the pre-rot basis and confirms
        # here one round later) — a one-off bad-box-window round warns
        # loudly and self-clears instead
        confirmed = bool(past) and past[-1][2]
        if confirmed:
            failures.append(
                f"{name}: {over} [basis {src}; also breached in the "
                "previous round — confirmed regression]"
            )
        else:
            warnings_.append(
                f"{name}: {over} [basis {src}] — UNCONFIRMED (first "
                "occurrence; box-noise suspect). Fails the gate if it "
                "persists next round."
            )
    print(f"bench sentinel: r{latest_num:02d} vs trailing-{WINDOW} "
          f"window floors (breaching rounds excluded from bases), "
          f"{checked} comparable metrics, tol {TOL:.0%}")
    for w in warnings_:
        print(f"  WARN {w}")
    if failures:
        for f in failures:
            print(f"  SENTINEL FAIL {f}", file=sys.stderr)
        return 1
    print("bench sentinel OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
