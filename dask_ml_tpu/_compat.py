"""Version-compat shims over moving jax APIs.

The ONE place the package touches ``shard_map``'s import location and
replication-check keyword: ``jax.shard_map`` (jax >= 0.6) vs
``jax.experimental.shard_map.shard_map`` (older), and ``check_vma`` vs
its pre-rename spelling ``check_rep``. Everything else imports
``shard_map`` from here — a repo lint (scripts/verify.sh) bans direct
``from jax import shard_map`` outside this module, because that single
import took down all 33 tier-1 test collections on jax 0.4.x.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the static replication checker's kwarg was renamed check_rep ->
# check_vma; dispatch on the resolved function's actual signature
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` under any supported jax: same call shape as the
    modern API; ``check_vma`` maps onto whichever replication-check
    keyword this jax spells."""
    kwargs = {}
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
