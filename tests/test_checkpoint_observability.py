"""Checkpoint/resume + observability + config subsystems (SURVEY.md §5:
built beyond the reference — dask-ml restarts searches from scratch)."""

import json
import os

import numpy as np
import pytest


def test_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    from dask_ml_tpu.utils import checkpoint as ckpt

    tree = {
        "beta": jnp.arange(6, dtype=jnp.float32),
        "it": jnp.asarray(3),
        "nested": {"m": jnp.ones((2, 2))},
    }
    path = os.path.join(tmp_path, "state")
    ckpt.save_pytree(path, tree)
    got = ckpt.restore_pytree(path, like=tree)
    np.testing.assert_allclose(np.asarray(got["beta"]), np.arange(6))
    assert int(got["it"]) == 3
    np.testing.assert_allclose(np.asarray(got["nested"]["m"]), 1.0)


def test_host_roundtrip(tmp_path):
    from sklearn.linear_model import SGDClassifier

    from dask_ml_tpu.utils import checkpoint as ckpt

    rng = np.random.RandomState(0)
    X = rng.randn(50, 4)
    y = (X[:, 0] > 0).astype(int)
    est = SGDClassifier(random_state=0).fit(X, y)
    p = os.path.join(tmp_path, "est.pkl")
    ckpt.save_host(p, est)
    got = ckpt.restore_host(p)
    np.testing.assert_array_equal(got.predict(X), est.predict(X))


def test_search_checkpoint_roundtrip(tmp_path):
    from dask_ml_tpu.utils.checkpoint import SearchCheckpoint

    sc = SearchCheckpoint(os.path.join(tmp_path, "search"))
    assert sc.load() is None
    history = [{"model_id": 0, "score": 0.5}]
    meta = {0: {"partial_fit_calls": 3}}
    sc.save_round(2, history, meta, models={0: "modelblob"})
    state = sc.load()
    assert state["round"] == 2
    assert state["history"] == history
    assert state["meta"] == meta
    assert state["models"][0] == "modelblob"


def test_metrics_logger_jsonl(tmp_path):
    from dask_ml_tpu.utils.observability import MetricsLogger

    p = os.path.join(tmp_path, "metrics.jsonl")
    with MetricsLogger(p, extra={"run": "t1"}) as log:
        log.log(step=0, loss=1.5)
        log.log(step=1, loss=0.7, samples_per_sec=123.0)
    lines = [json.loads(l) for l in open(p)]
    assert len(lines) == 2
    assert lines[0]["run"] == "t1" and lines[0]["step"] == 0
    assert lines[1]["samples_per_sec"] == 123.0
    assert all("time" in rec for rec in lines)


def test_timed():
    from dask_ml_tpu.utils.observability import timed

    out, secs = timed(lambda a, b: a + b, 2, b=3)
    assert out == 5 and secs >= 0.0


def test_config_set_overrides_and_env():
    from dask_ml_tpu import config

    base = config.get_config()
    assert base.dtype in ("auto", "float32", "bfloat16")
    with config.set(stream_block_rows=4096, dtype="bfloat16"):
        cfg = config.get_config()
        assert cfg.stream_block_rows == 4096
        assert cfg.dtype == "bfloat16"
        with config.set(dtype="float32"):
            assert config.get_config().dtype == "float32"
            assert config.get_config().stream_block_rows == 4096
    assert config.get_config().stream_block_rows == base.stream_block_rows


def test_config_rejects_unknown_key():
    from dask_ml_tpu import config

    with pytest.raises(TypeError):
        with config.set(not_a_field=1):
            pass


class _FlakyClassifier:
    """sklearn-compatible partial_fit classifier that raises after a set
    number of partial_fit calls across ALL instances — fault injection for
    the controller (SURVEY.md §5 failure row)."""

    CALLS = {"n": 0, "fail_at": None}

    def __init__(self, alpha=1e-4):
        from sklearn.linear_model import SGDClassifier

        self.alpha = alpha
        self._est = SGDClassifier(alpha=alpha, tol=1e-3, random_state=0)

    def get_params(self, deep=True):
        return {"alpha": self.alpha}

    def set_params(self, **p):
        self.__init__(**{**self.get_params(), **p})
        return self

    def partial_fit(self, X, y, classes=None, **kw):
        c = _FlakyClassifier.CALLS
        c["n"] += 1
        if c["fail_at"] is not None and c["n"] > c["fail_at"]:
            raise RuntimeError("injected failure")
        self._est.partial_fit(X, y, classes=classes)
        return self

    def predict(self, X):
        return self._est.predict(X)

    def score(self, X, y):
        return self._est.score(X, y)


def test_incremental_search_checkpoint_resume(tmp_path):
    """A KILLED adaptive search resumes from its last round; a COMPLETED
    one clears its checkpoint (SURVEY.md §5: beyond the reference, whose
    killed searches restart from scratch)."""
    from sklearn.datasets import make_classification

    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import IncrementalSearchCV
    from dask_ml_tpu.utils.checkpoint import SearchCheckpoint

    X, y = make_classification(n_samples=400, n_features=8, random_state=0)
    params = {"alpha": list(np.logspace(-4, -1, 8))}
    ckpt_dir = os.path.join(tmp_path, "ck")

    def make_search():
        return IncrementalSearchCV(
            _FlakyClassifier(), params,
            n_initial_parameters=4, max_iter=6, random_state=0,
        )

    # run 1: injected failure mid-search -> checkpoint survives
    _FlakyClassifier.CALLS.update(n=0, fail_at=8)
    with config.set(checkpoint_dir=ckpt_dir):
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="injected"):
            make_search().fit(X, y, classes=[0, 1])
    # per-search subdirectory is keyed by the identity token
    subs = os.listdir(ckpt_dir)
    assert len(subs) == 1 and subs[0].startswith("IncrementalSearchCV-")
    sub = os.path.join(ckpt_dir, subs[0])
    state = SearchCheckpoint(sub).load()
    assert state is not None and state["round"] >= 1
    calls_before_crash = sum(
        m["partial_fit_calls"] for m in state["meta"].values()
    )
    assert calls_before_crash >= 4

    # run 2: same search resumes from the checkpoint and completes;
    # the completed run clears the checkpoint
    _FlakyClassifier.CALLS.update(n=0, fail_at=None)
    with config.set(checkpoint_dir=ckpt_dir):
        s2 = make_search().fit(X, y, classes=[0, 1])
    new_calls = _FlakyClassifier.CALLS["n"]
    assert hasattr(s2, "best_params_") and s2.best_score_ > 0.5
    # resumed run re-used the checkpointed work: only the remaining calls
    # were executed on fresh estimators
    total_after = int(s2.cv_results_["partial_fit_calls"].sum())
    assert new_calls == total_after - calls_before_crash
    assert SearchCheckpoint(sub).load() is None  # cleared on completion


def test_checkpoint_different_search_isolated(tmp_path):
    """A DIFFERENT search (other budget) gets its own checkpoint dir: it
    starts fresh AND leaves the interrupted search's state resumable."""
    from sklearn.datasets import make_classification

    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import IncrementalSearchCV
    from dask_ml_tpu.utils.checkpoint import SearchCheckpoint

    X, y = make_classification(n_samples=300, n_features=6, random_state=0)
    ckpt_dir = os.path.join(tmp_path, "ck2")

    _FlakyClassifier.CALLS.update(n=0, fail_at=6)
    with config.set(checkpoint_dir=ckpt_dir):
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            IncrementalSearchCV(
                _FlakyClassifier(), {"alpha": [1e-4, 1e-3, 1e-2, 1e-1]},
                n_initial_parameters=4, max_iter=6, random_state=0,
            ).fit(X, y, classes=[0, 1])
    sub_a = os.path.join(ckpt_dir, os.listdir(ckpt_dir)[0])
    assert SearchCheckpoint(sub_a).load() is not None

    # different search (different max_iter): own subdir, fresh run
    _FlakyClassifier.CALLS.update(n=0, fail_at=None)
    with config.set(checkpoint_dir=ckpt_dir):
        s = IncrementalSearchCV(
            _FlakyClassifier(), {"alpha": [1e-4, 1e-3, 1e-2, 1e-1]},
            n_initial_parameters=4, max_iter=3, random_state=0,
        ).fit(X, y, classes=[0, 1])
    assert int(s.cv_results_["partial_fit_calls"].max()) <= 3
    assert _FlakyClassifier.CALLS["n"] == int(
        s.cv_results_["partial_fit_calls"].sum()
    )
    # the interrupted search's checkpoint is untouched and still resumable
    assert SearchCheckpoint(sub_a).load() is not None


def test_checkpoint_resume_disabled_without_random_state(tmp_path):
    """random_state=None draws a fresh split per run — resume must be
    disabled (a resumed model would be scored on rows it trained on)."""
    from sklearn.datasets import make_classification

    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import IncrementalSearchCV

    X, y = make_classification(n_samples=300, n_features=6, random_state=0)
    ckpt_dir = os.path.join(tmp_path, "ck3")
    _FlakyClassifier.CALLS.update(n=0, fail_at=6)
    with config.set(checkpoint_dir=ckpt_dir):
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            IncrementalSearchCV(
                _FlakyClassifier(), {"alpha": [1e-4, 1e-3, 1e-2, 1e-1]},
                n_initial_parameters=4, max_iter=6, random_state=None,
            ).fit(X, y, classes=[0, 1])
    # ADVICE r1 #2: no checkpoint state is written AT ALL — resume is
    # impossible, so writes would be pure overhead and a shared-dir hazard
    assert not os.path.exists(ckpt_dir) or os.listdir(ckpt_dir) == []

    # rerun completes from scratch (no resume), using its own full budget
    _FlakyClassifier.CALLS.update(n=0, fail_at=None)
    with config.set(checkpoint_dir=ckpt_dir):
        s = IncrementalSearchCV(
            _FlakyClassifier(), {"alpha": [1e-4, 1e-3, 1e-2, 1e-1]},
            n_initial_parameters=4, max_iter=6, random_state=None,
        ).fit(X, y, classes=[0, 1])
    assert _FlakyClassifier.CALLS["n"] == int(
        s.cv_results_["partial_fit_calls"].sum()
    )


def _read_jsonl(path):
    return [json.loads(line) for line in open(path)]


def _read_steps(path):
    """Per-step metric records only: fits also append one span record
    each (the observability package's trace layer) — step-count
    assertions exclude those."""
    return [r for r in _read_jsonl(path) if "span" not in r]


def test_resident_glm_per_step_metrics(tmp_path):
    """config.metrics_path wires per-iteration JSONL OUT OF the jitted
    while_loop solvers via debug callbacks (VERDICT r2 #3)."""
    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(0)
    X = rng.randn(400, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = as_sharded(X), as_sharded(y)
    path = str(tmp_path / "glm.jsonl")
    with config.set(metrics_path=path):
        clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(Xs, ys)
    recs = _read_steps(path)
    assert len(recs) == clf.n_iter_
    for r in recs:
        assert r["component"] == "LogisticRegression"
        assert r["solver"] == "lbfgs"
        assert "loss" in r and "grad_norm" in r and "step" in r
    # steps are the solver's own iteration counter
    assert [r["step"] for r in recs] == list(range(clf.n_iter_))
    # silent path: no file grows without the knob
    clf2 = LogisticRegression(solver="lbfgs", max_iter=5).fit(Xs, ys)
    assert len(_read_steps(path)) == len(recs)


@pytest.mark.parametrize("solver,keys", [
    ("newton", ("loss", "grad_norm")),
    ("gradient_descent", ("loss", "grad_norm")),
    ("proximal_grad", ("loss", "opt_residual")),
    ("admm", ("primal_residual", "dual_residual")),
])
def test_all_resident_solvers_emit_metrics(tmp_path, solver, keys):
    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(1)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    path = str(tmp_path / f"{solver}.jsonl")
    with config.set(metrics_path=path):
        LogisticRegression(solver=solver, max_iter=5).fit(
            as_sharded(X), as_sharded(y)
        )
    recs = _read_steps(path)
    assert recs, solver
    for k in keys:
        assert all(k in r for r in recs), (solver, k, recs[0])


def test_kmeans_per_iteration_metrics(tmp_path):
    from dask_ml_tpu import config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(2)
    X = np.concatenate([
        rng.randn(200, 4).astype(np.float32) + 4 * i for i in range(3)
    ])
    path = str(tmp_path / "km.jsonl")
    with config.set(metrics_path=path):
        km = KMeans(n_clusters=3, init="random", random_state=0,
                    max_iter=20).fit(as_sharded(X))
    recs = _read_steps(path)
    assert len(recs) == km.n_iter_
    for r in recs:
        assert r["component"] == "KMeans"
        assert "center_shift2" in r and "step" in r


def test_adaptive_search_metrics(tmp_path):
    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import IncrementalSearchCV
    from dask_ml_tpu.models.sgd import SGDClassifier

    rng = np.random.RandomState(3)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    path = str(tmp_path / "search.jsonl")
    with config.set(metrics_path=path):
        search = IncrementalSearchCV(
            SGDClassifier(random_state=0),
            {"alpha": [1e-4, 1e-3, 1e-2]},
            n_initial_parameters=3, max_iter=5, random_state=0,
        )
        search.fit(X, y, classes=[0.0, 1.0])
    recs = [r for r in _read_steps(path)
            if r.get("component") == "adaptive_search"]
    assert len(recs) == len(search.history_)
    for r in recs:
        assert "model_id" in r and "score" in r and "batch_size" in r


def test_checkpoint_data_fingerprint_isolates(tmp_path):
    """ADVICE r1 #1: same shape, same params, DIFFERENT data content must
    not resume the stale search — the identity token carries a content
    fingerprint."""
    from sklearn.datasets import make_classification

    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import IncrementalSearchCV

    X, y = make_classification(n_samples=300, n_features=6, random_state=0)
    X2, y2 = make_classification(n_samples=300, n_features=6,
                                 random_state=99)  # same shape, new data
    ckpt_dir = os.path.join(tmp_path, "ckfp")
    params = {"alpha": [1e-4, 1e-3, 1e-2, 1e-1]}

    def search():
        return IncrementalSearchCV(
            _FlakyClassifier(), params,
            n_initial_parameters=4, max_iter=6, random_state=0,
        )

    _FlakyClassifier.CALLS.update(n=0, fail_at=6)
    with config.set(checkpoint_dir=ckpt_dir):
        with pytest.raises(RuntimeError, match="injected"):
            search().fit(X, y, classes=[0, 1])
    assert len(os.listdir(ckpt_dir)) == 1

    # same-shape different data: must get its OWN token directory and run
    # from scratch, not resume the stale models
    _FlakyClassifier.CALLS.update(n=0, fail_at=None)
    with config.set(checkpoint_dir=ckpt_dir):
        s = search().fit(X2, y2, classes=[0, 1])
    assert len(os.listdir(ckpt_dir)) == 2  # distinct token dirs
    # fresh run executed its entire own budget (nothing resumed)
    assert _FlakyClassifier.CALLS["n"] == int(
        s.cv_results_["partial_fit_calls"].sum()
    )
