"""Model persistence: every fitted estimator pickles and predicts after
a round trip (sklearn's persistence contract; the reference's estimators
hold picklable dask collections — here ShardedArray pickles as its host
form and re-shards onto the ambient mesh on load)."""

import pickle

import numpy as np
import pytest

rng = np.random.RandomState(0)
X = rng.randn(200, 5).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
y3 = rng.randint(0, 3, 200).astype(np.float32)


def _cases():
    from dask_ml_tpu.cluster import KMeans, SpectralClustering
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.preprocessing import StandardScaler

    from sklearn.linear_model import SGDClassifier as SkSGD

    from dask_ml_tpu.wrappers import Incremental, ParallelPostFit

    return [
        (LogisticRegression(solver="lbfgs", max_iter=30), y, "predict"),
        (Incremental(SGDClassifier(max_iter=2, random_state=0),
                     random_state=0), y, "predict"),
        (ParallelPostFit(SkSGD(random_state=0, max_iter=5, tol=None)), y,
         "predict"),
        (LogisticRegression(solver="lbfgs", max_iter=30), y3, "predict"),
        (SGDClassifier(max_iter=3, random_state=0), y, "predict"),
        (KMeans(n_clusters=3, max_iter=10, random_state=0), None,
         "predict"),
        (SpectralClustering(n_clusters=2, n_components=16,
                            random_state=0), None, None),
        (PCA(n_components=2), None, "transform"),
        (StandardScaler(), None, "transform"),
    ]


@pytest.mark.parametrize("est,target,method", _cases(),
                         ids=lambda v: type(v).__name__
                         if hasattr(v, "get_params") else "")
def test_pickle_roundtrip(est, target, method):
    fitted = est.fit(X) if target is None else est.fit(X, target)
    back = pickle.loads(pickle.dumps(fitted))
    if method is not None:
        a = getattr(fitted, method)(X)
        b = getattr(back, method)(X)
        a = a.to_numpy() if hasattr(a, "to_numpy") else np.asarray(a)
        b = b.to_numpy() if hasattr(b, "to_numpy") else np.asarray(b)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_sharded_array_pickle_roundtrip():
    from dask_ml_tpu.parallel import ShardedArray, as_sharded

    arr = rng.randn(101, 3).astype(np.float32)
    xs = as_sharded(arr)
    back = pickle.loads(pickle.dumps(xs))
    assert isinstance(back, ShardedArray)
    np.testing.assert_array_equal(back.to_numpy(), arr)
    assert back.shape == xs.shape


def test_pickle_preserves_model_axis_sharding():
    """A tensor-parallel (data, model) layout survives the round trip
    when a 2-D mesh is ambient — features stay model-sharded."""
    import jax

    from dask_ml_tpu.parallel import ShardedArray
    from dask_ml_tpu.parallel.mesh import MODEL_AXIS, device_mesh, use_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    X6 = rng.randn(200, 6).astype(np.float32)  # features % model-axis == 0
    mesh2d = device_mesh((-1, 2), ("data", "model"))
    with use_mesh(mesh2d):
        xs = ShardedArray.from_array(X6, mesh=mesh2d, shard_features=True)
        back = pickle.loads(pickle.dumps(xs))
        spec = back.data.sharding.spec
        assert len(spec) > 1 and spec[1] == MODEL_AXIS
        np.testing.assert_array_equal(back.to_numpy(), xs.to_numpy())


@pytest.mark.slow
def test_fitted_search_pickles():
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV

    s = GridSearchCV(LogisticRegression(solver="lbfgs", max_iter=20),
                     {"C": [0.5, 2.0]}, cv=2).fit(X, y)
    back = pickle.loads(pickle.dumps(s))
    np.testing.assert_allclose(
        back.cv_results_["mean_test_score"],
        s.cv_results_["mean_test_score"],
    )
    np.testing.assert_array_equal(back.predict(X), s.predict(X))


@pytest.mark.slow
def test_fitted_search_with_named_scorer_pickles():
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV

    s = GridSearchCV(LogisticRegression(solver="lbfgs", max_iter=20),
                     {"C": [0.5, 2.0]}, cv=2, scoring="accuracy").fit(X, y)
    back = pickle.loads(pickle.dumps(s))
    assert back.score(X, y) == pytest.approx(s.score(X, y), abs=1e-6)
