"""Host→device block streaming for larger-than-HBM datasets.

Reference equivalent: dask's chunk scheduling — blocks materialize on
workers as tasks run (SURVEY.md §2b row 1). TPU design (SURVEY.md §7
design stance #1, "the heart of the system"): the working set lives in
host RAM (numpy / np.memmap); fixed-shape blocks are placed onto the mesh
with ``jax.device_put`` ONE BLOCK AHEAD of compute (device_put is async —
issuing the next transfer before consuming the current block overlaps DMA
with compute, the double-buffer pattern), and jitted steps donate the
block buffer so XLA reuses the HBM.

Blocks have a fixed padded shape (static shapes for jit); the final
partial block carries its logical row count and a mask.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, data_shards, resolve_mesh


class Block:
    """One streamed block: device data + logical row count."""

    __slots__ = ("arrays", "n_rows", "mask")

    def __init__(self, arrays, n_rows, mask):
        self.arrays = arrays
        self.n_rows = n_rows
        self.mask = mask


class BlockStream:
    """Double-buffered epoch iterator over host arrays.

    Parameters
    ----------
    arrays : tuple of host arrays (np.ndarray / np.memmap), equal length.
    block_rows : rows per block (rounded up to a multiple of the mesh's
        data-axis size).
    shuffle : shuffle block order each epoch (the reference's
        ``shuffle_blocks``); rows within a block keep locality.
    """

    def __init__(self, arrays, block_rows, mesh=None, shuffle=False,
                 seed=None, dtype=np.float32):
        self.mesh = resolve_mesh(mesh)
        self.arrays = tuple(arrays)
        n = len(self.arrays[0])
        for a in self.arrays:
            if len(a) != n:
                raise ValueError("arrays have inconsistent lengths")
        self.n_rows = n
        shards = data_shards(self.mesh)
        self.block_rows = max(
            int(np.ceil(block_rows / shards)) * shards, shards
        )
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.dtype = dtype
        self.n_blocks = int(np.ceil(n / self.block_rows))
        self._shardings = tuple(
            NamedSharding(self.mesh, P(*((DATA_AXIS,) + (None,) * (a.ndim - 1))))
            for a in self.arrays
        )
        self._mask_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

    def _block_host(self, b):
        lo = b * self.block_rows
        hi = min(lo + self.block_rows, self.n_rows)
        m = hi - lo
        outs = []
        for a in self.arrays:
            blk = np.asarray(a[lo:hi], dtype=self.dtype)
            if m < self.block_rows:  # fixed shape: pad the tail block
                pad = [(0, self.block_rows - m)] + [(0, 0)] * (blk.ndim - 1)
                blk = np.pad(blk, pad)
            outs.append(blk)
        mask = np.zeros(self.block_rows, self.dtype)
        mask[:m] = 1.0
        return outs, m, mask

    def _put(self, host_block):
        outs, m, mask = host_block
        dev = tuple(
            jax.device_put(a, s) for a, s in zip(outs, self._shardings)
        )
        return Block(dev, m, jax.device_put(mask, self._mask_sharding))

    def __iter__(self):
        order = np.arange(self.n_blocks)
        if self.shuffle:
            self.rng.shuffle(order)
        # one-ahead prefetch: transfer of block i+1 overlaps compute on i
        pending = None
        for b in order:
            nxt = self._put(self._block_host(b))
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    def __len__(self):
        return self.n_blocks

    def epochs(self, n_epochs):
        for _ in range(n_epochs):
            yield from self
