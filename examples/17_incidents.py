"""The incident plane: alert rules over the live registry, black-box
capture, and on-demand deep profiling (ISSUE 20).

The planes so far *watch* — this example closes the loop from a
breaching signal to a reviewable artifact:

- **alert rules engine** — ``config.obs_alert_rules`` holds
  declarative host-side rules (``<counter>:rate>N/Ws``,
  ``<gauge>:gauge>X``, ``<counter>:counter>=N``) evaluated by ONE
  ticker over the live counter/gauge registries (pure host dicts,
  zero device syncs); built-ins ride along (watchdog stalls,
  post-warmup recompiles, fleet SLO burn, drift, typed errors). Rules
  fire on the first breaching tick and resolve after two clean ones;
- **black-box incident capture** — every firing transition freezes
  one rate-limited, atomic JSON bundle under ``config.incident_dir``:
  open spans, counter/gauge/histogram snapshots, the programs table,
  device memory, the armed fault plan, a config fingerprint;
- **deep profiling** — ``POST /profile?seconds=N`` (or
  ``incidents.deep_profile``) runs a bounded ``jax.profiler`` window
  on TPU and answers the documented no-op-with-reason off it.

Both knobs at their "" defaults build no engine, no thread, no bundle
dir (``tests/test_incident_plane.py`` asserts the streamed-SGD jaxpr
is byte-identical either way).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.observability import alerts, incidents, span
from dask_ml_tpu.observability import report as report_cli

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 20_000))
X, y = make_classification(n_samples=n, n_features=16, n_informative=8,
                           random_state=0)
clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
Xh = X.to_numpy().astype(np.float32)

workdir = tempfile.mkdtemp(prefix="incidents_example_")
idir = os.path.join(workdir, "incidents")

# -- arm the plane: one gauge rule + the built-ins ---------------------------
#    (a tiny tick interval keeps the example fast; production default
#    is 5s. Arming normally happens implicitly on the same entry paths
#    as the telemetry exporter — ensure_engine() is the explicit form.)
#    (trace_dir gives the spans a sink — open spans register in the
#    live registry the bundles freeze — and collects the JSONL alert
#    transition records the report CLI renders)
with config.set(obs_alert_rules="example_queue_depth:gauge>100",
                incident_dir=idir, obs_alert_interval_s=0.1,
                trace_dir=os.path.join(workdir, "trace")):
    eng = alerts.ensure_engine()
    print(f"engine armed: {len(eng.rules)} rules "
          f"({sum(1 for r in eng.rules if r.builtin)} built-in)")

    # -- drive the gauge over the line while a span is open ------------------
    from dask_ml_tpu.observability.live import gauge_set

    with span("incidents_example.overload"):
        gauge_set("example_queue_depth", 250.0)
        deadline = time.time() + 10
        while "example_queue_depth:gauge>100.0" \
                not in alerts.alerts_data()["firing"]:
            assert time.time() < deadline, "rule never fired"
            time.sleep(0.05)
        print("rule firing:",
              [r["rule"] for r in eng.rows() if r["state"] == "firing"])

    # the firing transition froze ONE bundle (rate-limited: a storm of
    # transitions in the same window still writes just one; the write
    # happens on the ticker thread — wait for the atomic publish)
    deadline = time.time() + 10
    while not (os.path.isdir(idir)
               and any(f.startswith("incident_")
                       and f.endswith(".json")
                       for f in os.listdir(idir))):
        assert time.time() < deadline, "bundle never published"
        time.sleep(0.05)
    bundles = incidents.load_bundles(idir)
    b = bundles[0]
    print(f"bundle: reason={b['reason']!r} open_spans="
          f"{[s['span'] for s in b['open_spans']]} "
          f"counters={len(b['counters'])} "
          f"fingerprint={b['config']['fingerprint'][:12]}...")
    assert b["reason"] == "alert:example_queue_depth:gauge>100.0"
    assert any(s["span"] == "incidents_example.overload"
               for s in b["open_spans"])
    assert incidents.capture_incident("second-attempt") is None, \
        "rate limit should refuse a second capture inside the window"

    # -- recovery: two clean ticks resolve (hysteresis) ----------------------
    gauge_set("example_queue_depth", 3.0)
    deadline = time.time() + 10
    while alerts.alerts_data()["firing"]:
        assert time.time() < deadline, "rule never resolved"
        time.sleep(0.05)
    states = [t["state"] for t in alerts.alerts_data()["transitions"]]
    print(f"transitions: {states}")

    # -- deep profiling: real device traces on TPU, reasoned no-op off -------
    out = incidents.deep_profile(seconds=1)
    print(f"deep_profile: {json.dumps(out)[:100]}")

    # -- the offline reader: report --incidents <dir> ------------------------
    print("--- report --incidents " + "-" * 37)
    rc = report_cli.main(["--incidents", idir])
    assert rc == 0

alerts.stop_engine()
print("incident plane example done")
