"""Public pairwise metrics namespace (ref: dask_ml/metrics/pairwise.py).

The raw primitives in ``ops/pairwise.py`` operate on padded device arrays
(internal hot paths — KMeans, Nyström — mask padding themselves). The
PUBLIC functions here accept ShardedArray / numpy / jax inputs and return
results sliced to the logical rows, matching the reference's contract
that ``pairwise_distances(X, Y)`` has exactly ``len(X)`` rows.
"""

from __future__ import annotations

import functools

from ..ops import pairwise as _ops
from ..ops.pairwise import PAIRWISE_KERNEL_FUNCTIONS  # noqa: F401


def _logical_rows(x):
    if hasattr(x, "data") and hasattr(x, "n_rows"):
        return x.n_rows
    return None


def _public(fn, n_outputs=1):
    @functools.wraps(fn)
    def wrapped(X, Y=None, *args, **kwargs):
        # sklearn/dask-ml contract: Y=None means X-vs-X, Y passed by
        # keyword works
        n = _logical_rows(X)
        if Y is None:
            Y = X
        out = fn(_ops._unwrap_x(X), _ops._unwrap_y(Y), *args, **kwargs)
        if n is None:
            return out
        if n_outputs == 1:
            return out[:n]
        return tuple(o[:n] for o in out)

    return wrapped


pairwise_distances = _public(_ops.pairwise_distances)
pairwise_kernels = _public(_ops.pairwise_kernels)
euclidean_distances = _public(_ops.euclidean_distances)
manhattan_distances = _public(_ops.manhattan_distances)
cosine_distances = _public(_ops.cosine_distances)
linear_kernel = _public(_ops.linear_kernel)
rbf_kernel = _public(_ops.rbf_kernel)
polynomial_kernel = _public(_ops.polynomial_kernel)
sigmoid_kernel = _public(_ops.sigmoid_kernel)
pairwise_distances_argmin_min = _public(
    _ops.pairwise_distances_argmin_min, n_outputs=2
)


def pairwise_distances_argmin(X, Y=None):
    """sklearn's argmin-only variant: the labels half of
    pairwise_distances_argmin_min (euclidean — the metric the device
    kernel implements; an honest narrow signature beats a TypeError
    deep in the ops layer)."""
    return pairwise_distances_argmin_min(X, Y)[0]

__all__ = [
    "cosine_distances", "euclidean_distances", "linear_kernel",
    "manhattan_distances", "pairwise_distances",
    "pairwise_distances_argmin", "pairwise_distances_argmin_min",
    "pairwise_kernels", "polynomial_kernel", "rbf_kernel",
    "sigmoid_kernel",
]
