"""OneHotEncoder / OrdinalEncoder.

Reference: ``dask_ml/preprocessing/_encoders.py`` +
``dask_ml/preprocessing/data.py::{Categorizer, DummyEncoder,
OrdinalEncoder}`` (SURVEY.md §2a encoders rows). The reference has two
paths: a pandas-categorical fast path and an array path that wants known
categories. Here:

- array path: categories per column either given or derived (one host
  pass); transform is a fused device comparison program producing dense
  one-hot (TPU has no sparse — SURVEY.md §7 hard parts).
- DataFrame path (Categorizer / DummyEncoder / OrdinalEncoder): pandas
  categorical semantics on host, matching the reference's dtype-driven
  behavior.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..base import BaseEstimator, TransformerMixin, to_host
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_is_fitted


def _column_categories(col):
    return np.unique(col)


class OneHotEncoder(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/preprocessing/_encoders.py::OneHotEncoder. Dense
    output only (sparse_output=False default; True raises — no sparse on
    TPU)."""

    def __init__(self, categories="auto", drop=None, sparse_output=False,
                 dtype=np.float32, handle_unknown="error"):
        self.categories = categories
        self.drop = drop
        self.sparse_output = sparse_output
        self.dtype = dtype
        self.handle_unknown = handle_unknown

    def fit(self, X, y=None):
        if self.sparse_output:
            raise ValueError(
                "sparse_output=True is not supported on TPU; dense one-hot "
                "only (reference requires scipy.sparse here)"
            )
        if isinstance(X, pd.DataFrame):
            self._frame = True
            self.categories_ = [
                np.asarray(X[c].cat.categories)
                if isinstance(X[c].dtype, pd.CategoricalDtype)
                else _column_categories(X[c].to_numpy())
                for c in X.columns
            ]
            self.feature_names_in_ = np.asarray(X.columns, dtype=object)
        else:
            self._frame = False
            Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
            if self.categories == "auto":
                self.categories_ = [
                    _column_categories(Xh[:, j]) for j in range(Xh.shape[1])
                ]
            else:
                self.categories_ = [np.asarray(c) for c in self.categories]
        self.n_features_in_ = len(self.categories_)
        self.drop_idx_ = self._compute_drop_idx()
        return self

    def _compute_drop_idx(self):
        """sklearn's ``drop`` contract: None, 'first', 'if_binary', or an
        array of one category per feature (entries may be None)."""
        if self.drop is None:
            return None
        if isinstance(self.drop, str) and self.drop == "first":
            return np.zeros(len(self.categories_), dtype=object)
        if isinstance(self.drop, str) and self.drop == "if_binary":
            return np.asarray(
                [0 if len(c) == 2 else None for c in self.categories_],
                dtype=object,
            )
        drop = np.asarray(self.drop, dtype=object)
        if drop.shape != (len(self.categories_),):
            raise ValueError(
                f"drop should be of shape ({len(self.categories_)},), "
                f"got {drop.shape}"
            )
        idx = []
        for j, (d, cats) in enumerate(zip(drop, self.categories_)):
            if d is None:
                idx.append(None)
                continue
            where = np.flatnonzero(cats == d)
            if len(where) == 0:
                raise ValueError(
                    f"drop[{j}]={d!r} is not a category of feature {j}: "
                    f"{list(cats)}"
                )
            idx.append(int(where[0]))
        return np.asarray(idx, dtype=object)

    def _keep_indices(self):
        """Global output-column indices kept after ``drop``, or None when
        nothing is dropped (fast path: a gather is skipped entirely)."""
        if getattr(self, "drop_idx_", None) is None:
            return None
        keep, start = [], 0
        for j, cats in enumerate(self.categories_):
            di = self.drop_idx_[j]
            keep.extend(
                start + k for k in range(len(cats))
                if di is None or k != di
            )
            start += len(cats)
        return np.asarray(keep, dtype=np.int32)

    def transform(self, X):
        check_is_fitted(self, "categories_")
        if isinstance(X, pd.DataFrame):
            cols = [X[c].to_numpy() for c in X.columns]
            mesh = None
        elif isinstance(X, ShardedArray):
            cols = None
            mesh = X.mesh
        else:
            X = np.asarray(X)
            cols = [X[:, j] for j in range(X.shape[1])]
            mesh = None

        keep = self._keep_indices()
        if cols is not None:  # host path
            outs = []
            for col, cats in zip(cols, self.categories_):
                unknown = ~np.isin(col, cats)
                if unknown.any() and self.handle_unknown == "error":
                    raise ValueError(
                        f"found unknown categories {np.unique(col[unknown])}"
                    )
                onehot = (col[:, None] == cats[None, :]).astype(self.dtype)
                outs.append(onehot)
            full = np.concatenate(outs, axis=1)
            return full if keep is None else full[:, keep]

        # device path: fused comparisons per column — unknown checks run
        # over the FULL one-hot (a dropped category's all-zero row is
        # legitimate), the ``drop`` gather comes after
        data = X.data
        mask = X.row_mask(data.dtype)
        outs = []
        for j, cats in enumerate(self.categories_):
            cats_d = jnp.asarray(cats, data.dtype)
            onehot = (data[:, j][:, None] == cats_d[None, :]).astype(data.dtype)
            outs.append(onehot)
        out = jnp.concatenate(outs, axis=1) * mask[:, None]
        if self.handle_unknown == "error":
            # a row with no matching category in some column is unknown
            start = 0
            host_check = to_host(out)
            for cats in self.categories_:
                seg = host_check[: X.n_rows, start:start + len(cats)]
                if (seg.sum(axis=1) == 0).any():
                    raise ValueError("found unknown categories in input")
                start += len(cats)
        if keep is not None:
            out = out[:, jnp.asarray(keep)]
        return ShardedArray(out, X.n_rows, X.mesh)

    def get_feature_names_out(self, input_features=None):
        check_is_fitted(self, "categories_")
        if input_features is None:
            input_features = getattr(
                self, "feature_names_in_",
                [f"x{i}" for i in range(self.n_features_in_)],
            )
        names = []
        for j, (f, cats) in enumerate(zip(input_features, self.categories_)):
            di = (None if getattr(self, "drop_idx_", None) is None
                  else self.drop_idx_[j])
            names.extend(
                f"{f}_{c}" for k, c in enumerate(cats)
                if di is None or k != di
            )
        return np.asarray(names, dtype=object)

    def inverse_transform(self, X):
        """Map one-hot columns back to the original categories (sklearn's
        OneHotEncoder.inverse_transform; per-column argmax over each
        category segment). All-zero segments (unknowns dropped by
        handle_unknown='ignore') map to None, as in sklearn."""
        check_is_fitted(self, "categories_")
        Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
        drop_idx = getattr(self, "drop_idx_", None)
        seg_cats = []  # per feature: (kept categories, dropped cat or None)
        for j, cats in enumerate(self.categories_):
            di = None if drop_idx is None else drop_idx[j]
            if di is None:
                seg_cats.append((np.asarray(cats), None))
            else:
                kept = np.asarray(
                    [c for k, c in enumerate(cats) if k != di], dtype=cats.dtype
                )
                seg_cats.append((kept, cats[di]))
        n_out = sum(len(kept) for kept, _ in seg_cats)
        if Xh.shape[1] != n_out:
            raise ValueError(
                f"Expected {n_out} one-hot columns, got {Xh.shape[1]}"
            )
        cols, start, any_unknown = [], 0, False
        for kept, dropped in seg_cats:
            if len(kept) == 0:
                # a single-category feature fully dropped: every row is
                # the dropped constant (sklearn reconstructs it too)
                cols.append(np.full(Xh.shape[0], dropped))
                continue
            seg = Xh[:, start:start + len(kept)]
            vals = kept[np.argmax(seg, axis=1)]
            zero = seg.max(axis=1) == 0
            if zero.any():
                if dropped is not None:
                    # all-zero with a dropped category means THAT category
                    # (sklearn's inverse under drop=), not unknown
                    vals = vals.copy()
                    vals[zero] = dropped
                else:
                    any_unknown = True
                    vals = vals.astype(object)
                    vals[zero] = None
            cols.append(vals)
            start += len(kept)
        dtypes = {c.dtype for c in cols}
        if any_unknown or len(dtypes) > 1:
            # object output preserves each column's native type (a plain
            # stack would coerce, e.g. floats to strings next to a
            # string column — sklearn returns object here)
            out = np.empty((Xh.shape[0], len(cols)), dtype=object)
            for j, c in enumerate(cols):
                out[:, j] = c
            return out
        return np.stack(cols, axis=1)


class OrdinalEncoder(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/preprocessing/data.py::OrdinalEncoder — DataFrame
    categorical-dtype based; array path maps via per-column categories."""

    def __init__(self, categories="auto", dtype=np.float32):
        self.categories = categories
        self.dtype = dtype

    def fit(self, X, y=None):
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            # post-Categorizer partitions share GLOBAL categorical dtypes,
            # so the first partition carries everything fit needs
            return self.fit(X.partitions[0])
        if isinstance(X, pd.DataFrame):
            self.categorical_columns_ = [
                c for c in X.columns
                if isinstance(X[c].dtype, pd.CategoricalDtype)
            ]
            self.categories_ = [
                np.asarray(X[c].cat.categories)
                for c in self.categorical_columns_
            ]
            self.columns_ = np.asarray(X.columns, dtype=object)
        else:
            Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
            if self.categories == "auto":
                self.categories_ = [
                    _column_categories(Xh[:, j]) for j in range(Xh.shape[1])
                ]
            else:
                self.categories_ = [np.asarray(c) for c in self.categories]
        self.n_features_in_ = (
            len(self.columns_) if hasattr(self, "columns_")
            else len(self.categories_)
        )
        return self

    def transform(self, X):
        check_is_fitted(self, "categories_")
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            return X.map_partitions(self.transform)
        if isinstance(X, pd.DataFrame):
            out = X.copy()
            for c in self.categorical_columns_:
                out[c] = X[c].cat.codes
            return out
        Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
        cols = []
        for j, cats in enumerate(self.categories_):
            codes = np.searchsorted(cats, Xh[:, j])
            cols.append(codes.astype(self.dtype))
        out = np.stack(cols, axis=1)
        if isinstance(X, ShardedArray):
            return ShardedArray.from_array(out, X.mesh)
        return out


class Categorizer(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/preprocessing/data.py::Categorizer — convert object /
    string columns of a DataFrame to pandas categorical dtype (the dtype
    contract DummyEncoder/OrdinalEncoder consume)."""

    def __init__(self, categories=None, columns=None):
        self.categories = categories
        self.columns = columns

    def fit(self, X, y=None):
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            return self._fit_partitioned(X)
        if not isinstance(X, pd.DataFrame):
            raise TypeError(
                "Categorizer requires a pandas DataFrame or PartitionedFrame"
            )
        columns = self.columns
        if columns is None:
            # object (pandas<3) or str/string (pandas>=3) or categorical
            columns = [
                c for c in X.columns
                if pd.api.types.is_object_dtype(X[c].dtype)
                or pd.api.types.is_string_dtype(X[c].dtype)
                or isinstance(X[c].dtype, pd.CategoricalDtype)
            ]
        categories = {}
        for c in columns:
            if self.categories is not None and c in self.categories:
                categories[c] = self.categories[c]
            elif isinstance(X[c].dtype, pd.CategoricalDtype):
                categories[c] = X[c].dtype
            else:
                categories[c] = pd.CategoricalDtype(
                    pd.unique(X[c].dropna())
                )
        self.categories_ = categories
        self.columns_ = pd.Index(columns)
        return self

    def _fit_partitioned(self, X):
        """Global category union across partitions — the reference's
        distributed known-categories build (dd ``.cat.as_known()``)."""
        columns = self.columns
        if columns is None:
            columns = [
                c for c in X.columns
                if pd.api.types.is_object_dtype(X.dtypes[c])
                or pd.api.types.is_string_dtype(X.dtypes[c])
                or isinstance(X.dtypes[c], pd.CategoricalDtype)
            ]
        fixed = {
            c: (self.categories[c] if self.categories is not None
                and c in self.categories else None)
            for c in columns
        }
        need_global = [
            c for c in columns
            if fixed[c] is None
            and not isinstance(X.dtypes[c], pd.CategoricalDtype)
        ]
        global_cats = X.global_categories(need_global) if need_global else {}
        categories = {}
        for c in columns:
            if fixed[c] is not None:
                categories[c] = fixed[c]
            elif isinstance(X.dtypes[c], pd.CategoricalDtype):
                categories[c] = X.dtypes[c]
            else:
                categories[c] = global_cats[c]
        self.categories_ = categories
        self.columns_ = pd.Index(columns)
        return self

    def transform(self, X, y=None):
        check_is_fitted(self, "categories_")
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            return X.map_partitions(self.transform)
        X = X.copy()
        for c, dtype in self.categories_.items():
            X[c] = X[c].astype(dtype)
        return X


class DummyEncoder(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/preprocessing/data.py::DummyEncoder — pd.get_dummies
    on categorical-dtype columns with stable column order."""

    def __init__(self, columns=None, drop_first=False):
        self.columns = columns
        self.drop_first = drop_first

    def fit(self, X, y=None):
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            # post-Categorizer partitions share GLOBAL categorical dtypes
            return self.fit(X.partitions[0])
        if not isinstance(X, pd.DataFrame):
            raise TypeError(
                "DummyEncoder requires a pandas DataFrame or "
                "PartitionedFrame"
            )
        columns = self.columns
        if columns is None:
            columns = [
                c for c in X.columns
                if isinstance(X[c].dtype, pd.CategoricalDtype)
            ]
        for c in columns:
            if not isinstance(X[c].dtype, pd.CategoricalDtype):
                raise ValueError(
                    f"column {c!r} is not categorical; run Categorizer first"
                )
        self.columns_ = pd.Index(columns)
        self.categorical_columns_ = self.columns_
        self.non_categorical_columns_ = X.columns.drop(self.columns_)
        self.transformed_columns_ = pd.Index(
            list(self.non_categorical_columns_) + [
                f"{c}_{cat}" for c in self.columns_
                for cat in (
                    X[c].cat.categories[1:] if self.drop_first
                    else X[c].cat.categories
                )
            ]
        )
        return self

    def transform(self, X, y=None):
        check_is_fitted(self, "columns_")
        from ..parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            return X.map_partitions(self.transform)
        out = pd.get_dummies(X, columns=list(self.columns_),
                             drop_first=self.drop_first)
        return out.reindex(columns=self.transformed_columns_, fill_value=0)

    def inverse_transform(self, X):
        check_is_fitted(self, "columns_")
        out = X[list(self.non_categorical_columns_)].copy()
        for c in self.columns_:
            prefix = f"{c}_"
            dummy_cols = [
                col for col in X.columns if str(col).startswith(prefix)
            ]
            cats = [str(col)[len(prefix):] for col in dummy_cols]
            out[c] = pd.Categorical.from_codes(
                np.argmax(X[dummy_cols].to_numpy(), axis=1), cats
            )
        return out
