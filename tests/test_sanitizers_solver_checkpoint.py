"""NaN/Inf sanitizers + solver-iteration checkpointing (VERDICT r2 #7,
SURVEY.md §5 rows 2-4): poisoned input must raise, not silently
"converge"; a killed long-running solve resumes mid-solve."""

import os

import numpy as np
import pytest

from dask_ml_tpu.parallel import as_sharded


@pytest.fixture(scope="module")
def poisoned():
    rng = np.random.RandomState(0)
    X = rng.randn(320, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xbad = X.copy()
    Xbad[7, 3] = np.nan
    return X, Xbad, y


@pytest.mark.parametrize("solver", [
    "lbfgs", "newton", "gradient_descent", "admm",
])
def test_poisoned_input_raises_resident(poisoned, solver):
    from dask_ml_tpu.linear_model import LogisticRegression

    _, Xbad, y = poisoned
    with pytest.raises(FloatingPointError, match="non-finite"):
        LogisticRegression(solver=solver, max_iter=10).fit(
            as_sharded(Xbad), as_sharded(y)
        )


def test_poisoned_input_raises_streamed(poisoned, tmp_path):
    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression

    _, Xbad, y = poisoned
    with config.set(stream_block_rows=100):
        with pytest.raises(FloatingPointError, match="non-finite"):
            LogisticRegression(solver="lbfgs", max_iter=10).fit(Xbad, y)


def test_poisoned_input_raises_kmeans(poisoned):
    from dask_ml_tpu.cluster import KMeans

    X, Xbad, _ = poisoned
    init = X[:3]
    with pytest.raises(FloatingPointError, match="non-finite"):
        KMeans(n_clusters=3, init=init, max_iter=10).fit(as_sharded(Xbad))


def test_clean_input_unaffected(poisoned):
    from dask_ml_tpu.linear_model import LogisticRegression

    X, _, y = poisoned
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(
        as_sharded(X), as_sharded(y)
    )
    assert np.isfinite(clf.coef_).all()


@pytest.mark.slow
def test_lbfgs_kill_and_resume(tmp_path, poisoned, monkeypatch):
    """Every-k-iteration checkpointing: a solve killed mid-run resumes
    from the last saved chunk and reaches the same answer as an
    uninterrupted solve."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.utils import checkpoint as ckpt

    X, _, y = poisoned
    Xs, ys = as_sharded(X), as_sharded(y)
    path = str(tmp_path / "solver_ckpt")
    kw = dict(solver="lbfgs", max_iter=40, tol=0.0,
              solver_kwargs={"checkpoint_path": path,
                             "checkpoint_every": 10})

    # uninterrupted reference (no checkpointing)
    ref = LogisticRegression(solver="lbfgs", max_iter=40, tol=0.0).fit(
        Xs, ys
    )

    # kill after the 2nd chunk save (i.e. at iteration 20)
    real_save = ckpt.save_pytree
    saves = {"n": 0}

    def dying_save(p, tree, force=True):
        real_save(p, tree, force=force)
        saves["n"] += 1
        if saves["n"] == 2:
            raise KeyboardInterrupt("injected kill")

    monkeypatch.setattr(ckpt, "save_pytree", dying_save)
    with pytest.raises(KeyboardInterrupt):
        LogisticRegression(**kw).fit(Xs, ys)
    monkeypatch.setattr(ckpt, "save_pytree", real_save)
    assert os.path.exists(path)

    # resume: picks up at iteration 20, not zero
    clf = LogisticRegression(**kw).fit(Xs, ys)
    assert clf.solver_info_["resumed_from"] == 20
    assert clf.solver_info_["n_iter"] == 40
    np.testing.assert_allclose(clf.coef_, ref.coef_, rtol=1e-5, atol=1e-7)
    # a COMPLETED solve clears its checkpoint: re-fitting with different
    # params on the same path must not return the stale beta
    assert not os.path.exists(path)
    clf_c10 = LogisticRegression(solver="lbfgs", max_iter=40, tol=0.0,
                                 C=10.0, solver_kwargs=kw["solver_kwargs"]
                                 ).fit(Xs, ys)
    assert clf_c10.solver_info_["resumed_from"] == 0
    assert not np.allclose(clf_c10.coef_, clf.coef_)

    # fresh path: no resume
    kw2 = dict(kw)
    kw2["solver_kwargs"] = {"checkpoint_path": str(tmp_path / "other"),
                            "checkpoint_every": 10}
    clf2 = LogisticRegression(**kw2).fit(Xs, ys)
    assert clf2.solver_info_["resumed_from"] == 0


def test_streamed_kmeans_kill_and_resume(tmp_path, monkeypatch):
    """Streamed (out-of-core) Lloyd checkpoints centers every k passes
    and resumes mid-run after a kill."""
    from dask_ml_tpu import config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.utils import checkpoint as ckpt

    rng = np.random.RandomState(5)
    centers_true = rng.randn(3, 5).astype(np.float32) * 2
    X = np.concatenate([
        centers_true[i] + 1.5 * rng.randn(400, 5).astype(np.float32)
        for i in range(3)
    ])
    rng.shuffle(X)
    init = X[:3].copy()  # poor init: overlapping blobs need many passes
    path = str(tmp_path / "km_ckpt")
    kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0,
              checkpoint_path=path, checkpoint_every=1)

    with config.set(stream_block_rows=400):
        ref = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(X)
        assert ref.n_iter_ > 3  # premise: the kill interrupts mid-run

        real_save = ckpt.save_pytree
        saves = {"n": 0}

        def dying_save(p, tree, force=True):
            real_save(p, tree, force=force)
            saves["n"] += 1
            if saves["n"] == 2:  # dies at iteration 2 (saves every pass)
                raise KeyboardInterrupt("injected kill")

        monkeypatch.setattr(ckpt, "save_pytree", dying_save)
        with pytest.raises(KeyboardInterrupt):
            KMeans(**kw).fit(X)
        monkeypatch.setattr(ckpt, "save_pytree", real_save)
        assert os.path.exists(path)

        km = KMeans(**kw).fit(X)
    np.testing.assert_allclose(km.cluster_centers_, ref.cluster_centers_,
                               rtol=1e-4, atol=1e-4)
    assert not os.path.exists(path)  # cleared on completion


def test_kmeans_multiblock_larger_kd_parity():
    """>1-block KMeans at larger k/d matches sklearn's converged
    solution from the same init (VERDICT r2 weak #9)."""
    from sklearn.cluster import KMeans as SkKMeans

    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(6)
    k, d = 32, 96
    centers_true = rng.randn(k, d).astype(np.float32) * 3
    X = np.concatenate([
        centers_true[i] + 0.2 * rng.randn(80, d).astype(np.float32)
        for i in range(k)
    ])
    rng.shuffle(X)
    init = (centers_true + 0.3 * rng.randn(k, d)).astype(np.float32)

    ours = KMeans(n_clusters=k, init=init, max_iter=100, tol=1e-6).fit(
        as_sharded(X)
    )
    sk = SkKMeans(n_clusters=k, init=init, n_init=1, max_iter=100,
                  tol=1e-6).fit(X)
    np.testing.assert_allclose(ours.inertia_, sk.inertia_, rtol=1e-3)
    # same init, same Lloyd fixed point: centers match up to tolerance
    np.testing.assert_allclose(
        np.sort(ours.cluster_centers_, axis=0),
        np.sort(sk.cluster_centers_, axis=0), atol=5e-2,
    )


def test_kmeans_checkpoint_identity_and_resident_path(tmp_path):
    """A stale KMeans checkpoint from a DIFFERENT fit is ignored (identity
    token), and the resident (in-memory) path also checkpoints."""
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(7)
    X = np.concatenate([
        rng.randn(300, 4).astype(np.float32) + 3 * i for i in range(3)
    ])
    rng.shuffle(X)
    path = str(tmp_path / "ck")
    init = X[:3].copy()

    # resident path writes and clears its checkpoint
    km = KMeans(n_clusters=3, init=init, max_iter=10, tol=0.0,
                checkpoint_path=path, checkpoint_every=2).fit(as_sharded(X))
    assert km.n_iter_ >= 1
    assert not os.path.exists(path)

    # leave a stale checkpoint behind (simulated kill), then fit with
    # DIFFERENT data content: token mismatch -> fresh run, same answer as
    # a checkpoint-free fit
    from dask_ml_tpu.models.kmeans import _LloydCheckpoint

    stale = _LloydCheckpoint(path, 2, "deadbeef" * 5, 3, 4)
    stale.save(np.zeros((3, 4), np.float32), 7)
    X2 = X + 0.5
    ref = KMeans(n_clusters=3, init=init, max_iter=10, tol=0.0).fit(
        as_sharded(X2)
    )
    km2 = KMeans(n_clusters=3, init=init, max_iter=10, tol=0.0,
                 checkpoint_path=path, checkpoint_every=2).fit(
        as_sharded(X2)
    )
    np.testing.assert_allclose(km2.cluster_centers_, ref.cluster_centers_,
                               rtol=1e-5, atol=1e-5)
