"""Device-side building blocks for bucketed-nnz sparse blocks.

A staged sparse block is a fixed-shape COO-expanded CSR triple —
``data (cap,) float32``, ``cols (cap,) int32``, ``rows (cap,) int32``
(row id per nonzero, local to the block/slab) — padded to an
nnz-bucket capacity with ``data == 0`` entries (rows/cols of padding
point at slot 0, which a zero value cannot perturb). Everything here is
built from ``jnp.take`` + ``jax.ops.segment_sum`` so XLA's own cost
model attributes nnz-proportional FLOPs/bytes (never n x d), and the
take-based matvec is autodiff-friendly: the backward pass of ``take``
is the scatter-add that computes the nnz-proportional gradient.

Masking contract: validity is per ROW (the streamed prefix-count mask),
exactly like the dense blocks — padding NNZ entries carry zero values
and thus vanish from every sum on their own, while ragged-tail ROWS are
dropped by the same ``(arange(S) < count)`` mask the dense kernels use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "sparse_eta", "sparse_eta_multi", "sparse_densify",
    "sparse_sq_norms", "sparse_center_dots", "sparse_label_sums",
]


def sparse_eta(data, cols, rows, w_feat, n_rows: int):
    """``X @ w_feat`` of one sparse block: (n_rows,) row sums of
    ``data * w_feat[cols]``. Differentiable in ``w_feat`` at nnz cost
    (the take's backward is a scatter-add)."""
    contrib = data * jnp.take(w_feat, cols)
    return jax.ops.segment_sum(contrib, rows, num_segments=n_rows)


def sparse_eta_multi(data, cols, rows, W_feat, n_rows: int):
    """``X @ W_feat.T`` of one sparse block: (n_rows, C). One gather of
    the (C,)-wide weight columns per nonzero — the multiclass OvR
    analog of :func:`sparse_eta` (all C classes served by one pass over
    the nnz)."""
    contrib = data[:, None] * jnp.take(W_feat.T, cols, axis=0)  # (cap, C)
    return jax.ops.segment_sum(contrib, rows, num_segments=n_rows)


def sparse_densify(data, cols, rows, n_rows: int, n_features: int,
                   dtype=jnp.float32):
    """Scatter the block dense on DEVICE — the escape hatch for math
    that is intrinsically O(d^2) anyway (the streamed Newton Hessian
    X^T W X): one (n_rows, n_features) buffer per block, never the
    corpus. Padding entries add zero at [0, 0]."""
    out = jnp.zeros((n_rows, n_features), dtype)
    return out.at[rows, cols].add(data.astype(dtype))


def sparse_sq_norms(data, rows, n_rows: int):
    """Per-row ||x||^2 of one sparse block."""
    return jax.ops.segment_sum(data * data, rows, num_segments=n_rows)


def sparse_center_dots(data, cols, rows, centers, n_rows: int):
    """``X @ centers.T`` of one sparse block: (n_rows, k). The KMeans
    assignment's matmul at nnz * k cost."""
    contrib = data[:, None] * jnp.take(centers.T, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=n_rows)


def sparse_label_sums(data, cols, rows, labels, k: int, n_features: int):
    """Per-label feature sums of one sparse block: (k, n_features) with
    ``out[labels[r]] += X[r]`` — the KMeans stats accumulation done as
    ONE flat segment_sum over ``label * d + col`` ids (padding entries
    carry zero values and land harmlessly in segment 0)."""
    seg = jnp.take(labels, rows) * n_features + cols
    flat = jax.ops.segment_sum(data, seg, num_segments=k * n_features)
    return flat.reshape(k, n_features)
