"""ModelServer: online inference over a fitted estimator.

The serving loop the dask-ml reference never had (its inference story
stops at offline blockwise ``ParallelPostFit``): many small, concurrently
arriving requests of ragged sizes are admitted into a bounded queue,
coalesced by a micro-batcher into padded batches drawn from a geometric
ladder of shape buckets (``_buckets``), executed through one compiled
static-shape entry point per method (``wrappers.compiled_batch_fn`` —
device-resident parameters, donated ping-pong input staging), and
demultiplexed back to the callers with padding rows masked out.

Around the hot loop:

- admission control / backpressure — ``submit`` never blocks: a full
  queue sheds immediately with :class:`ServerOverloaded` (the caller's
  cue to retry elsewhere), and requests whose deadline lapses while
  queued resolve with :class:`RequestTimeout`;
- ``warmup()`` — compiles every (method, bucket) program up front, so a
  warmed server answers steady-state ragged traffic with ZERO new XLA
  compiles (asserted by the serving tests via the observability
  recompile counter);
- graceful drain — ``stop()`` (or leaving the context manager) stops
  admissions, finishes every queued request, and joins the worker;
- telemetry — per-batch ``serving.batch`` spans plus queue-depth /
  occupancy / padding-waste / shed counters through
  ``dask_ml_tpu/observability`` (``serving/metrics.py``).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..wrappers import ParamSwapError, compiled_batch_fn
from . import metrics as smetrics
from ._batching import (
    BoundedQueue,
    PingPongStaging,
    Request,
    demux_outputs,
    fail_requests,
    pack_batch,
    release_deadline,
)
from ._buckets import BucketLadder
from .policy import ExecStats

__all__ = ["ModelServer", "ServingError", "ServerOverloaded",
           "RequestTimeout", "ServerClosed", "SloShed"]


class ServingError(RuntimeError):
    """Base class for typed serving failures."""


class ServerOverloaded(ServingError):
    """Admission control shed this request: the bounded queue is full.
    Retry with backoff, widen ``max_queue``, or add replicas."""


class SloShed(ServerOverloaded):
    """SLO-aware admission shed this request: every candidate replica's
    predicted completion (queued work x predicted execution time) would
    miss ``config.serving_slo_ms``. Queueing it anyway would only add a
    guaranteed violation — retry with backoff or add capacity."""


class RequestTimeout(ServingError, TimeoutError):
    """The request's deadline passed while it waited in the queue."""


class ServerClosed(ServingError):
    """submit() after stop()/drain began."""


class ModelServer:
    """Serve ``estimator``'s post-fit methods over micro-batched
    concurrent requests.

    Parameters
    ----------
    estimator : fitted estimator or pipeline ending in one
    methods : tuple of method names to serve (compiled entry points are
        built eagerly — a typo fails at construction, not first request)
    ladder : BucketLadder, default from config
        (``serving_min_batch`` / ``serving_max_batch`` /
        ``serving_bucket_growth``)
    max_queue : int, queued-request bound for admission control
    batch_window_ms : float, coalescing wait after the first request
    timeout_ms : float, per-request queue deadline (0 = none)

    Use as a context manager::

        with ModelServer(clf).warmup() as srv:
            fut = srv.submit(x)           # -> Future
            y = srv.predict(x)            # blocking convenience
    """

    def __init__(self, estimator, methods=("predict",), ladder=None,
                 max_queue=None, batch_window_ms=None, timeout_ms=None,
                 device=None, replica_id=None, name=None):
        from ..config import get_config

        cfg = get_config()
        # config is thread-local; the worker thread re-applies the
        # config active HERE so trace_dir/metrics/counter gating follow
        # the server's creator, not the daemon thread's defaults
        self._cfg = cfg
        self.estimator = estimator
        self.ladder = ladder if ladder is not None \
            else BucketLadder.from_config()
        self.max_queue = int(cfg.serving_max_queue
                             if max_queue is None else max_queue)
        self.batch_window_s = float(
            cfg.serving_batch_window_ms
            if batch_window_ms is None else batch_window_ms
        ) / 1e3
        self.timeout_s = float(
            cfg.serving_timeout_ms if timeout_ms is None else timeout_ms
        ) / 1e3
        # deadline-aware batch release (see _batching.release_deadline):
        # armed by an SLO in the creator's config
        self._slo_s = float(cfg.serving_slo_ms) / 1e3
        # per-replica placement: the fleet commits each replica's param
        # pytrees to its own device; None = default device
        self.device = device
        self.replica_id = replica_id
        self.model_version = 0          # stamped by swap/rebuild/fleet
        # quality observability (observability/drift.py): serving-side
        # sketches + the hot-swap shadow canary, keyed by this model
        # name (a fleet stamps its registry name onto every replica).
        # The gate is captured ONCE — the worker must not pay a config
        # read per batch
        self.model_name = str(name) if name else type(estimator).__name__
        self._drift_on = bool(cfg.obs_drift)
        # request trace plane (observability/_requests.py): the gate is
        # captured ONCE, like _drift_on — with obs_trace_sample=0 the
        # hot path never allocates a trace (one bool check per admit /
        # batch), and nothing the plane does ever enters a jaxpr
        self._trace_on = float(cfg.obs_trace_sample) > 0.0
        # versions whose publish ran the shadow canary — traces served
        # by such a version carry the canary_scored tag
        self._canary_versions = set()
        self._shadow_frac = float(cfg.obs_shadow_fraction)
        self._shadow = {}               # method -> drift.ShadowBuffer
        self._pend = {}                 # method -> pending fold sample
        self._pend_lock = threading.Lock()
        self._next_fold_t = 0.0         # backpressure gate (see _execute)
        self._fns = {m: compiled_batch_fn(estimator, m, device=device)
                     for m in methods}
        # sparse (CSR-in) entry points (ISSUE 13): linear predict /
        # decision_function bucketed by (rows, nnz) — built eagerly
        # (compiles only when called/warmed) so hashed-text traffic
        # stops paying the host fallback; methods/estimators without a
        # sparse story simply have no entry here and a sparse submit
        # refuses typed
        from ..wrappers import sparse_batch_fn

        self._sparse_fns = {}
        for m in methods:
            try:
                sfn = sparse_batch_fn(estimator, m, device=device)
            except Exception:
                sfn = None
            if sfn is not None:
                self._sparse_fns[m] = sfn
        # precision-flavor table: "" (float32) plus every flavor named
        # in config.serving_warm_flavors gets its OWN entry-point set,
        # built now and warmed by warmup() — so a registry publish
        # flagged quantize="int8" (and the rollback to f32) hot-swaps
        # between flavors with ZERO new XLA compiles. Methods without
        # an int8 path (predict_proba, non-linear families) build a
        # fresh higher-precision entry point inside the flavor, so a
        # quantized server still serves them.
        self._flavor_fns = {"": self._fns}
        for fl in str(cfg.serving_warm_flavors).replace(",", " ").split():
            if fl in self._flavor_fns:
                continue
            self._flavor_fns[fl] = {
                m: compiled_batch_fn(estimator, m, device=device,
                                     quantize=fl)
                for m in methods
            }
        self._active_flavor = ""
        self._queue = BoundedQueue(self.max_queue)
        self._staging = PingPongStaging()
        self._latency = smetrics.LatencyWindow()
        self._stats_cursor = None       # windowed-quantile cursor
        self._exec = ExecStats()        # per-(method,bucket) exec times
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._accepting = False
        self._paused = threading.Event()
        self._paused.set()              # set = running, cleared = paused
        self._parked = threading.Event()  # worker acknowledged a pause
        self._batches = 0
        self._warmed = False

    # -- lifecycle --------------------------------------------------------
    def start(self):
        from ..observability.live import ensure_telemetry, register_server

        # a serving process is exactly what the live exporter exists
        # for: arm it (no-op unless config.obs_http_port is set) and
        # list this server's stats() window on /status
        ensure_telemetry()
        register_server(self)
        if self._drift_on:
            # register the served version's training profile (when the
            # fit recorded one) and arm the background drift monitor —
            # both host-only, neither touches the request path
            from ..observability import drift

            drift.note_training_profile(
                self.model_name, self.model_version,
                getattr(self.estimator, "training_profile_", None),
            )
            drift.ensure_monitor(self._cfg)
        with self._lock:
            if self._thread is not None:
                return self
            if self._queue.closed:   # restart after stop(): fresh queue
                self._queue = BoundedQueue(self.max_queue)
            self._stop.clear()
            self._accepting = True
            self._thread = threading.Thread(
                target=self._run, name="dask-ml-tpu-serving", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop admissions; with ``drain`` (default) finish every queued
        request before joining the worker, else shed them with
        ServerClosed."""
        from ..observability.live import unregister_server

        unregister_server(self)
        with self._lock:
            self._accepting = False
            thread = self._thread
        # close the queue under ITS lock: every put that succeeded
        # happens-before this, so the worker's tail drain sees it —
        # submit() racing with stop() either gets ServerClosed or a
        # request the drain is guaranteed to serve
        self._queue.close()
        if thread is None:
            # never started: resolve anything queued directly
            self._shed_queue(drain)
            if self._drift_on:
                self._flush_quality()
            return
        if not drain:
            fail_requests(self._queue.drain_all(), ServerClosed(
                "server stopped without drain"
            ))
        self._paused.set()              # a paused server must still drain
        self._stop.set()
        self._queue.wake()
        thread.join(timeout)
        with self._lock:
            self._thread = None
        if self._drift_on:
            # the drained tail's pending sample folds before callers
            # read scores (tests stop the server, then compute)
            self._flush_quality()

    def _shed_queue(self, drain):
        reqs = self._queue.drain_all()
        if not reqs:
            return
        if drain:
            for r in reqs:
                self._execute([r])
        else:
            fail_requests(reqs, ServerClosed("server stopped"),
                          outcome="closed")

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop(drain=exc_type is None)
        return False

    def pause(self):
        """Hold the worker between batches (requests keep queueing up to
        the admission bound) — maintenance windows and backpressure
        tests. Blocks briefly until the worker acknowledges the park, so
        requests submitted after pause() returns stay queued."""
        self._parked.clear()
        self._paused.clear()
        if self._thread is not None:
            self._parked.wait(5.0)
        return self

    def resume(self):
        self._paused.set()
        return self

    @property
    def healthy(self) -> bool:
        """Accepting requests with a live (or not-yet-started) worker —
        the fleet's routing predicate."""
        if not self._accepting:
            return False
        thread = self._thread
        return thread is None or thread.is_alive()

    # -- hot-swap ----------------------------------------------------------
    def swap_model(self, estimator, version=None, quantize=None):
        """Zero-recompile hot-swap: replace the served parameters with
        ``estimator``'s under the SAME compiled entry points
        (``CompiledBatchFn.swap_params`` — programs close over shapes,
        not values, so a same-shape swap mints no XLA compile; asserted
        via the recompile counters in tests and fleet_smoke). Raises
        :class:`~dask_ml_tpu.wrappers.ParamSwapError` when the new
        version is structurally incompatible — use :meth:`rebuild_model`
        then. In-flight batches finish on the old version; batches
        packed after return serve the new one. Safe under live traffic.

        ``quantize`` selects the serving precision FLAVOR for the new
        version ("int8" or None = float32). Flavors named in
        ``config.serving_warm_flavors`` were pre-built at construction
        and warmed with warmup(), so flipping a model between f32 and
        int8 is the same zero-compile swap as a same-flavor version
        push; an un-warmed flavor refuses with ParamSwapError (the
        rebuild_model cue), keeping the no-compiles-on-the-serving-path
        contract explicit.
        """
        flavor = quantize or ""
        fns = self._flavor_fns.get(flavor)
        if fns is None:
            raise ParamSwapError(
                f"serving flavor {flavor!r} was not pre-built on this "
                "server; add it to config.serving_warm_flavors (and "
                "re-warm) or install via rebuild_model"
            )
        # validate EVERY method against the new estimator before
        # mutating ANY entry point: a multi-method server must never be
        # left half-swapped (predict on v2, predict_proba on v1).
        # prepare_swap covers every entry-point flavor — compiled,
        # pipeline, host fallback — and touches no live state.
        tokens = {}
        for m, fn in fns.items():
            try:
                tokens[m] = fn.prepare_swap(estimator)
            except ParamSwapError as exc:
                raise ParamSwapError(f"method {m!r}: {exc}") from exc
        # the sparse entry points swap in the same two-phase pass — a
        # version flip must never leave dense serving v2 while sparse
        # still serves v1
        sparse_tokens = {}
        for m, fn in self._sparse_fns.items():
            try:
                sparse_tokens[m] = fn.prepare_swap(estimator)
            except ParamSwapError as exc:
                raise ParamSwapError(f"sparse method {m!r}: {exc}") \
                    from exc
        # canary phase 1 (obs_drift + a warmed server only): score the
        # shadow sample of recent traffic against the OUTGOING params
        # through the already-compiled entry points — the batch rides a
        # warmed ladder bucket, so both canary passes mint ZERO XLA
        # compiles (the zero-recompile swap contract holds with the
        # canary on)
        v_old = self.model_version
        if self._drift_on:
            # the outgoing version's pending sample must fold under ITS
            # version key before the flip
            self._flush_quality()
        old_outs = self._canary_pass() if self._drift_on else {}
        for m, fn in fns.items():
            fn.commit_swap(tokens[m])
        for m, fn in self._sparse_fns.items():
            fn.commit_swap(sparse_tokens[m])
        # flavor flip is one dict-reference assignment: the worker reads
        # self._fns[method] per batch, so it sees either the complete
        # old flavor or the complete new one
        self._fns = fns
        self._active_flavor = flavor
        self.estimator = estimator
        if version is not None:
            self.model_version = int(version)
        else:
            self.model_version += 1
        if old_outs:
            # traces served by this version carry canary_scored: the
            # publish was shadow-scored against recent traffic
            self._canary_versions.add(self.model_version)
            # canary phase 2: the SAME shadow rows through the
            # just-committed parameters; the per-method prediction
            # deltas (disagreement + max quantile shift) publish as
            # per-version series on /metrics and a JSONL drift record
            from ..observability import drift

            for m, (sample_n, old) in old_outs.items():
                try:
                    new = self._canary_run(m, sample_n[0], sample_n[1])
                    drift.record_canary(self.model_name, v_old,
                                        self.model_version, m, old, new)
                except Exception:
                    pass  # diagnostics never fail a swap
        if self._drift_on:
            from ..observability import drift

            drift.note_training_profile(
                self.model_name, self.model_version,
                getattr(estimator, "training_profile_", None),
            )
        smetrics.record_swap()
        if self.replica_id is not None:
            smetrics.set_replica_gauges(self.replica_id,
                                        version=self.model_version)
        return self

    def _canary_pass(self):
        """Run every shadow-sampled method's reservoir through the LIVE
        entry points (pre-commit = outgoing version). Returns
        {method: ((padded_batch, n_rows), outputs)} — phase 2 reruns the
        identical padded batch post-commit. Only a warmed server
        canaries (every ladder bucket is compiled, so the pass cannot
        mint a compile); failures return {} and never block the swap."""
        if not self._warmed:
            return {}
        outs = {}
        for m, buf in list(self._shadow.items()):
            fn = self._fns.get(m)
            if fn is None or not fn.jitted:
                continue
            try:
                sample = buf.sample()
                if sample is None:
                    continue
                sample = sample[: self.ladder.max_rows]
                bucket = self.ladder.bucket_for(len(sample))
                padded = np.zeros((bucket, sample.shape[1]), np.float32)
                padded[: len(sample)] = sample
                outs[m] = ((padded, len(sample)),
                           self._canary_run(m, padded, len(sample)))
            except Exception:
                continue
        return outs

    def _canary_run(self, method, padded, n_rows):
        return np.asarray(self._fns[method](padded))[:n_rows]

    _KEEP_FLAVOR = object()  # "caller didn't say": keep current flavor

    def rebuild_model(self, estimator, version=None, warm=None,
                      quantize=_KEEP_FLAVOR):
        """The slow path a shape-incompatible publish needs: build fresh
        compiled entry points for ``estimator`` (paying compiles), warm
        them off the serving path, then install atomically. ``warm``
        defaults to whether this server was warmed. Every pre-built
        flavor rebuilds (a shape change invalidates all of them);
        ``quantize`` picks which flavor serves afterward — with the
        SAME semantics as :meth:`swap_model` (None = float32; an
        int8-serving replica receiving a shape-changed f32 publish must
        come out serving f32, not its old flavor). Omitting the
        argument keeps the current flavor. Naming a flavor that wasn't
        in the table adds it (this is the paid path, so growing the
        flavor set here is fine)."""
        flavor = self._active_flavor \
            if quantize is ModelServer._KEEP_FLAVOR else (quantize or "")
        flavors = set(self._flavor_fns) | {flavor}
        table = {
            fl: {m: compiled_batch_fn(estimator, m, device=self.device,
                                      quantize=(fl or None))
                 for m in self._fns}
            for fl in flavors
        }
        if warm or (warm is None and self._warmed):
            for fns in table.values():
                self._warm_fns(fns)
        # sparse entry points rebuild alongside (fresh shapes) over the
        # SERVED methods, not the old sparse table — a server whose
        # previous estimator had no sparse story gains entry points
        # when the rebuilt one supports them; the (rows, nnz) grid
        # re-warms lazily or via warmup_sparse()
        from ..wrappers import sparse_batch_fn

        sparse_table = {}
        for m in self._fns:
            try:
                sfn = sparse_batch_fn(estimator, m, device=self.device)
            except Exception:
                sfn = None
            if sfn is not None:
                sparse_table[m] = sfn
        self._sparse_fns = sparse_table
        self._flavor_fns = table
        self._fns = table[flavor]
        self._active_flavor = flavor
        self.estimator = estimator
        if version is not None:
            self.model_version = int(version)
        else:
            self.model_version += 1
        if self._drift_on:
            # a rebuild changes shapes — the old shadow rows no longer
            # fit the new entry points, so no canary; the new version's
            # training profile still registers for train-vs-serve
            from ..observability import drift

            self._shadow.clear()
            drift.note_training_profile(
                self.model_name, self.model_version,
                getattr(estimator, "training_profile_", None),
            )
        smetrics.record_swap(rebuilt=True)
        if self.replica_id is not None:
            smetrics.set_replica_gauges(self.replica_id,
                                        version=self.model_version)
        return self

    # -- warmup -----------------------------------------------------------
    def warmup(self):
        """Compile every (method, bucket) program now, before traffic:
        one call per rung per method through the real entry point. After
        this, a workload whose batches stay on the ladder triggers zero
        new XLA compiles.

        Warming routes through the process-wide plans WarmupRegistry
        (ISSUE 15): each (entry point, rung) warms at most once per
        process — a second server over the same-shaped model (whose
        plan-cached build shares the first's compiled entry points)
        skips the redundant executions (``plan_cache_hits`` counts),
        and the plans table on ``/status`` / in the report CLI shows
        which ladder rung minted each specialization.

        With ``config.compile_cache_dir`` set, these compiles also land
        in jax's persistent compilation cache: warmup still walks the
        full (method, bucket) grid, but a later process serving the same
        model shapes replays each program from disk instead of paying
        XLA again — cold-start warmup cost becomes mostly cache reads."""
        from ..config import ensure_compile_cache

        ensure_compile_cache()
        # every pre-built flavor warms (config.serving_warm_flavors):
        # a later f32 <-> int8 flavor swap then hits only warm caches
        for fns in self._flavor_fns.values():
            self._warm_fns(fns)
        self._warmed = True
        return self

    @staticmethod
    def _plan_token(fn):
        """The warm-dedup identity of a compiled entry point: the plan
        token of its (innermost, for pipelines) tracked jit. Plan-cached
        builds share tokens exactly when they share executables, so the
        registry skips precisely the warms whose compiles already
        exist; a host fallback (or a jit built outside the plan layer)
        gets a per-object token."""
        inner = fn
        while getattr(inner, "_inner", None) is not None:
            inner = inner._inner
        tgt = getattr(inner, "_fn", None)
        tok = getattr(tgt, "plan_token", None)
        return tok if tok is not None else ("obj", id(fn))

    @staticmethod
    def _plan_prog(fn):
        """The program name warmups attribute to — the innermost
        tracked jit's (a pipeline's own ``_fn`` is None; its compiled
        program is the final step's leaf)."""
        inner = fn
        while getattr(inner, "_inner", None) is not None:
            inner = inner._inner
        return getattr(getattr(inner, "_fn", None), "program_name",
                       None)

    def _warm_fns(self, fns):
        from ..plans import warmups

        for method, fn in fns.items():
            if not fn.jitted:
                continue   # host fallback: nothing to compile
            d = fn.n_features or self._probe_width()
            if d is None:
                raise ValueError(
                    "cannot infer n_features for warmup; estimator "
                    "exposes neither fitted params nor n_features_in_"
                )
            token = self._plan_token(fn)
            prog = self._plan_prog(fn)
            # the key carries the replica's device: XLA specializes per
            # param placement, so two replicas sharing one plan-cached
            # entry point still each warm their own device's programs
            for bucket in self.ladder:
                warmups.warm(
                    ("serving", token, self.device, int(bucket),
                     int(d)),
                    lambda b=bucket: fn(np.zeros((b, d), np.float32)),
                    program=prog, ladder="serving-rows",
                    rung=int(bucket),
                )

    def _probe_width(self):
        est = self.estimator
        if hasattr(est, "steps"):
            est = est.steps[0][1]
        return getattr(est, "n_features_in_", None)

    def warmup_sparse(self, max_nnz=None):
        """Compile the sparse entry points' (rows, nnz-bucket) grid —
        every row rung x every nnz rung (bounded above by
        ``max_nnz``'s rung when given, so a deployment that knows its
        traffic density doesn't compile the whole ladder). Routed
        through the plans WarmupRegistry like the dense grid. After
        this, sparse traffic whose batches stay on the grid mints zero
        new XLA compiles; over-top-nnz batches spill to the
        (dense-warmed) densify path."""
        from ..config import ensure_compile_cache
        from ..plans import warmups

        ensure_compile_cache()
        for fn in self._sparse_fns.values():
            top = fn.nnz_ladder.max_rows if max_nnz is None \
                else fn.nnz_bucket(min(max_nnz, fn.nnz_ladder.max_rows))
            token = self._plan_token(fn)
            prog = self._plan_prog(fn)
            for rb in self.ladder:
                for nb in fn.nnz_ladder:
                    if nb > top:
                        break
                    warmups.warm(
                        ("serving-sparse", token, self.device,
                         int(rb), int(nb)),
                        lambda rb=rb, nb=nb: fn.warm(rb, nb),
                        program=prog, ladder="serving-nnz",
                        rung=int(nb),
                    )
        return self

    # -- request plane ----------------------------------------------------
    def submit(self, X, method="predict"):
        """Admit one request; returns a ``concurrent.futures.Future``
        resolving to the method's output rows for ``X``. Sheds with
        ServerOverloaded when the queue is at bound, ServerClosed after
        stop. Requests taller than the top bucket are chunked internally
        and reassembled — one Future either way."""
        if method not in self._fns:
            raise ValueError(
                f"method {method!r} not served; constructed with "
                f"methods={tuple(self._fns)}"
            )
        if not self._accepting:
            raise ServerClosed("server is not accepting requests")
        import scipy.sparse as sp_

        if sp_.issparse(X):
            return self._submit_sparse(X, method)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(
                f"expected a non-empty (n, d) request, got {X.shape}"
            )
        want = self._fns[method].n_features
        if want is not None and X.shape[1] != want:
            raise ValueError(
                f"request has {X.shape[1]} features; the served model "
                f"expects {want}"
            )
        top = self.ladder.max_rows
        if X.shape[0] <= top:
            return self._admit([Request(X, method, self.timeout_s)])
        # oversize: chunk to top-bucket tiles, admit all-or-nothing
        # (atomic in the queue — a shed mid-request must not leave
        # orphaned chunks burning capacity), reassemble via callbacks
        parts = [X[i:i + top] for i in range(0, X.shape[0], top)]
        if len(parts) > self.max_queue:
            # structurally un-admittable even against an idle server:
            # ServerOverloaded ("retry with backoff") would lie — this
            # can never succeed, so fail fast and permanently
            raise ValueError(
                f"request of {X.shape[0]} rows needs {len(parts)} "
                f"chunks but max_queue={self.max_queue}; raise "
                "max_queue or split the request"
            )
        reqs = [Request(p, method, self.timeout_s) for p in parts]
        self._admit(reqs)
        return _gather_futures([r.future for r in reqs])

    def _submit_sparse(self, X, method):
        """Admit a scipy-sparse request onto the sparse serving lane
        (ISSUE 13): CSR blocks coalesce with other sparse requests of
        the same method (never with dense ones — the lane key keeps the
        batcher's packing homogeneous), bucket by (rows, nnz) and run
        the warmed sparse entry point; over-nnz batches spill to the
        densified dense rung. Refuses typed when the served estimator
        has no sparse entry point for ``method``."""
        if method not in self._sparse_fns:
            raise ValueError(
                f"method {method!r} has no sparse entry point on this "
                "server (sparse serving covers linear predict / "
                "decision_function); densify the request or serve a "
                "linear model"
            )
        import scipy.sparse as sp_

        X = X.tocsr() if not sp_.isspmatrix_csr(X) else X
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(
                f"expected a non-empty sparse (n, d) request, got "
                f"{X.shape}"
            )
        want = self._sparse_fns[method].n_features
        if want is not None and X.shape[1] != want:
            raise ValueError(
                f"request has {X.shape[1]} features; the served model "
                f"expects {want}"
            )
        lane = method + "#sparse"
        top = self.ladder.max_rows
        if X.shape[0] <= top:
            return self._admit([Request(X, lane, self.timeout_s)])
        parts = [X[i:i + top] for i in range(0, X.shape[0], top)]
        if len(parts) > self.max_queue:
            raise ValueError(
                f"request of {X.shape[0]} rows needs {len(parts)} "
                f"chunks but max_queue={self.max_queue}; raise "
                "max_queue or split the request"
            )
        reqs = [Request(p, lane, self.timeout_s) for p in parts]
        self._admit(reqs)
        return _gather_futures([r.future for r in reqs])

    def _admit(self, reqs):
        if self._trace_on:
            # traces exist BEFORE the queue decides: a shed/closed
            # request still produces a (tail-sampled) trace — the
            # contract that 100% of refused requests are attributable
            from ..observability import _requests as rtrace

            for r in reqs:
                r.trace = rtrace.new_trace(r.method, r.n_rows,
                                           t_admit=r.t_enqueue)
                if self.replica_id is not None:
                    r.trace.tag(replica=self.replica_id)
        verdict = self._queue.put_many(reqs)
        if verdict == "closed":
            for r in reqs:
                if r.trace is not None:
                    r.trace.finish("closed")
            raise ServerClosed("server is not accepting requests")
        if verdict != "ok":
            smetrics.record_drop("shed")
            for r in reqs:
                if r.trace is not None:
                    r.trace.finish("shed")
            raise ServerOverloaded(
                f"queue at bound ({self.max_queue} requests); request "
                "shed"
            )
        for r in reqs:
            smetrics.record_request(r.n_rows)
        return reqs[0].future

    # blocking conveniences ------------------------------------------------
    def _call(self, X, method):
        import concurrent.futures as cf

        fut = self.submit(X, method=method)
        extra = self.timeout_s if self.timeout_s > 0 else None
        # queue deadline + generous execution allowance; None = wait.
        # The wait-timeout surfaces as the package's typed error (which
        # still subclasses TimeoutError), not cf's — callers are told to
        # catch ServingError subclasses.
        try:
            return fut.result(None if extra is None else 30.0 + extra)
        except cf.TimeoutError:
            raise RequestTimeout(
                f"served {method} did not complete within the "
                f"{self.timeout_s * 1e3:.0f}ms deadline + 30s execution "
                "allowance"
            ) from None

    def predict(self, X):
        return self._call(X, "predict")

    def predict_proba(self, X):
        return self._call(X, "predict_proba")

    def decision_function(self, X):
        return self._call(X, "decision_function")

    def transform(self, X):
        return self._call(X, "transform")

    def score(self, X, y):
        """Served-path score: predictions via the batcher (so padding
        masking is exercised), metric via the package's own
        accuracy/r2 — same dispatch AND same edge-case conventions
        (e.g. constant-target r2 forced to 0.0) as ParallelPostFit."""
        from ..metrics import accuracy_score, r2_score

        pred = self.predict(X)
        y = np.asarray(y)
        if hasattr(self.estimator, "classes_") or hasattr(
                self.estimator, "predict_proba"):
            return float(accuracy_score(y, pred))
        return float(r2_score(y, pred))

    # -- stats -------------------------------------------------------------
    @property
    def queue_rows(self) -> int:
        """Rows currently queued — the fleet's least-loaded routing
        signal (requests vary 1..top-bucket rows, so row depth ranks
        load better than request depth)."""
        return self._queue.rows

    def predict_exec_s(self, method: str, n_rows: int):
        """Predicted execution seconds for an ``n_rows`` batch of
        ``method`` (windowed per-(method, bucket) quantile; None before
        any history) — the fleet admission's per-replica input."""
        try:
            bucket = self.ladder.bucket_for(min(n_rows,
                                                self.ladder.max_rows))
        except ValueError:
            bucket = self.ladder.max_rows
        return self._exec.predict_s(method, bucket)

    def stats(self):
        """Live snapshot: queue depth/rows/peak, batch count, request
        count, and latency quantiles — BOTH lifetime (``latency_s``:
        "how has this server behaved", the histogram keeps the whole
        run) and windowed (``latency_window_s``: quantiles over the
        requests since the PREVIOUS stats() call — the view routing
        and dashboards should ride, since a long fast history dilutes a
        fresh degradation). ``exec_s`` carries the per-(method, bucket)
        execution-time summary feeding deadline release and SLO
        admission."""
        q = self._queue
        cursor = self._stats_cursor
        cur = self._latency.snapshot()
        self._stats_cursor = cur
        out = {
            "queue_depth": q.depth,
            "queue_rows": q.rows,
            "queue_peak_depth": q.peak_depth,
            "batches": self._batches,
            "requests": self._latency.count,
            "warmed": self._warmed,
            "healthy": self.healthy,
            "version": self.model_version,
            "latency_s": self._latency.percentiles((50, 99)),
            "latency_window_s": self._latency.percentiles_between(
                cursor, (50, 99), cur=cur
            ),
            "exec_s": self._exec.snapshot(),
        }
        if self.replica_id is not None:
            out["replica"] = self.replica_id
        return out

    # -- worker ------------------------------------------------------------
    def _run(self):
        import dataclasses

        from .. import config
        from ..observability import watchdog

        # re-apply the creator's (thread-local) config in this thread so
        # spans/counters gate exactly as they did where the server was
        # built; the worker runs under the slow-span watchdog (a no-op
        # unless config.watchdog_timeout_s is set) so a wedged batch
        # execution dumps thread stacks + memory gauges instead of
        # silently freezing the queue
        with config.set(**dataclasses.asdict(self._cfg)):
            with watchdog():
                self._run_loop()

    def _run_loop(self):
        from ..reliability.faults import fault_point

        while True:
            # the replica-worker fault site, BEFORE any request is
            # popped (a crash here kills this worker thread with zero
            # requests in hand — the queued backlog stays recoverable
            # for the fleet supervisor's drain-and-requeue)
            fault_point("replica_worker")
            if not self._paused.is_set():
                if self._stop.is_set():
                    break
                self._parked.set()
                self._paused.wait(0.05)
                continue
            self._parked.clear()
            first = self._queue.pop_first(timeout=0.05)
            if first is None:
                if self._stop.is_set() and self._queue.depth == 0:
                    break
                continue
            self._serve_guarded(first)
        # drain tail: stop() requested with requests still queued
        while True:
            req = self._queue.pop_first(timeout=0.0)
            if req is None:
                break
            self._serve_guarded(req)

    def _serve_guarded(self, first):
        # the worker must be immortal: _execute already fails its own
        # batch on error, this outer guard covers the assembly path so
        # no exception can kill the thread and strand the queue
        try:
            self._serve_one(first)
        except Exception as exc:  # pragma: no cover - defensive
            smetrics.record_drop("error")
            fail_requests([first], ServingError(
                f"serving worker error: {type(exc).__name__}: {exc}"
            ), outcome="error")

    def _serve_one(self, first):
        if first.expired():
            smetrics.record_drop("timeout")
            fail_requests([first], RequestTimeout(
                f"request waited past its {self.timeout_s * 1e3:.0f}ms "
                "deadline"
            ), outcome="timeout")
            return
        batch = [first]
        rows = first.n_rows
        top = self.ladder.max_rows
        # coalescing deadline, measured from the FIRST dequeue (a
        # trickle of stragglers cannot hold a batch forever). With an
        # SLO configured and execution history to predict from, the
        # fixed window is REPLACED by the deadline-aware rule: release
        # when waiting longer would make the oldest request miss its
        # SLO (predicted exec for the CURRENT candidate bucket), and
        # keep coalescing past the fixed window while the budget is
        # ample (_batching.release_deadline)
        dequeue_t = time.perf_counter()
        if first.trace is not None:
            first.trace.stamp("queue_pop", dequeue_t)
        # exec predictions change once per ExecStats WINDOW (seconds),
        # not per coalescing wake (<=10ms) — cache per candidate bucket
        # for this assembly so the loop doesn't pay a locked histogram
        # snapshot + percentile scan on every iteration
        pred_cache = {}
        while rows < top and not self._stop.is_set():
            got = self._queue.drain_method(first.method, top - rows)
            for r in got:
                if r.trace is not None:
                    r.trace.stamp("queue_pop")
                if r.expired():
                    smetrics.record_drop("timeout")
                    fail_requests([r], RequestTimeout(
                        "request waited past its deadline"
                    ), outcome="timeout")
                else:
                    batch.append(r)
                    rows += r.n_rows
            now = time.perf_counter()
            if self._slo_s > 0:
                bucket = self.ladder.bucket_for(rows)
                if bucket not in pred_cache:
                    pred_cache[bucket] = self._exec.predict_s(
                        first.method, bucket
                    )
                predicted = pred_cache[bucket]
            else:
                predicted = None
            deadline = release_deadline(
                first.t_enqueue, dequeue_t, self.batch_window_s,
                self._slo_s, predicted,
            )
            if now >= deadline or rows >= top:
                break
            # sleep on THIS method's lane — depth > 0 from other
            # methods' requests must not turn the window into a spin
            self._queue.wait_method(first.method,
                                    min(deadline - now, 0.01))
        self._execute(batch)

    # pending-fold batching: the sketch fold's ~30 small numpy calls
    # cost ~0.2-1 ms of fixed overhead per invocation — paid per BATCH
    # on the worker thread, that taxes serving throughput by tens of
    # percent. The worker therefore only memcpy's a strided row sample
    # (a few µs) into a pending list and folds it in one amortized
    # chunk every _FOLD_PENDING_ROWS rows / _FOLD_PENDING_S seconds.
    _FOLD_PENDING_ROWS = 1024
    _FOLD_PENDING_S = 0.5
    _FOLD_ROWS_PER_BATCH = 128

    def _fold_quality(self, method, rows_view, out):
        """Serving-side sketch fold + shadow sampling (obs_drift only).
        Pure host numpy on buffers the batch already produced; any
        failure disables quality capture for this server rather than
        ever surfacing into the worker."""
        try:
            from ..observability import drift

            if rows_view.shape[1] > drift._MAX_SKETCH_FEATURES:
                self._drift_on = False   # ultra-wide model: skip capture
                return
            out_rows = None
            try:
                if hasattr(out, "__len__") and len(out) >= len(rows_view):
                    out_rows = np.asarray(out)[: len(rows_view)]
            except Exception:
                out_rows = None
            stride = max(
                -(-len(rows_view) // self._FOLD_ROWS_PER_BATCH), 1
            )
            sample_X = np.array(rows_view[::stride])
            sample_out = np.array(out_rows[::stride]) \
                if out_rows is not None else None
            now = time.monotonic()
            ready = []
            with self._pend_lock:
                pend = self._pend.get(method)
                if pend is not None \
                        and pend["version"] != self.model_version:
                    ready.append(self._pend.pop(method))  # old tail
                    pend = None
                if pend is None:
                    pend = self._pend[method] = {
                        "version": self.model_version, "X": [],
                        "out": [], "rows": 0, "t": now,
                    }
                pend["X"].append(sample_X)
                pend["out"].append(sample_out)
                pend["rows"] += sample_X.shape[0]
                if pend["rows"] >= self._FOLD_PENDING_ROWS \
                        or now - pend["t"] > self._FOLD_PENDING_S:
                    ready.append(self._pend.pop(method))
            for p in ready:
                self._fold_pending(method, p)
            if self._shadow_frac > 0:
                buf = self._shadow.get(method)
                if buf is None:
                    buf = self._shadow[method] = drift.ShadowBuffer()
                buf.offer(rows_view, self._shadow_frac)
        except Exception:  # pragma: no cover - defensive
            self._drift_on = False

    def _flush_quality(self):
        """Fold every method's pending row sample now — the swap path
        (sketches must be current per version before the version flips)
        and ``stop()`` (tests compute scores right after) call this
        from their own threads; the pop is under ``_pend_lock``."""
        with self._pend_lock:
            ready = dict(self._pend)
            self._pend.clear()
        for m, pend in ready.items():
            self._fold_pending(m, pend)

    def _fold_pending(self, method, pend):
        """One amortized sketch fold of a popped pending sample."""
        from ..observability import drift

        if not pend or not pend["rows"]:
            return
        X = np.concatenate(pend["X"], axis=0)
        outs = None
        if pend["out"] and all(o is not None for o in pend["out"]):
            try:
                outs = np.concatenate(
                    [np.atleast_1d(o) for o in pend["out"]], axis=0
                )
            except Exception:
                outs = None
        drift.fold_serving(self.model_name, pend["version"], method, X,
                           outs, max_rows=X.shape[0])

    @staticmethod
    def _tag_fault(batch, exc):
        """Mark every traced request in a failed batch whose failure
        was a chaos-plane injection (``fault_plan`` at the
        serving_execute site) — the tag makes injected faults
        distinguishable from organic batch failures on /traces."""
        from ..reliability.faults import FaultInjected

        if not isinstance(exc, FaultInjected):
            return
        for r in batch:
            if r.trace is not None:
                r.trace.tag(fault_injected=True)

    def _execute(self, batch):
        if batch[0].method.endswith("#sparse"):
            return self._execute_sparse(batch)
        # EVERYTHING from pack to demux sits inside the guard: an
        # exception anywhere (ragged widths slipping past validation,
        # a fallback output that isn't row-sliceable) must fail THIS
        # batch's futures, never kill the worker thread — a dead worker
        # would strand every later request behind a queue nobody drains
        try:
            # the serving-execute fault site sits INSIDE the guard: an
            # injected fault fails THIS batch's futures typed (the
            # worker survives) — the documented batch-failure contract,
            # now deterministically exercisable
            from ..reliability.faults import fault_point

            fault_point("serving_execute")
            method = batch[0].method
            fn = self._fns[method]
            buf, segments, bucket, rows = pack_batch(
                batch, self.ladder, self._staging
            )
            if self._trace_on:
                t_pack = time.perf_counter()
                canary = self.model_version in self._canary_versions
                for r in batch:
                    tr = r.trace
                    if tr is not None:
                        tr.stamp("pack", t_pack)
                        tr.tag(bucket=int(bucket),
                               flavor=self._active_flavor,
                               version=self.model_version)
                        if canary:
                            tr.tag(canary_scored=True)
            smetrics.set_queue_gauges(self._queue.depth, rows,
                                      replica=self.replica_id)
            t_exec = time.perf_counter()
            with smetrics.batch_span(
                method, bucket, rows, len(batch),
                self._queue.depth,
            ):
                out = fn(buf)
            self._batches += 1
            smetrics.record_batch(rows, bucket)
            done = time.perf_counter()
            # the deadline-release / SLO-admission predictor's feed:
            # execution wall of THIS (method, bucket), queue wait
            # excluded
            self._exec.observe(method, bucket, done - t_exec)
            for r in batch:
                lat = done - r.t_enqueue
                self._latency.observe(lat)
                # the /metrics histogram series: per (method, bucket)
                # so a capacity review sees which rung is slow, and the
                # SLO counter when config.serving_slo_ms is set
                smetrics.observe_request_latency(method, bucket, lat)
                tr = r.trace
                if tr is not None:
                    tr.stamp("dispatch", t_exec)
                    tr.stamp("execute_done", done)
                    if self._slo_s > 0 and lat > self._slo_s:
                        tr.tag(slo_violation=True)
            demux_outputs(out, segments)
            if self._drift_on:
                # quality sketches AFTER demux (callers already have
                # their results — the fold never adds request latency):
                # admitted rows + emitted predictions into the
                # per-(model, version, method) serving sketches, plus
                # the shadow reservoir the next hot-swap canary scores.
                # buf/out stay untouched until the next batch packs
                # (single worker thread), so the views are stable here.
                # RATE GATE: sample at most ~20 batches/s into the
                # sketches. A per-batch fold costs far more wall than
                # CPU under concurrent load — every extra preemption
                # point in the worker hands the GIL to a hammering
                # client for a whole switch interval — so the gate must
                # be ONE clock read + compare on the skipped path (a
                # queue-emptiness test flickers with coalescing and
                # makes the overhead nondeterministic)
                now2 = time.monotonic()
                if now2 >= self._next_fold_t:
                    self._next_fold_t = now2 + 0.05
                    self._fold_quality(method, buf[:rows], out)
        except Exception as exc:
            for _ in batch:   # per REQUEST, matching the timeout path
                smetrics.record_drop("error")
            self._tag_fault(batch, exc)
            try:
                # opt-in incident hook: a failed batch is a typed error
                # (one module-global check when the plane is disarmed)
                from ..observability import alerts as _obs_alerts

                _obs_alerts.note_error(exc, "serving_execute")
            except Exception:
                pass
            fail_requests(batch, ServingError(
                f"batch execution failed: {type(exc).__name__}: {exc}"
            ), outcome="error")
        finally:
            # inflight back to 0 on the failure path too — a failed
            # batch must not leave /metrics showing phantom inflight rows
            smetrics.set_queue_gauges(self._queue.depth, 0,
                                      replica=self.replica_id)

    def _execute_sparse(self, batch):
        """The sparse lane's pack → run → demux (ISSUE 13): vstack the
        coalesced CSR requests (O(nnz)), pick the (rows, nnz) grid
        cell, run the sparse entry point, slice per-request rows back
        out. A batch whose nnz overflows the warmed nnz ladder spills
        to the DENSE entry point over a densified batch — the dense
        row rung is already warm, so even the spill mints zero new XLA
        compiles (serving_sparse_spills counts). Same immortal-worker
        guard/metrics contract as the dense _execute."""
        import scipy.sparse as sp_

        try:
            from ..reliability.faults import fault_point

            fault_point("serving_execute")
            lane = batch[0].method
            method = lane[: -len("#sparse")]
            fn = self._sparse_fns[method]
            X = batch[0].X if len(batch) == 1 \
                else sp_.vstack([r.X for r in batch]).tocsr()
            rows = int(X.shape[0])
            bucket = self.ladder.bucket_for(rows)
            if self._trace_on:
                t_pack = time.perf_counter()
                canary = self.model_version in self._canary_versions
                for r in batch:
                    tr = r.trace
                    if tr is not None:
                        tr.stamp("pack", t_pack)
                        tr.tag(bucket=int(bucket),
                               flavor=self._active_flavor,
                               version=self.model_version)
                        if canary:
                            tr.tag(canary_scored=True)
            smetrics.set_queue_gauges(self._queue.depth, rows,
                                      replica=self.replica_id)
            t_exec = time.perf_counter()
            with smetrics.batch_span(lane, bucket, rows, len(batch),
                                     self._queue.depth):
                # the spill decision is an EXPLICIT nnz check, not an
                # exception catch — a real defect raised from the
                # sparse entry point must fail the batch typed, never
                # silently densify every batch forever
                if int(X.nnz) > fn.nnz_ladder.max_rows:
                    # nnz over the ladder top: densify THIS batch into
                    # the warmed dense rung instead of minting a novel
                    # sparse shape
                    from ..observability import record_sparse_spill

                    record_sparse_spill()
                    padded = np.zeros((bucket, X.shape[1]), np.float32)
                    padded[:rows] = X.toarray()
                    out = np.asarray(self._fns[method](padded))[:rows]
                else:
                    out = fn(X, n_rows=bucket)
            self._batches += 1
            smetrics.record_batch(rows, bucket)
            done = time.perf_counter()
            self._exec.observe(lane, bucket, done - t_exec)
            for r in batch:
                lat = done - r.t_enqueue
                self._latency.observe(lat)
                smetrics.observe_request_latency(lane, bucket, lat)
                tr = r.trace
                if tr is not None:
                    tr.stamp("dispatch", t_exec)
                    tr.stamp("execute_done", done)
                    if self._slo_s > 0 and lat > self._slo_s:
                        tr.tag(slo_violation=True)
            out = np.asarray(out)
            lo = 0
            for r in batch:
                f = r.future
                tr = r.trace
                if tr is not None:
                    tr.stamp("demux")
                if f.set_running_or_notify_cancel():
                    f.set_result(out[lo:lo + r.n_rows])
                    if tr is not None:
                        tr.stamp("complete")
                        tr.finish("ok")
                elif tr is not None:
                    tr.finish("cancelled")
                lo += r.n_rows
        except Exception as exc:
            for _ in batch:
                smetrics.record_drop("error")
            self._tag_fault(batch, exc)
            fail_requests(batch, ServingError(
                f"sparse batch execution failed: "
                f"{type(exc).__name__}: {exc}"
            ), outcome="error")
        finally:
            smetrics.set_queue_gauges(self._queue.depth, 0,
                                      replica=self.replica_id)


def _gather_futures(futures):
    """One Future resolving to the row-concatenation of ``futures``'
    results (oversize-request reassembly); the first failure propagates."""
    from concurrent.futures import Future

    out = Future()
    remaining = [len(futures)]
    lock = threading.Lock()

    def _fail(exc):
        try:
            if out.set_running_or_notify_cancel():
                out.set_exception(exc)
        except Exception:
            pass  # already resolved by a racing callback

    def _done(fut):
        # FIRST failure propagates immediately — a doomed oversize
        # request must not keep its caller waiting on the slow chunks
        exc = fut.exception() if not fut.cancelled() else None
        if exc is not None:
            _fail(exc)
            return
        with lock:
            remaining[0] -= 1
            if remaining[0] > 0 or out.done():
                return
        try:
            parts = [f.result() for f in futures]
        except BaseException as exc:  # noqa: BLE001 - forwarded, not hidden
            _fail(exc)
            return
        if out.set_running_or_notify_cancel():
            out.set_result(np.concatenate(parts, axis=0))

    for f in futures:
        f.add_done_callback(_done)
    return out
