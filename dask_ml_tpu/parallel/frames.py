"""Row-partitioned DataFrame substrate — the host-side scale-out frame
story.

Reference: ``dask.dataframe`` (SURVEY.md §1 L2: "dd.DataFrame — the data
type every dask-ml estimator consumes"). The reference's frame layer is a
task graph of pandas partitions with map_partitions + shuffle/reduce; the
TPU-native stack keeps frames HOST-side (TPUs have no string/categorical
kernels — SURVEY.md §7 "Sparse"/dtype notes): a
:class:`PartitionedFrame` is a list of pandas partitions with

- ``map_partitions`` fanned over a thread pool (pandas' C kernels release
  the GIL, so partitions genuinely overlap),
- controller-side reductions for global statistics (category unions,
  lengths) — the same map/reduce shape as dd without a scheduler,
- ``to_sharded``: the bridge that places the numeric columns on the
  device mesh as a ShardedArray, where the estimator stack takes over.

Categorizer/DummyEncoder/OrdinalEncoder consume this type partition-wise
with GLOBAL categories, matching the reference's dd behavior.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pandas as pd

__all__ = ["PartitionedFrame", "from_pandas"]

_MAX_WORKERS = 8


class PartitionedFrame:
    """A logically concatenated DataFrame stored as row partitions."""

    def __init__(self, partitions):
        partitions = list(partitions)
        if not partitions:
            raise ValueError("PartitionedFrame needs >= 1 partition")
        cols = partitions[0].columns
        for p in partitions[1:]:
            if not p.columns.equals(cols):
                raise ValueError("partitions have mismatched columns")
        self.partitions = partitions

    # -- construction ------------------------------------------------------
    @classmethod
    def from_pandas(cls, df: pd.DataFrame, npartitions: int = 8):
        n = len(df)
        npartitions = max(1, min(npartitions, n or 1))
        bounds = np.linspace(0, n, npartitions + 1, dtype=int)
        return cls([
            df.iloc[lo:hi] for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo
        ] or [df])

    # -- metadata ----------------------------------------------------------
    @property
    def npartitions(self):
        return len(self.partitions)

    @property
    def columns(self):
        return self.partitions[0].columns

    @property
    def dtypes(self):
        return self.partitions[0].dtypes

    def __len__(self):
        return sum(len(p) for p in self.partitions)

    def __repr__(self):
        return (f"PartitionedFrame(npartitions={self.npartitions}, "
                f"n_rows={len(self)}, columns={list(self.columns)})")

    # -- partition-parallel ops -------------------------------------------
    def map_partitions(self, fn, *args, **kwargs):
        """Apply ``fn(partition, *args, **kwargs)`` to every partition
        concurrently. DataFrame results re-wrap as a PartitionedFrame;
        anything else returns the list of per-partition results."""
        if len(self.partitions) == 1:
            results = [fn(self.partitions[0], *args, **kwargs)]
        else:
            with ThreadPoolExecutor(
                max_workers=min(_MAX_WORKERS, len(self.partitions))
            ) as pool:
                results = list(pool.map(
                    lambda p: fn(p, *args, **kwargs), self.partitions
                ))
        if all(isinstance(r, pd.DataFrame) for r in results):
            return PartitionedFrame(results)
        return results

    def reduce_partitions(self, map_fn, reduce_fn):
        """map over partitions + controller-side reduce — the dd
        tree-reduce shape for global statistics."""
        return reduce_fn(self.map_partitions(map_fn))

    # -- pandas-surface subset --------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, (list, pd.Index)):
            return PartitionedFrame([p[list(key)] for p in self.partitions])
        return pd.concat([p[key] for p in self.partitions])  # one Series

    def assign(self, **kwargs):
        return self.map_partitions(lambda p: p.assign(**kwargs))

    def compute(self) -> pd.DataFrame:
        """Materialize the single concatenated pandas DataFrame."""
        return pd.concat(self.partitions, axis=0)

    # -- global categorical support ---------------------------------------
    def global_categories(self, columns):
        """Per-column category union across ALL partitions (the
        reference's distributed ``.cat`` known-categories build)."""
        def part_cats(p):
            return {c: pd.unique(p[c].dropna()) for c in columns}

        parts = self.map_partitions(part_cats)
        out = {}
        for c in columns:
            vals = pd.unique(np.concatenate([
                np.asarray(d[c], dtype=object) for d in parts
            ])) if parts else []
            out[c] = pd.CategoricalDtype(vals)
        return out

    # -- device bridge -----------------------------------------------------
    def to_sharded(self, mesh=None, dtype=np.float32, columns=None,
                   shard_features=False):
        """Place the (numeric) columns onto the device mesh as a
        ShardedArray — the frame→array handoff where TPU compute begins.
        Categorical columns must be encoded first (OrdinalEncoder /
        DummyEncoder).

        With a mesh spanning MULTIPLE PROCESSES (``mesh=global_mesh()``),
        each process contributes ITS local partitions: the global row
        order is process order, column sets must agree, and only
        shard-boundary rows travel cross-host
        (``distributed.array_from_process_local``) — the multi-host
        ingest story for frames (reference: dd partition locality,
        SURVEY.md §1 L2)."""
        import jax

        from .sharded import ShardedArray

        # pandas-aware dtype checks: np.issubdtype raises TypeError on
        # extension dtypes (Categorical, StringDtype, nullable Int64)
        cols = list(columns) if columns is not None else [
            c for c in self.columns
            if pd.api.types.is_numeric_dtype(self.dtypes[c])
            or pd.api.types.is_bool_dtype(self.dtypes[c])
        ]
        from .mesh import resolve_mesh

        mesh = resolve_mesh(mesh)  # ambient/default meshes can ALSO span
        # processes — detection must see the resolved mesh, or a
        # multi-process to_sharded() with no mesh arg would take the
        # SPMD path with per-process-different arrays. Virtual ranks
        # (distributed.run_virtual_processes) share one real process
        # whose devices all report process 0, so THEY need the explicit
        # virtual-world probe; a real multi-process session keeps the
        # device-attribute check — its process_count() is >1 for every
        # call, including to_sharded onto a purely process-LOCAL mesh,
        # which must stay on the local path (no peer reaches the
        # collective).
        from . import distributed as dist

        cross_process = dist.in_virtual_world() or any(
            d.process_index != jax.process_index()
            for d in mesh.devices.flat
        )
        if cross_process:
            from .distributed import allgather_object, \
                array_from_process_local

            # gather BEFORE any raise (including the empty-column one):
            # a process erroring out pre-collective would leave its
            # peers blocked in the allgather forever — every process
            # must reach the collective, then raise together
            col_sets = allgather_object(list(map(str, cols)))
            if any(cs != col_sets[0] for cs in col_sets):
                raise ValueError(
                    "cross-process to_sharded requires identical numeric "
                    f"column sets on every process; got {col_sets}"
                )
            if not cols:
                raise ValueError("no numeric columns to place on device")
            host = np.concatenate([
                p[cols].to_numpy(dtype=dtype) for p in self.partitions
            ], axis=0)
            return array_from_process_local(host, mesh=mesh, dtype=dtype)
        if not cols:
            raise ValueError("no numeric columns to place on device")
        host = np.concatenate([
            p[cols].to_numpy(dtype=dtype) for p in self.partitions
        ], axis=0)
        # shard_features rides the logical-axis rules (mesh.py): on a
        # 2-D ("data", "model") mesh the columns tile over "model"
        return ShardedArray.from_array(host, mesh=mesh, dtype=dtype,
                                       shard_features=shard_features)


def from_pandas(df: pd.DataFrame, npartitions: int = 8) -> PartitionedFrame:
    return PartitionedFrame.from_pandas(df, npartitions)
