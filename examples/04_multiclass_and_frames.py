"""Multiclass one-vs-rest GLM + the DataFrame ingestion path.

- A pandas frame with mixed dtypes is categorized (GLOBAL category
  union across partitions), dummy-encoded, and placed on the mesh.
- LogisticRegression fits >2 classes as ONE program: the C per-class
  solves run vmapped (XLA) or — on TPU — through the multi-target
  fused Pallas kernel that reads X once per iteration for ALL classes.
- The same estimator fits out-of-core from an np.memmap: the streamed
  one-vs-rest objective shares one data pass per epoch across classes.

Under jax.distributed, each host can build its own PartitionedFrame
from local files and `to_sharded(mesh=global_mesh())` assembles the
global design matrix with only shard-boundary rows crossing hosts.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np
import pandas as pd

from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.parallel import from_pandas
from dask_ml_tpu.preprocessing import Categorizer, DummyEncoder

rng = np.random.RandomState(0)
n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 60_000))
df = pd.DataFrame({
    "x0": rng.randn(n).astype(np.float32),
    "x1": rng.randn(n).astype(np.float32),
    "plan": rng.choice(["free", "pro", "enterprise"], size=n),
})
label = (df["x0"] + (df["plan"] == "pro") - (df["plan"] == "free")
         + 0.3 * rng.randn(n))
y = np.digitize(label, [-0.6, 0.6]).astype(np.float32)  # 3 classes

# frame → categorical dtypes → dense dummies → device
pf = from_pandas(df, npartitions=16)
pf = Categorizer().fit(pf).transform(pf)
X = DummyEncoder().fit(pf).transform(pf).to_sharded()

clf = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
print("classes:", clf.classes_, "coef:", clf.coef_.shape)
print("train accuracy:", round(clf.score(X, y), 4))
proba = clf.predict_proba(X.to_numpy()[:4])
print("proba rows sum to", proba.sum(axis=1))

# the SAME estimator out-of-core: memmap in, streamed OvR fit
mm_path = os.path.join(tempfile.mkdtemp(), "example_X.f32")
Xh = X.to_numpy().astype(np.float32)
Xh.tofile(mm_path)
Xm = np.memmap(mm_path, dtype=np.float32, mode="r", shape=Xh.shape)
streamed = LogisticRegression(solver="lbfgs", max_iter=100).fit(Xm, y)
print("streamed:", streamed.solver_info_.get("streamed"),
      "classes in one pass:", streamed.solver_info_.get("n_classes"))
print("agreement with in-core fit:",
      round(float(np.mean(streamed.predict(Xh) == clf.predict(X))), 4))
