"""Substrate tests: padding, masks, masked reductions (SURVEY.md §7 B0)."""

import jax
import numpy as np
import pytest

from dask_ml_tpu.ops import reductions
from dask_ml_tpu.parallel import ShardedArray, default_mesh


@pytest.mark.parametrize("n", [7, 8, 16, 33, 100])
def test_roundtrip_and_padding(n):
    mesh = default_mesh()
    x = np.random.RandomState(0).randn(n, 3)
    sx = ShardedArray.from_array(x, mesh)
    assert sx.shape == (n, 3)
    assert sx.padded_shape[0] % mesh.devices.size == 0
    np.testing.assert_allclose(sx.to_numpy(), x.astype(np.float64), rtol=1e-6)


def test_row_mask():
    mesh = default_mesh()
    sx = ShardedArray.from_array(np.ones((10, 2)), mesh)
    m = np.asarray(sx.row_mask())
    assert m.sum() == 10
    assert m[:10].all()


def test_masked_reductions_match_numpy():
    mesh = default_mesh()
    rng = np.random.RandomState(1)
    x = rng.randn(37, 5).astype(np.float32)
    sx = ShardedArray.from_array(x, mesh)
    mask = sx.row_mask()
    np.testing.assert_allclose(
        np.asarray(reductions.masked_sum(sx.data, mask)), x.sum(0), rtol=1e-5
    )
    mean, var = reductions.masked_mean_var(sx.data, mask, sx.n_rows)
    np.testing.assert_allclose(np.asarray(mean), x.mean(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), x.var(0), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(reductions.masked_min(sx.data, mask)), x.min(0), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(reductions.masked_max(sx.data, mask)), x.max(0), rtol=1e-6
    )


def test_sharding_is_row_wise():
    mesh = default_mesh()
    sx = ShardedArray.from_array(np.zeros((64, 4)), mesh)
    n_dev = mesh.devices.size
    assert len(sx.data.sharding.device_set) == n_dev
    shard_shapes = {s.data.shape for s in sx.data.addressable_shards}
    assert shard_shapes == {(64 // n_dev, 4)}


def test_metrics_sample_weight_with_padding():
    # regression test: sample_weight must be padded like the data
    from dask_ml_tpu import metrics

    mesh = default_mesh()
    y = np.r_[np.zeros(50), np.ones(51)]  # 101 rows → padded on 8 devices
    p = y.copy(); p[:10] = 1.0
    sy = ShardedArray.from_array(y, mesh)
    w = np.linspace(0.5, 1.5, 101)
    got = metrics.accuracy_score(sy, p, sample_weight=w)
    from sklearn.metrics import accuracy_score as sk_acc
    assert got == pytest.approx(sk_acc(y, p, sample_weight=w), abs=1e-6)
    got_r2 = metrics.r2_score(sy, p, sample_weight=w)
    from sklearn.metrics import r2_score as sk_r2
    assert got_r2 == pytest.approx(sk_r2(y, p, sample_weight=w), abs=1e-5)


def test_reshard_between_meshes():
    """reshard = rechunk-parity repartition (SURVEY.md §5): values survive
    a move to a smaller mesh and back, across padding granularities."""
    import jax

    from dask_ml_tpu.parallel import as_sharded, device_mesh, reshard

    rng = np.random.RandomState(0)
    x = rng.randn(1003, 5).astype(np.float32)  # odd rows: padding differs
    devs = jax.devices()
    full = device_mesh(devices=devs)
    small = device_mesh(devices=devs[:4])
    a = as_sharded(x, mesh=full)
    b = reshard(a, small)
    assert b.mesh.shape["data"] == 4
    assert b.n_rows == 1003
    assert b.padded_shape[0] % 4 == 0
    np.testing.assert_array_equal(b.to_numpy(), x)
    c = reshard(b, full)
    assert c.mesh.shape["data"] == len(devs)
    np.testing.assert_array_equal(c.to_numpy(), x)
    # same-mesh reshard is a no-op (returns the same object)
    assert reshard(c, full) is c
    # padded region of the resharded array stays zero (mask invariant)
    pad = np.asarray(b.data)[b.n_rows:]
    assert (pad == 0).all()


def test_reshard_1d_array():
    import jax

    from dask_ml_tpu.parallel import as_sharded, device_mesh, reshard

    y = np.arange(37, dtype=np.float32)
    small = device_mesh(devices=jax.devices()[:2])
    b = reshard(as_sharded(y), small)
    np.testing.assert_array_equal(b.to_numpy(), y)


def test_device_mesh_cpu_enumeration_order():
    """Topology-aware reordering is TPU-only: CPU meshes keep plain
    enumeration order (tests depend on deterministic shard placement)."""
    import jax

    if jax.default_backend() != "cpu":
        import pytest

        pytest.skip("enumeration-order assertion is CPU-specific")

    from dask_ml_tpu.parallel.mesh import device_mesh

    mesh = device_mesh()
    assert [d.id for d in mesh.devices.flat] == \
        [d.id for d in jax.devices()]
    # explicit device lists are never reordered, any platform
    sub = jax.devices()[:2]
    mesh2 = device_mesh(devices=sub)
    assert list(mesh2.devices.flat) == list(sub)
