"""Deterministic, seeded fault injection (the chaos plane's input side).

Reference: **none** — dask-ml inherits dask.distributed's organic chaos
(workers really die); a single-process TPU runtime has no such ambient
failure source, so failures must be INJECTED to be testable. The design
constraint (mirroring the ``obs_*`` contract): off by default, zero
overhead when off — ``config.fault_plan`` unset costs every site one
config read + branch, and nothing here is ever traced into a jaxpr
(every site is host-side), so streamed-program jaxprs stay
byte-identical with the plane present.

A :class:`FaultPlan` arms named host-side SITES; each arm fires by the
site's **invocation index** (never wall clock), so a chaos run replays
exactly: the same code on the same data hits the same faults.

Plan grammar (``;``-separated arms)::

    site:kind@N          fire at the site's N-th invocation (0-based)
    site:kind@N*M        ... and the M-1 invocations after it
    site:kind@N+K        ... and every K-th invocation after it
    site:kind~P@S        fire with probability P, decided by
                         hash(seed S, site, index) — deterministic
                         replay, Poisson-like arrival
    site:kind@N/T        hang kinds sleep T seconds (default 60)

Sites (all host-side):

======================  =====================================================
``staging_read``        one host block read (reader or positional slice)
``stream_put``          ``BlockStream._put`` (per-block device staging)
``stream_put_sharded``  ``BlockStream._put_sharded`` (per-shard slab put)
``superblock_dispatch`` the consumer-facing super-block yield boundary
``serving_execute``     ``ModelServer._execute`` (inside the batch guard)
``replica_worker``      the serving worker loop (a crash kills the thread)
``pass_barrier``        ``distributed.sync_stream_pass`` body
======================  =====================================================

Kinds: ``io`` (raises :class:`InjectedIOError` — retryable, an
``OSError``), ``crash`` (raises :class:`InjectedCrash` — not
retryable), ``nan`` (returns a poisoned COPY of the payload — the
source array is never touched), ``hang`` (sleeps; pairs with the pass-
barrier deadline / watchdog).
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

__all__ = [
    "FAULT_KINDS",
    "FAULT_SITES",
    "FaultInjected",
    "FaultPlan",
    "InjectedCrash",
    "InjectedIOError",
    "NonFiniteBlock",
    "StreamIORetriesExhausted",
    "active_plan",
    "fault_point",
    "fire_plan",
    "reset_plans",
]

FAULT_SITES = frozenset({
    "staging_read", "stream_put", "stream_put_sharded",
    "superblock_dispatch", "serving_execute", "replica_worker",
    "pass_barrier",
})
FAULT_KINDS = frozenset({"io", "crash", "nan", "hang"})


class FaultInjected(RuntimeError):
    """Base class for deliberately injected faults — chaos tests catch
    this to distinguish the injection from a real failure."""


class InjectedIOError(FaultInjected, OSError):
    """An injected transient IO failure: an ``OSError``, so the staging
    retry machinery treats it exactly like a real disk/reader hiccup."""


class InjectedCrash(FaultInjected):
    """An injected hard failure — NOT an OSError, so retry loops let it
    propagate (it models a process/thread death, not a flaky read)."""


class NonFiniteBlock(RuntimeError):
    """A streamed host block contained non-finite values and
    ``config.stream_nonfinite`` is ``"raise"``. Typed so out-of-core
    pipelines can quarantine-and-requeue at their own layer."""


class StreamIORetriesExhausted(OSError):
    """A staging read kept failing past ``config.stream_io_retries``
    bounded exponential-backoff attempts. Subclasses ``OSError`` so
    callers catching IO failures today still catch the typed form."""


class _Arm:
    __slots__ = ("site", "kind", "at", "times", "every", "rate", "seed",
                 "hang_s")

    def __init__(self, site, kind, at=0, times=1, every=0, rate=None,
                 seed=0, hang_s=60.0):
        self.site = site
        self.kind = kind
        self.at = int(at)
        self.times = int(times)
        self.every = int(every)
        self.rate = rate
        self.seed = int(seed)
        self.hang_s = float(hang_s)

    def fires(self, idx: int) -> bool:
        if self.rate is not None:
            # keyed hash of (seed, site, index): replays exactly for the
            # same invocation sequence, no RNG state to carry
            h = hashlib.sha1(
                f"{self.seed}|{self.site}|{idx}".encode()
            ).digest()
            return int.from_bytes(h[:8], "big") / 2.0 ** 64 < self.rate
        if idx < self.at:
            return False
        d = idx - self.at
        if self.every > 0:
            return d % self.every == 0 and d // self.every < self.times
        return d < self.times


def _parse_arm(text: str) -> _Arm:
    raw = text.strip()
    if ":" not in raw:
        raise ValueError(
            f"fault_plan arm {raw!r} needs 'site:kind[@N|~P@S]'"
        )
    site, rest = raw.split(":", 1)
    site = site.strip()
    if site not in FAULT_SITES:
        raise ValueError(
            f"fault_plan site {site!r} is unknown; sites: "
            f"{sorted(FAULT_SITES)}"
        )
    hang_s = 60.0
    if "/" in rest:
        rest, hs = rest.rsplit("/", 1)
        hang_s = float(hs)
    kw = {}
    if "~" in rest:
        kind, sched = rest.split("~", 1)
        if "@" in sched:
            p, seed = sched.split("@", 1)
            kw["seed"] = int(seed.lstrip("seed"))
        else:
            p = sched
        kw["rate"] = float(p)
        if not 0.0 < kw["rate"] <= 1.0:
            raise ValueError(
                f"fault_plan rate must be in (0, 1], got {kw['rate']}"
            )
    elif "@" in rest:
        kind, sched = rest.split("@", 1)
        if "*" in sched:
            at, times = sched.split("*", 1)
            kw["at"], kw["times"] = int(at), int(times)
        elif "+" in sched:
            at, every = sched.split("+", 1)
            kw["at"], kw["every"] = int(at), int(every)
            kw["times"] = 1 << 30
        else:
            kw["at"] = int(sched)
    else:
        kind = rest
    kind = kind.strip()
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"fault_plan kind {kind!r} is unknown; kinds: "
            f"{sorted(FAULT_KINDS)}"
        )
    return _Arm(site, kind, hang_s=hang_s, **kw)


class FaultPlan:
    """Parsed ``config.fault_plan``: per-site invocation counters plus
    the arms that decide which invocations fire. Counters are process-
    global per plan instance (one instance per distinct spec string —
    see :func:`active_plan`) so a fit's sites count monotonically across
    threads; the lock makes ``fire`` safe from staging/serving workers."""

    def __init__(self, arms):
        self.arms = tuple(arms)
        self._counts: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan | None":
        spec = (spec or "").strip()
        if not spec:
            return None
        return cls([_parse_arm(a) for a in spec.split(";") if a.strip()])

    def fire(self, site: str):
        """Advance ``site``'s invocation counter; return the firing
        ``(kind, arm)`` or None. At most one arm fires per invocation
        (first match in spec order)."""
        with self._lock:
            idx = self._counts.get(site, 0)
            self._counts[site] = idx + 1
            for arm in self.arms:
                if arm.site == site and arm.fires(idx):
                    self._fired[site] = self._fired.get(site, 0) + 1
                    return arm
        return None

    def snapshot(self) -> dict:
        """Per-site invocation/fired counts — the /status reliability
        block's view of where the plan stands."""
        with self._lock:
            return {
                s: {"invocations": n, "fired": self._fired.get(s, 0)}
                for s, n in sorted(self._counts.items())
            }


# one plan INSTANCE per distinct spec string: counters must persist
# across call sites and threads for index-based schedules to mean
# anything. reset_plans() gives tests a clean slate.
_plans: dict[str, FaultPlan] = {}
_plans_lock = threading.Lock()


def active_plan() -> FaultPlan | None:
    """The FaultPlan for the current config's ``fault_plan`` spec (None
    when unset — the zero-overhead path is one config read + branch)."""
    from ..config import get_config

    spec = get_config().fault_plan
    if not spec:
        return None
    plan = _plans.get(spec)
    if plan is None:
        with _plans_lock:
            plan = _plans.get(spec)
            if plan is None:
                plan = _plans[spec] = FaultPlan.parse(spec)
    return plan


def reset_plans() -> None:
    """Forget every armed plan's counters (test isolation: the same
    spec string in a second test must start its schedule at index 0)."""
    with _plans_lock:
        _plans.clear()


def fault_point(site: str, payload=None):
    """One named host-side fault site. Returns ``payload`` (possibly a
    poisoned COPY under a ``nan`` arm) or raises the armed fault. With
    ``config.fault_plan`` unset this is one config read + branch —
    nothing allocates, nothing is traced."""
    from ..config import get_config

    return fire_plan(get_config().fault_plan, site, payload)


def fire_plan(spec: str, site: str, payload=None):
    """:func:`fault_point` against an EXPLICIT plan spec — for call
    sites running on worker threads (super-block staging) where the
    thread-local config does not carry the creator's ``config.set``
    overrides; the creator captures its spec once and threads it
    through, the way ``BlockStream`` captures ``stream_zero_copy``."""
    if not spec:
        return payload
    plan = _plans.get(spec)
    if plan is None:
        with _plans_lock:
            plan = _plans.get(spec)
            if plan is None:
                plan = _plans[spec] = FaultPlan.parse(spec)
    arm = plan.fire(site)
    if arm is None:
        return payload
    from ..observability._counters import record_fault_injected

    record_fault_injected(site, arm.kind)
    if arm.kind == "io":
        raise InjectedIOError(
            f"fault_plan: injected IO fault at site {site!r}"
        )
    if arm.kind == "crash":
        raise InjectedCrash(
            f"fault_plan: injected crash at site {site!r}"
        )
    if arm.kind == "hang":
        time.sleep(arm.hang_s)
        return payload
    # "nan": poison a COPY — the payload may be a view of user data /
    # a zero-copy staging alias, which must never be mutated in place
    if payload is not None:
        try:
            poisoned = np.array(payload, copy=True)
            flat = poisoned.reshape(-1)
            flat[: max(1, flat.size // 64)] = np.nan
            return poisoned
        except Exception:
            return payload
    return payload
