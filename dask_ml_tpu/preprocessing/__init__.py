"""Ref: dask_ml/preprocessing/__init__.py."""
from .data import (MinMaxScaler, PolynomialFeatures, QuantileTransformer,
                   RobustScaler, StandardScaler)
