"""Package-wide sklearn-contract sweep (ref: SURVEY.md §4 "sklearn API
fidelity ... MUST, for clone/search compat" and the reference's reliance
on sklearn's estimator checks across its suite).

Every public estimator must satisfy: get_params/set_params round-trip,
clone() yields an unfitted copy, fit returns self, fitted attributes are
underscore-suffixed, and predict/transform before fit raises. This is the
contract GridSearchCV/Incremental/Hyperband rely on to clone and re-fit
candidates, so a violation here breaks every meta-estimator above it.
"""

import numpy as np
import pytest
from sklearn.exceptions import NotFittedError

from dask_ml_tpu.base import clone

rng = np.random.RandomState(0)
Xc = rng.randn(64, 5).astype(np.float32)
yc = (Xc[:, 0] + 0.3 * rng.randn(64) > 0).astype(np.float32)
yr = (Xc @ rng.randn(5) + 0.1 * rng.randn(64)).astype(np.float32)


def _cases():
    from dask_ml_tpu.cluster import KMeans, SpectralClustering
    from dask_ml_tpu.decomposition import PCA, IncrementalPCA, TruncatedSVD
    from dask_ml_tpu.ensemble import (
        BlockwiseVotingClassifier, BlockwiseVotingRegressor,
    )
    from dask_ml_tpu.impute import SimpleImputer
    from dask_ml_tpu.linear_model import (
        LinearRegression, LogisticRegression, PoissonRegression,
        SGDClassifier, SGDRegressor,
    )
    from dask_ml_tpu.naive_bayes import GaussianNB
    from dask_ml_tpu.preprocessing import (
        MinMaxScaler, PolynomialFeatures, QuantileTransformer, RobustScaler,
        StandardScaler,
    )
    from dask_ml_tpu.wrappers import Incremental, ParallelPostFit

    # (estimator, y-or-None, fitted attribute, prediction method)
    return [
        (LogisticRegression(solver="lbfgs", max_iter=20), yc,
         "coef_", "predict"),
        (LinearRegression(solver="lbfgs", max_iter=20), yr,
         "coef_", "predict"),
        (PoissonRegression(solver="lbfgs", max_iter=20),
         np.abs(yr).astype(np.float32), "coef_", "predict"),
        (SGDClassifier(max_iter=3), yc, "coef_", "predict"),
        (SGDRegressor(max_iter=3), yr, "coef_", "predict"),
        (GaussianNB(), yc, "theta_", "predict"),
        (KMeans(n_clusters=3, max_iter=5, random_state=0), None,
         "cluster_centers_", "predict"),
        (SpectralClustering(n_clusters=2, n_components=16, random_state=0),
         None, "labels_", None),
        (PCA(n_components=2, random_state=0), None,
         "components_", "transform"),
        (TruncatedSVD(n_components=2, random_state=0), None,
         "components_", "transform"),
        (IncrementalPCA(n_components=2), None, "components_", "transform"),
        (StandardScaler(), None, "mean_", "transform"),
        (MinMaxScaler(), None, "scale_", "transform"),
        (RobustScaler(), None, "center_", "transform"),
        (QuantileTransformer(n_quantiles=16), None,
         "quantiles_", "transform"),
        (PolynomialFeatures(degree=2), None,
         "n_output_features_", "transform"),
        (SimpleImputer(), None, "statistics_", "transform"),
        (BlockwiseVotingClassifier(
            LogisticRegression(solver="lbfgs", max_iter=10),
            classes=[0, 1]), yc, "estimators_", "predict"),
        (BlockwiseVotingRegressor(
            LinearRegression(solver="lbfgs", max_iter=10)), yr,
         "estimators_", "predict"),
        (ParallelPostFit(LogisticRegression(solver="lbfgs", max_iter=10)),
         yc, "estimator_", "predict"),
        (Incremental(SGDClassifier(max_iter=2)), yc,
         "estimator_", "predict"),
    ]


CASES = _cases()
IDS = [type(c[0]).__name__ for c in CASES]


@pytest.mark.parametrize("est,y,attr,pred", CASES, ids=IDS)
def test_sklearn_contract(est, y, attr, pred):
    # params round-trip through get/set (what clone/search depend on)
    params = est.get_params(deep=False)
    est.set_params(**params)
    assert est.get_params(deep=False).keys() == params.keys()

    # clone yields an UNfitted copy with identical params
    c = clone(est)
    assert type(c) is type(est)
    assert not hasattr(c, attr)

    # pre-fit prediction raises (NotFittedError or the package's
    # check_is_fitted ValueError — both sklearn-compatible)
    if pred is not None:
        with pytest.raises((NotFittedError, ValueError, AttributeError)):
            getattr(c, pred)(Xc)

    # fit returns self, sets the advertised fitted attribute
    fitted = c.fit(Xc) if y is None else c.fit(Xc, y)
    assert fitted is c
    assert hasattr(c, attr)

    # prediction produces one row per input sample
    if pred is not None:
        out = getattr(c, pred)(Xc)
        out = np.asarray(out.to_numpy() if hasattr(out, "to_numpy") else out)
        assert out.shape[0] == Xc.shape[0]

    # cloning a FITTED estimator still yields an unfitted one
    c2 = clone(c)
    assert not hasattr(c2, attr)


@pytest.mark.parametrize("est,y,attr,pred", CASES, ids=IDS)
def test_params_survive_double_clone(est, y, attr, pred):
    a = clone(est)
    b = clone(a)
    assert repr(a.get_params()) == repr(b.get_params())
