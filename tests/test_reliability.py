"""Chaos plane (ISSUE 11): deterministic fault injection, the hardening
each injected fault exercises, pass-granular streamed-fit resume, and
replica supervision.

Contracts under test, per the tentpole:

- fault plans parse strictly, fire by invocation INDEX (replayable),
  and cost one config read when unset — the streamed scan jaxpr is
  byte-identical with the whole plane armed (every site is host-side);
- transient staging IO faults are absorbed by bounded-backoff retry
  (``stream_io_retries``) with the fit's result bit-identical to a
  fault-free run; exhaustion raises typed;
- the non-finite block policy raises typed or quarantines via the
  existing masked prefix-count (counts folded to 0 — no recompile);
- streamed SGD/GLM fits killed after pass p and resumed match an
  uninterrupted fit to 1e-6 (shuffled lr-clock identity and the
  sharded dp>1 flavor included); a wrong-fingerprint checkpoint is
  ignored; completion clears the slot;
- ``utils.checkpoint`` writes are atomic: a kill mid-save leaves the
  previous checkpoint restorable;
- a dead fleet replica is rebuilt off the serving path (warmed before
  rejoining), its queued requests drained onto the replacement, under
  a bounded restart budget; its stale gauge series are dropped.
"""

import os
import tempfile
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import config
from dask_ml_tpu.observability import counters_reset, counters_snapshot
from dask_ml_tpu.reliability import (
    FaultInjected,
    FaultPlan,
    InjectedCrash,
    InjectedIOError,
    NonFiniteBlock,
    StreamIORetriesExhausted,
    fault_point,
    reset_plans,
)


@pytest.fixture(autouse=True)
def _clean_plane():
    reset_plans()
    counters_reset()
    yield
    reset_plans()
    counters_reset()


def _xy(n=2000, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------

class TestFaultPlan:
    def test_index_schedules_fire_deterministically(self):
        p = FaultPlan.parse("staging_read:io@2;serving_execute:crash@1*2")
        kinds = [(a.kind if a else None)
                 for a in (p.fire("staging_read") for _ in range(5))]
        assert kinds == [None, None, "io", None, None]
        kinds = [(a.kind if a else None)
                 for a in (p.fire("serving_execute") for _ in range(4))]
        assert kinds == [None, "crash", "crash", None]

    def test_every_k_schedule(self):
        p = FaultPlan.parse("staging_read:io@1+3")
        fired = [i for i in range(10)
                 if p.fire("staging_read") is not None]
        assert fired == [1, 4, 7]

    def test_probabilistic_schedule_replays_exactly(self):
        seq1 = [FaultPlan.parse("staging_read:io~0.5@seed7")
                .fire("staging_read") is not None for _ in range(64)]
        p2 = FaultPlan.parse("staging_read:io~0.5@seed7")
        seq2 = []
        for _ in range(64):
            seq2.append(p2.fire("staging_read") is not None)
        # fresh plan, same seed, same invocation sequence -> same fires
        p3 = FaultPlan.parse("staging_read:io~0.5@seed7")
        assert seq2 == [p3.fire("staging_read") is not None
                        for _ in range(64)]
        assert any(seq2) and not all(seq2)

    def test_unknown_site_and_kind_raise_listing(self):
        with pytest.raises(ValueError, match="staging_read"):
            FaultPlan.parse("bogus_site:io@0")
        with pytest.raises(ValueError, match="crash"):
            FaultPlan.parse("staging_read:meteor@0")
        with pytest.raises(ValueError, match="site:kind"):
            FaultPlan.parse("just-nonsense")

    def test_snapshot_counts_invocations_and_fires(self):
        p = FaultPlan.parse("staging_read:io@1")
        for _ in range(3):
            p.fire("staging_read")
        snap = p.snapshot()
        assert snap["staging_read"] == {"invocations": 3, "fired": 1}

    def test_fault_point_default_is_identity(self):
        # zero-overhead contract: unset plan returns the payload as-is
        sentinel = object()
        assert fault_point("staging_read", sentinel) is sentinel

    def test_typed_errors(self):
        assert issubclass(InjectedIOError, OSError)
        assert issubclass(InjectedIOError, FaultInjected)
        assert not issubclass(InjectedCrash, OSError)
        with config.set(fault_plan="serving_execute:crash@0"):
            with pytest.raises(InjectedCrash):
                fault_point("serving_execute")

    def test_nan_kind_poisons_a_copy_never_the_source(self):
        src = np.ones((8, 3), np.float32)
        with config.set(fault_plan="staging_read:nan@0"):
            out = fault_point("staging_read", src)
        assert np.isnan(out).any()
        assert np.isfinite(src).all()          # source untouched
        assert out is not src


# ---------------------------------------------------------------------------
# staging retry + non-finite policy
# ---------------------------------------------------------------------------

class TestStagingHardening:
    def test_io_fault_retried_to_bitwise_parity(self):
        X, y = _xy()
        from dask_ml_tpu.models.sgd import SGDClassifier

        with config.set(stream_block_rows=256):
            clean = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
        reset_plans()
        with config.set(stream_block_rows=256, stream_io_retries=2,
                        fault_plan="staging_read:io@3"):
            faulted = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
        snap = counters_snapshot()
        assert snap.get("stream_retries", 0) >= 1
        assert snap.get("faults_injected", 0) >= 1
        assert snap.get("faults_injected_staging_read", 0) >= 1
        assert np.array_equal(clean.coef_, faulted.coef_)

    def test_retries_exhausted_raises_typed(self):
        X, y = _xy(600)
        from dask_ml_tpu.parallel.streaming import BlockStream

        with config.set(stream_block_rows=128, stream_io_retries=2,
                        fault_plan="staging_read:io@0*64"):
            with pytest.raises(StreamIORetriesExhausted):
                for _ in BlockStream((X, y), block_rows=128):
                    pass

    def test_put_fault_retried(self):
        X, y = _xy(600)
        from dask_ml_tpu.parallel.streaming import BlockStream

        with config.set(stream_block_rows=128, stream_superblock=False,
                        stream_io_retries=2, fault_plan="stream_put:io@1"):
            blocks = list(BlockStream((X, y), block_rows=128))
        assert counters_snapshot().get("stream_retries", 0) >= 1
        assert sum(b.n_rows for b in blocks) == len(X)

    def test_nonfinite_raise_is_typed(self):
        X, y = _xy(1500)
        X[400:410, 1] = np.inf
        from dask_ml_tpu.models.sgd import SGDClassifier

        with config.set(stream_block_rows=256, stream_nonfinite="raise"):
            with pytest.raises(NonFiniteBlock):
                SGDClassifier(max_iter=1, shuffle=False).fit(X, y)

    def test_nonfinite_quarantine_folds_counts_to_zero(self):
        X, y = _xy(1500)
        X[300:310, 2] = np.nan     # inside block 1 at 256-row blocks
        from dask_ml_tpu.parallel.streaming import BlockStream

        with config.set(stream_block_rows=256, stream_mesh=1,
                        stream_nonfinite="quarantine"):
            s = BlockStream((X, y), block_rows=256)
            sbs = list(s.superblocks())
        counts = np.concatenate([np.asarray(sb.counts)[:sb.n_blocks]
                                 for sb in sbs])
        assert counts[1] == 0 and counts[0] == 256
        # quarantined slot's DATA is zeroed too (a masked NaN would
        # still poison sums: NaN * 0 == NaN)
        first = np.asarray(sbs[0].arrays[0])
        blk1 = first[1] if first.ndim == 3 else np.asarray(
            sbs[0].arrays[0][1])
        assert np.all(blk1 == 0)
        assert counters_snapshot().get(
            "stream_quarantined_blocks", 0) >= 1

    def test_nonfinite_quarantine_fit_survives(self):
        X, y = _xy(1500)
        X[300:310, 2] = np.nan
        from dask_ml_tpu.models.sgd import SGDClassifier

        with config.set(stream_block_rows=256,
                        stream_nonfinite="quarantine"):
            clf = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(X, y)
        assert np.isfinite(clf.coef_).all()

    def test_inference_stream_hardens_quarantine_to_raise(self):
        # silently dropping a predict block would misalign output rows
        X, y = _xy(1500)
        from dask_ml_tpu.models.sgd import SGDClassifier

        with config.set(stream_block_rows=256):
            clf = SGDClassifier(max_iter=1, random_state=0).fit(X, y)
        Xbad = X.copy()
        Xbad[700:705, 0] = np.nan
        with config.set(stream_block_rows=256,
                        stream_nonfinite="quarantine"):
            with pytest.raises(NonFiniteBlock):
                clf.predict(Xbad)

    def test_bad_policy_value_raises_listing(self):
        from dask_ml_tpu.parallel.streaming import BlockStream

        X, y = _xy(600)
        with config.set(stream_nonfinite="meteor"):
            with pytest.raises(ValueError, match="quarantine"):
                BlockStream((X, y), block_rows=128)

    def test_jaxpr_byte_identical_with_plane_armed(self):
        """The acceptance-criteria contract: the streamed-SGD superblock
        jaxpr with the chaos plane armed (fault plan + quarantine +
        retries) is byte-identical to the default-config one — every
        site and policy is host-side."""
        from dask_ml_tpu.models.sgd import _sgd_sb_scan
        from dask_ml_tpu.observability._programs import unwrap

        def scan_jaxpr():
            body = unwrap(_sgd_sb_scan)
            K, S, d = 2, 8, 3
            return str(jax.make_jaxpr(
                lambda W, Xs, ys, c, lrs: body(
                    W, Xs, ys, c, lrs, 1e-4, 1.0, 0.0, 1.0, "hinge", None
                )
            )(jnp.zeros(d + 1), jnp.zeros((K, S, d)), jnp.zeros((K, S)),
              jnp.zeros(K, jnp.int32), jnp.zeros(K)))

        baseline = scan_jaxpr()
        with config.set(fault_plan="staging_read:io@0",
                        stream_nonfinite="quarantine",
                        stream_io_retries=7,
                        stream_checkpoint_path="/tmp/never-used"):
            assert scan_jaxpr() == baseline


# ---------------------------------------------------------------------------
# pass-granular checkpoint / resume
# ---------------------------------------------------------------------------

pytest.importorskip("orbax.checkpoint")


class TestStreamResume:
    def _kill_and_resume(self, make, crash_at, tmp, **cfg):
        """Run ``make()`` fits: control (no ckpt), killed (crash arm),
        resumed — returns (control, resumed)."""
        with config.set(**cfg):
            control = make()
        reset_plans()
        with config.set(stream_checkpoint_path=tmp,
                        fault_plan=f"superblock_dispatch:crash@{crash_at}",
                        **cfg):
            with pytest.raises(FaultInjected):
                make()
        reset_plans()
        with config.set(stream_checkpoint_path=tmp, **cfg):
            resumed = make()
        return control, resumed

    def test_sgd_shuffled_resume_parity(self, tmp_path):
        X, y = _xy(3000)
        from dask_ml_tpu.models.sgd import SGDClassifier

        def fit():
            return SGDClassifier(max_iter=4, random_state=0,
                                 shuffle=True).fit(X, y)

        ctl, res = self._kill_and_resume(fit, 4, str(tmp_path),
                                         stream_block_rows=256)
        assert counters_snapshot().get("stream_resumes", 0) == 1
        assert np.allclose(res.coef_, ctl.coef_, atol=1e-6)
        # completion cleared the slot
        assert not os.path.exists(os.path.join(str(tmp_path), "sgd"))

    def test_sgd_sharded_dp2_resume_parity(self, tmp_path):
        X, y = _xy(3000)
        from dask_ml_tpu.models.sgd import SGDClassifier

        def fit():
            return SGDClassifier(max_iter=3, random_state=0,
                                 shuffle=True).fit(X, y)

        ctl, res = self._kill_and_resume(fit, 3, str(tmp_path),
                                         stream_block_rows=256,
                                         stream_mesh=2)
        assert np.allclose(res.coef_, ctl.coef_, atol=1e-6)

    def test_wrong_fingerprint_checkpoint_ignored(self, tmp_path):
        X, y = _xy(3000)
        from dask_ml_tpu.models.sgd import SGDClassifier

        with config.set(stream_block_rows=256,
                        stream_checkpoint_path=str(tmp_path),
                        fault_plan="superblock_dispatch:crash@4"):
            with pytest.raises(FaultInjected):
                SGDClassifier(max_iter=4, random_state=0).fit(X, y)
        assert os.path.exists(os.path.join(str(tmp_path), "sgd"))
        reset_plans()
        counters_reset()
        X2 = X + 1.0   # different data content -> different fingerprint
        with config.set(stream_block_rows=256,
                        stream_checkpoint_path=str(tmp_path)):
            SGDClassifier(max_iter=4, random_state=0).fit(X2, y)
        assert counters_snapshot().get("stream_resumes", 0) == 0

    def test_glm_lbfgs_resume_parity(self, tmp_path):
        X, y = _xy(2000)
        from dask_ml_tpu.linear_model import LogisticRegression

        def fit():
            return LogisticRegression(solver="lbfgs",
                                      max_iter=10).fit(X, y)

        ctl, res = self._kill_and_resume(fit, 10, str(tmp_path),
                                         stream_block_rows=256)
        assert counters_snapshot().get("stream_resumes", 0) == 1
        assert np.allclose(res.coef_, ctl.coef_, atol=1e-6)
        assert not os.path.exists(os.path.join(str(tmp_path), "glm"))

    def test_glm_admm_resume_parity(self, tmp_path):
        X, y = _xy(2000)
        from dask_ml_tpu.linear_model import LogisticRegression

        def fit():
            return LogisticRegression(solver="admm", penalty="l1",
                                      C=1.0, max_iter=8).fit(X, y)

        # one super-block dispatch per admm iteration at this shape:
        # crash@5 kills the fit mid-iteration 6 of 8
        ctl, res = self._kill_and_resume(fit, 5, str(tmp_path),
                                         stream_block_rows=256)
        assert np.allclose(res.coef_, ctl.coef_, atol=1e-6)

    def test_incremental_pass_resume_parity(self, tmp_path):
        X, y = _xy(2000)
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        def make():
            return Incremental(SGDClassifier(random_state=0),
                               shuffle_blocks=True, random_state=0)

        ctl = make()
        for _ in range(5):
            ctl.partial_fit(X, y, classes=[0.0, 1.0])
        with config.set(stream_block_rows=256,
                        stream_checkpoint_path=str(tmp_path)):
            a = make()
            for _ in range(3):
                a.partial_fit(X, y, classes=[0.0, 1.0])
            assert a.completed_passes_ == 3
            # "kill": a fresh wrapper restores the killed run's state
            b = make()
            b.partial_fit(X, y, classes=[0.0, 1.0])
            assert b.completed_passes_ == 4
            assert counters_snapshot().get("stream_resumes", 0) == 1
            b.partial_fit(X, y, classes=[0.0, 1.0])
            b._clear_pass_checkpoint()
        assert np.allclose(b.estimator_.coef_, ctl.estimator_.coef_,
                           atol=1e-6)

    def test_serve_while_training_resume_skips_completed_passes(
            self, tmp_path):
        """A pass driver killed AFTER its final pass (but before the
        completion clear) must resume to ZERO remaining work — not
        train and publish one pass past the target; killed mid-sequence
        it runs exactly the remaining passes."""
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.serving.fleet import serve_while_training
        from dask_ml_tpu.wrappers import Incremental

        X, y = _xy(1500)

        class DummyFleet:
            def __init__(self):
                self.tags = []

            def publish(self, est, tag=None, quantize=None):
                self.tags.append(tag)
                return len(self.tags)

        def make():
            return Incremental(SGDClassifier(random_state=0),
                               shuffle_blocks=True, random_state=0)

        ctl = make()
        for _ in range(3):
            ctl.partial_fit(X, y, classes=[0.0, 1.0])
        with config.set(stream_block_rows=256,
                        stream_checkpoint_path=str(tmp_path)):
            # killed AFTER pass 3 of 3 (no clear ran)
            a = make()
            for _ in range(3):
                a.partial_fit(X, y, classes=[0.0, 1.0])
            b = make()
            fleet = DummyFleet()
            serve_while_training(fleet, b, X, y, passes=3,
                                 classes=[0.0, 1.0])
            assert b.completed_passes_ == 3
            assert fleet.tags == []        # nothing re-trained
            assert np.allclose(b.estimator_.coef_, ctl.estimator_.coef_,
                               atol=1e-6)
            # killed after pass 2 of 3: exactly ONE more pass runs
            c = make()
            for _ in range(2):
                c.partial_fit(X, y, classes=[0.0, 1.0])
            d = make()
            fleet2 = DummyFleet()
            serve_while_training(fleet2, d, X, y, passes=3,
                                 classes=[0.0, 1.0])
            assert fleet2.tags == ["pass3"]
            assert d.completed_passes_ == 3
            assert np.allclose(d.estimator_.coef_, ctl.estimator_.coef_,
                               atol=1e-6)

    def test_multihost_refusal(self):
        from dask_ml_tpu.parallel.distributed import run_virtual_processes
        from dask_ml_tpu.reliability.stream_ckpt import stream_checkpoint

        def body(rank):
            with config.set(stream_checkpoint_path="/tmp/x"):
                return stream_checkpoint("sgd", ("a",))

        assert run_virtual_processes(body, world=2) == [None, None]


# ---------------------------------------------------------------------------
# atomic checkpoint writes
# ---------------------------------------------------------------------------

class TestAtomicCheckpoint:
    def test_kill_mid_save_keeps_previous_state(self):
        from dask_ml_tpu.utils import checkpoint as ckpt

        d = tempfile.mkdtemp()
        p = os.path.join(d, "state")
        ckpt.save_pytree(p, {"x": np.arange(4.0)})

        # a killed save leaves a partial temp sibling; the live slot is
        # untouched (orbax's own force=True used to DELETE it first)
        os.makedirs(p + ".tmp", exist_ok=True)
        with open(os.path.join(p + ".tmp", "junk"), "w") as f:
            f.write("partial garbage")
        st = ckpt.restore_pytree(p)
        assert np.array_equal(np.asarray(st["x"]), np.arange(4.0))
        # the next save bulldozes the junk and publishes atomically
        ckpt.save_pytree(p, {"x": np.arange(5.0)})
        assert np.asarray(ckpt.restore_pytree(p)["x"]).size == 5

    def test_crash_window_between_renames_restores_old(self):
        from dask_ml_tpu.utils import checkpoint as ckpt

        d = tempfile.mkdtemp()
        p = os.path.join(d, "state")
        ckpt.save_pytree(p, {"x": np.arange(3.0)})
        # simulate a kill between "retire old" and "publish new"
        os.rename(p, p + ".old")
        assert ckpt.checkpoint_exists(p)
        st = ckpt.restore_pytree(p)
        assert np.array_equal(np.asarray(st["x"]), np.arange(3.0))

    def test_repeated_crash_keeps_old_until_publish(self, monkeypatch):
        """After crash #1 left the only good state at ``.old``, a kill
        during the NEXT save's publish must still leave it restorable —
        the .old fallback may only be deleted once the new checkpoint
        has published."""
        from dask_ml_tpu.utils import checkpoint as ckpt

        d = tempfile.mkdtemp()
        p = os.path.join(d, "state")
        ckpt.save_pytree(p, {"x": np.arange(2.0)})
        os.rename(p, p + ".old")   # crash #1: retired, never published
        real_rename = os.rename

        def killed_publish(src, dst):
            if dst == p:
                raise RuntimeError("kill mid-publish")
            return real_rename(src, dst)

        monkeypatch.setattr(os, "rename", killed_publish)
        with pytest.raises(RuntimeError, match="kill mid-publish"):
            ckpt.save_pytree(p, {"x": np.arange(9.0)})
        monkeypatch.undo()
        assert ckpt.checkpoint_exists(p)
        st = ckpt.restore_pytree(p)
        assert np.array_equal(np.asarray(st["x"]), np.arange(2.0))

    def test_save_host_atomic(self):
        from dask_ml_tpu.utils import checkpoint as ckpt

        d = tempfile.mkdtemp()
        p = os.path.join(d, "h.pkl")
        ckpt.save_host(p, {"v": 1})

        class Boom:
            def __reduce__(self):
                raise RuntimeError("kill mid-write")

        with pytest.raises(RuntimeError):
            ckpt.save_host(p, Boom())
        assert ckpt.restore_host(p) == {"v": 1}
        assert not any(f.startswith("h.pkl.tmp") for f in os.listdir(d))


# ---------------------------------------------------------------------------
# pass-barrier deadline
# ---------------------------------------------------------------------------

class TestSyncDeadline:
    def test_deadline_raises_typed(self):
        from dask_ml_tpu.parallel.distributed import (
            StreamSyncTimeout, run_with_deadline)

        with pytest.raises(StreamSyncTimeout, match="checkpoint"):
            run_with_deadline(lambda: time.sleep(5.0), 0.15, "t")

    def test_body_error_propagates(self):
        from dask_ml_tpu.parallel.distributed import run_with_deadline

        def boom():
            raise ValueError("collective failed")

        with pytest.raises(ValueError, match="collective failed"):
            run_with_deadline(boom, 5.0, "t")

    def test_single_process_sync_is_noop(self):
        from dask_ml_tpu.parallel.distributed import sync_stream_pass

        assert sync_stream_pass("test", timeout_s=0.1) is False


# ---------------------------------------------------------------------------
# replica supervision
# ---------------------------------------------------------------------------

def _fitted_model():
    X, y = _xy(400)
    from dask_ml_tpu.models.sgd import SGDClassifier

    with config.set(stream_block_rows=0):
        return SGDClassifier(max_iter=2, random_state=0).fit(X, y), X


_SMALL_FLEET = dict(serving_min_batch=8, serving_max_batch=32,
                    serving_supervise=True,
                    serving_supervise_interval_s=0.05)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
class TestReplicaSupervision:
    def test_dead_replica_rebuilt_and_rejoins(self):
        from dask_ml_tpu.serving.fleet import FleetServer

        clf, X = _fitted_model()
        with config.set(fault_plan="replica_worker:crash@25",
                        **_SMALL_FLEET):
            fleet = FleetServer(clf, replicas=2, timeout_ms=10000).warmup()
            with fleet:
                deadline = time.time() + 20
                restarted = False
                while time.time() < deadline:
                    try:
                        fleet.predict(X[:8])
                    except Exception:
                        pass
                    if (counters_snapshot().get(
                            "serving_replica_restarts", 0) >= 1
                            and sum(1 for r in fleet.replicas
                                    if r.healthy) == 2):
                        restarted = True
                        break
                    time.sleep(0.02)
                assert restarted, counters_snapshot()
                # the rebuilt fleet still answers correctly
                out = fleet.predict(X[:16])
                assert len(out) == 16
                assert fleet.stats()["healthy_replicas"] == 2

    def test_restart_budget_degrades_to_permanent_failover(self):
        from dask_ml_tpu.serving.fleet import FleetServer

        clf, X = _fitted_model()
        cfg = dict(_SMALL_FLEET)
        cfg["serving_restart_budget"] = 0
        # rate-less @0 arm: the FIRST worker loop iteration of whichever
        # replica hits the site dies; budget 0 -> permanent failover
        with config.set(fault_plan="replica_worker:crash@0", **cfg):
            fleet = FleetServer(clf, replicas=2, timeout_ms=10000).warmup()
            with fleet:
                deadline = time.time() + 20
                failed = False
                while time.time() < deadline:
                    snap = counters_snapshot()
                    if snap.get("serving_replica_failures", 0) >= 1:
                        failed = True
                        break
                    time.sleep(0.02)
                assert failed, counters_snapshot()
                assert counters_snapshot().get(
                    "serving_replica_restarts", 0) == 0
                # the survivor keeps serving
                out = fleet.predict(X[:8])
                assert len(out) == 8
                assert fleet.stats()["healthy_replicas"] == 1

    def test_dead_replica_gauges_dropped(self):
        from dask_ml_tpu.observability import live
        from dask_ml_tpu.serving import metrics as smetrics

        live.metrics_reset()
        labels = (("replica", "7"),)
        live.gauge_set("serving_replica_version", 3, labels)
        live.gauge_set("serving_replica_healthy", 1, labels)
        live.gauge_set("serving_queue_depth", 2, labels)
        assert any(k[0].startswith("serving_replica")
                   for k in live.gauges_snapshot())
        smetrics.drop_replica_gauges(7)
        snap = live.gauges_snapshot()
        assert not any(("replica", "7") in k[1] for k in snap)
        live.metrics_reset()


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------

class TestReliabilityObservability:
    def test_status_block(self):
        from dask_ml_tpu.observability.live import status_data
        from dask_ml_tpu.reliability import status_block

        with config.set(fault_plan="staging_read:io@0"):
            fault_point("staging_read", None) if False else None
            try:
                fault_point("staging_read")
            except InjectedIOError:
                pass
            block = status_block()
            assert block["fault_plan"] == "staging_read:io@0"
            assert block["sites"]["staging_read"]["fired"] == 1
            assert block["counters"].get("faults_injected") == 1
            assert status_data()["reliability"]["fault_plan"] \
                == "staging_read:io@0"
        # unarmed: the block is quiet, not absent
        assert status_block()["fault_plan"] is None

    def test_report_reliability_table(self):
        from dask_ml_tpu.observability._counters import counter_add
        from dask_ml_tpu.observability.report import (build_report,
                                                      report_data)

        counter_add("stream_retries", 3)
        counter_add("serving_replica_restarts", 1)
        counter_add("faults_injected_staging_read", 2)
        records = [{"counters": True, **counters_snapshot()}]
        data = report_data(records)
        names = {r["counter"] for r in data["reliability"]}
        assert {"stream_retries", "serving_replica_restarts",
                "faults_injected_staging_read"} <= names
        text = build_report(records)
        assert "reliability" in text and "stream_retries" in text

    def test_metrics_page_renders_reliability_counters(self):
        from dask_ml_tpu.observability._counters import counter_add
        from dask_ml_tpu.observability.live import render_prometheus

        counter_add("stream_retries", 2)
        counter_add("serving_replica_restarts", 1)
        page = render_prometheus()
        assert "dask_ml_tpu_stream_retries_total 2" in page
        assert "dask_ml_tpu_serving_replica_restarts_total 1" in page
