"""Streaming, device SGD, checkpoint, config, observability, distributed
single-host tests (SURVEY.md §5 aux subsystems + §7 B0 streaming)."""

import json
import os

import numpy as np
import pytest

from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor
from dask_ml_tpu.parallel import BlockStream, default_mesh
from dask_ml_tpu.parallel import distributed as dist


def test_block_stream_covers_all_rows():
    X = np.arange(100, dtype=np.float32).reshape(50, 2)
    y = np.arange(50, dtype=np.float32)
    stream = BlockStream((X, y), block_rows=16)
    seen = []
    total = 0
    for block in stream:
        Xb, yb = block.arrays
        assert Xb.shape[0] % default_mesh().devices.size == 0
        m = np.asarray(block.mask)
        assert m.sum() == block.n_rows
        seen.append(np.asarray(yb)[: block.n_rows])
        total += block.n_rows
    assert total == 50
    np.testing.assert_array_equal(np.sort(np.concatenate(seen)), y)


def test_block_stream_shuffle_epochs():
    X = np.arange(60, dtype=np.float32).reshape(60, 1)
    stream = BlockStream((X,), block_rows=10, shuffle=True, seed=0)
    e1 = [b.arrays[0][0, 0].item() for b in stream]
    e2 = [b.arrays[0][0, 0].item() for b in stream]
    assert sorted(e1) == sorted(e2)
    assert len(list(stream.epochs(2))) == 2 * len(stream)


def test_block_stream_length_mismatch():
    with pytest.raises(ValueError, match="inconsistent"):
        BlockStream((np.zeros((5, 2)), np.zeros(4)), block_rows=2)


def test_sgd_classifier_learns(xy_classification):
    X, y = xy_classification
    clf = SGDClassifier(eta0=0.5, max_iter=40, random_state=0)
    clf.fit(X, y)
    assert clf.score(X, y) > 0.8
    proba = clf.predict_proba(X)
    np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)
    assert clf.coef_.shape == (1, X.shape[1])


def test_sgd_classifier_partial_fit_contract(xy_classification):
    X, y = xy_classification
    clf = SGDClassifier(eta0=0.5, learning_rate="constant")
    for i in range(0, len(X), 100):
        clf.partial_fit(X[i:i + 100], y[i:i + 100], classes=[0.0, 1.0])
    assert clf.score(X, y) > 0.6
    # composes with the Incremental wrapper (device path)
    from dask_ml_tpu.wrappers import Incremental

    inc = Incremental(SGDClassifier(eta0=0.5, learning_rate="constant"),
                      random_state=0)
    inc.fit(X, y, classes=[0.0, 1.0])
    assert inc.score(X, y) > 0.6


def test_sgd_classifier_in_incremental_search(xy_classification):
    from scipy.stats import loguniform

    from dask_ml_tpu.model_selection import IncrementalSearchCV

    X, y = xy_classification
    search = IncrementalSearchCV(
        SGDClassifier(learning_rate="constant"),
        {"eta0": loguniform(1e-2, 1.0), "alpha": [1e-4, 1e-2]},
        n_initial_parameters=5, max_iter=10, random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    assert search.best_score_ > 0.6


def test_sgd_regressor(xy_regression):
    X, y = xy_regression
    y = (y - y.mean()) / y.std()
    reg = SGDRegressor(eta0=0.05, max_iter=60, random_state=0).fit(X, y)
    assert reg.score(X, y) > 0.7


def test_sgd_bad_loss():
    with pytest.raises(ValueError, match="loss"):
        SGDClassifier(loss="perceptron").fit(
            np.zeros((10, 2)), np.arange(10) % 2
        )


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from dask_ml_tpu.utils import checkpoint as ckpt

    tree = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.asarray(7)}
    path = str(tmp_path / "state")
    ckpt.save_pytree(path, tree)
    back = ckpt.restore_pytree(path, like=tree)
    np.testing.assert_allclose(np.asarray(back["w"]), np.asarray(tree["w"]))
    assert int(back["step"]) == 7

    sc = ckpt.SearchCheckpoint(str(tmp_path / "search"))
    assert sc.load() is None
    sc.save_round(2, [{"score": 0.5}], {"n": 1}, {"0": b"blob"})
    state = sc.load()
    assert state["round"] == 2 and state["history"][0]["score"] == 0.5


def test_metrics_logger(tmp_path):
    from dask_ml_tpu.utils.observability import MetricsLogger, timed

    p = str(tmp_path / "metrics.jsonl")
    with MetricsLogger(p, extra={"run": "t"}) as log:
        log.log(step=0, loss=1.5)
        log.log(step=1, loss=1.2, samples_per_sec=1e6)
    lines = [json.loads(l) for l in open(p)]
    assert lines[0]["loss"] == 1.5 and lines[0]["run"] == "t"
    assert lines[1]["step"] == 1

    import jax.numpy as jnp

    out, secs = timed(lambda: jnp.ones((100, 100)) @ jnp.ones((100, 100)))
    assert secs > 0 and out.shape == (100, 100)


def test_config():
    from dask_ml_tpu import config

    base = config.get_config()
    assert base.dtype == "auto"   # bf16 on TPU, f32 elsewhere (ISSUE 8)
    with config.set(stream_block_rows=123):
        assert config.get_config().stream_block_rows == 123
        with config.set(dtype="bfloat16"):  # nested set layers, not replaces
            assert config.get_config().dtype == "bfloat16"
            assert config.get_config().stream_block_rows == 123
    assert config.get_config().stream_block_rows == base.stream_block_rows


def test_distributed_single_host():
    dist.initialize()  # no-op
    assert dist.process_count() == 1
    assert dist.is_coordinator()
    assert dist.barrier() == len(__import__("jax").devices())
    v = dist.broadcast_host(np.array([1.0, 2.0]))
    np.testing.assert_array_equal(v, [1.0, 2.0])


@pytest.mark.slow
def test_fused_epoch_matches_block_loop():
    """The Incremental wrapper's fused-epoch program (one lax.scan per
    pass) produces the SAME weights as the per-block partial_fit loop —
    same updates, same block order, same lr clock, same masking."""
    from dask_ml_tpu.models.sgd import SGDClassifier, fused_blocks
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.parallel.sharded import take_rows

    rng = np.random.RandomState(3)
    n, d = 1100, 9   # deliberately not a multiple of the mesh
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = as_sharded(X), as_sharded(y)
    B, S = fused_blocks(Xs)  # the ONE block partition both paths use

    fused = SGDClassifier(random_state=0, learning_rate="invscaling")
    fused._fused_epoch(Xs, ys, list(range(B)),
                       classes=np.array([0.0, 1.0]))
    loop = SGDClassifier(random_state=0, learning_rate="invscaling")
    for b in range(B):
        idx = np.arange(b * S, min((b + 1) * S, n))
        kw = {"classes": np.array([0.0, 1.0])} if b == 0 else {}
        loop.partial_fit(take_rows(Xs, idx), take_rows(ys, idx), **kw)
    np.testing.assert_allclose(fused.coef_, loop.coef_, atol=1e-6)
    np.testing.assert_allclose(fused.intercept_, loop.intercept_,
                               atol=1e-6)
    assert fused._t == loop._t  # lr clocks agree for the NEXT epoch


def test_incremental_wrapper_takes_fused_path():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.wrappers import Incremental

    rng = np.random.RandomState(4)
    X = rng.randn(900, 6).astype(np.float32)
    y = (X[:, 1] > 0).astype(np.float32)
    calls = []
    orig = SGDClassifier._fused_epoch

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    SGDClassifier._fused_epoch = spy
    try:
        inc = Incremental(SGDClassifier(random_state=0),
                          shuffle_blocks=True, random_state=5)
        inc.fit(as_sharded(X), as_sharded(y))
    finally:
        SGDClassifier._fused_epoch = orig
    assert calls, "fused path did not engage"
    assert inc.score(as_sharded(X), as_sharded(y)) > 0.8
    # multiclass rides the same fused program (vmapped over classes)
    y3 = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(np.float32)
    inc3 = Incremental(SGDClassifier(random_state=0)).fit(
        as_sharded(X), as_sharded(y3)
    )
    assert inc3.estimator_.coef_.shape == (3, 6)
