"""Host→device block streaming for larger-than-HBM datasets.

Reference equivalent: dask's chunk scheduling — blocks materialize on
workers as tasks run (SURVEY.md §2b row 1). TPU design (SURVEY.md §7
design stance #1, "the heart of the system"): the working set lives in
host RAM (numpy / np.memmap); fixed-shape blocks are placed onto the mesh
with ``jax.device_put`` AHEAD of compute (device_put is async — issuing
the next transfer before consuming the current block overlaps DMA with
compute, the double-buffer pattern). A consumed block's HBM is released
when its Python reference drops at the next loop iteration, so peak
footprint is ≈ (prefetch + 1) blocks.

Blocks have a fixed padded shape (static shapes for jit); the final
partial block carries its logical row count and a mask.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, data_shards, resolve_mesh


class Block:
    """One streamed block: device data + logical row count."""

    __slots__ = ("arrays", "n_rows", "mask")

    def __init__(self, arrays, n_rows, mask):
        self.arrays = arrays
        self.n_rows = n_rows
        self.mask = mask


# auto block budget: bytes of ONE block's X on device. Fixed bytes (not a
# fraction of n) so an arbitrarily large memmap still streams in
# HBM-bounded blocks; peak device footprint ≈ (prefetch + 1) blocks.
_AUTO_BLOCK_BYTES = 256 << 20


def auto_block_rows(n_rows: int, row_bytes: int = 4) -> int:
    """Block size from config: ``stream_block_rows`` if set, else an
    HBM byte budget divided by the bytes-per-row of the streamed data."""
    from ..config import get_config

    br = get_config().stream_block_rows
    if br and br > 0:
        return int(br)
    return max(_AUTO_BLOCK_BYTES // max(int(row_bytes), 1), 1)


def stream_plan(X) -> int | None:
    """Rows-per-block when ``X`` should be fitted out-of-core, else None.

    Streams when X is host-resident and either (a) an ``np.memmap`` —
    its backing file may exceed host AND device memory, so it must never
    be materialized whole — or (b) larger than a configured
    ``config.stream_block_rows``. Device-resident inputs (ShardedArray /
    jax.Array) always take the resident path.
    """
    from ..config import get_config

    if not isinstance(X, np.ndarray) or isinstance(X, np.generic):
        return None
    n = X.shape[0] if X.ndim else 0
    if n == 0:
        return None
    if isinstance(X, np.memmap):
        # blocks stream as float32 regardless of the memmap dtype
        row_bytes = 4 * int(np.prod(X.shape[1:], dtype=np.int64) or 1)
        return min(auto_block_rows(n, row_bytes), n)
    br = get_config().stream_block_rows
    if br and 0 < br < n:
        return br
    return None


class BlockStream:
    """Prefetched epoch iterator over host arrays.

    Parameters
    ----------
    arrays : tuple of host arrays (np.ndarray / np.memmap), equal length.
    block_rows : rows per block (rounded up to a multiple of the mesh's
        data-axis size); None reads ``config.stream_block_rows``, falling
        back to an HBM byte budget divided by the arrays' combined
        bytes-per-row.
    shuffle : shuffle block order each epoch (the reference's
        ``shuffle_blocks``); rows within a block keep locality.
    prefetch : transfers kept in flight ahead of compute (1 = classic
        double buffering); None reads ``config.stream_prefetch``.
    """

    def __init__(self, arrays, block_rows=None, mesh=None, shuffle=False,
                 seed=None, dtype=np.float32, prefetch=None):
        self.mesh = resolve_mesh(mesh)
        self.arrays = tuple(arrays)
        n = len(self.arrays[0])
        for a in self.arrays:
            if len(a) != n:
                raise ValueError("arrays have inconsistent lengths")
        self.n_rows = n
        if block_rows is None:
            row_bytes = sum(
                4 * int(np.prod(a.shape[1:], dtype=np.int64) or 1)
                for a in self.arrays
            )
            block_rows = min(auto_block_rows(n, row_bytes), n)
        if prefetch is None:
            from ..config import get_config

            prefetch = get_config().stream_prefetch
        self.prefetch = max(int(prefetch), 1)
        shards = data_shards(self.mesh)
        self.block_rows = max(
            int(np.ceil(block_rows / shards)) * shards, shards
        )
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.dtype = dtype
        self.n_blocks = int(np.ceil(n / self.block_rows))
        self._shardings = tuple(
            NamedSharding(self.mesh, P(*((DATA_AXIS,) + (None,) * (a.ndim - 1))))
            for a in self.arrays
        )
        self._mask_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

    def _verify_native(self):
        """Which arrays the C++ readahead reader can serve, verified by
        comparing its block 0 against the numpy slice — catches sliced /
        re-offset memmap views whose .offset no longer describes them."""
        from ..io.native import NativeBlockReader, load_block_reader

        oks = []
        for a in self.arrays:
            ok = False
            if (type(a) is np.memmap and a.flags["C_CONTIGUOUS"]
                    and getattr(a, "filename", None) is not None
                    and load_block_reader() is not None):
                try:
                    # the offset/contiguity property is independent of
                    # block size: verify with a SMALL block instead of
                    # double-reading a full (possibly 256 MB) one.
                    # equal_nan: datasets with missing values must not
                    # silently lose the readahead path
                    vb = min(self.block_rows, len(a), 4096)
                    r = NativeBlockReader(a, vb)
                    blk = r.next()
                    ok = blk is not None and np.array_equal(
                        blk, np.asarray(a[: len(blk)]),
                        equal_nan=np.issubdtype(a.dtype, np.floating),
                    )
                    r.close()
                except Exception:
                    ok = False
            oks.append(ok)
        return oks

    def _native_readers(self):
        """Per-array readahead readers for a SEQUENTIAL pass (None where
        inapplicable); the reader thread pread()s blocks ahead of the
        consumer, overlapping disk latency with device transfer/compute
        (native/block_reader.cpp)."""
        if self.shuffle:
            return None
        if getattr(self, "_native_ok", None) is None:
            self._native_ok = self._verify_native()
        if not any(self._native_ok):
            return None
        from ..io.native import NativeBlockReader

        return [
            NativeBlockReader(a, self.block_rows) if ok else None
            for ok, a in zip(self._native_ok, self.arrays)
        ]

    def _block_host(self, b, readers=None):
        lo = b * self.block_rows
        hi = min(lo + self.block_rows, self.n_rows)
        m = hi - lo
        outs = []
        for i, a in enumerate(self.arrays):
            if readers is not None and readers[i] is not None:
                raw = readers[i].next()
                # copy out: the reader's ring buffer is reused, and
                # device_put reads the host buffer asynchronously
                blk = raw.astype(self.dtype, copy=True)
            else:
                blk = np.asarray(a[lo:hi], dtype=self.dtype)
            if m < self.block_rows:  # fixed shape: pad the tail block
                pad = [(0, self.block_rows - m)] + [(0, 0)] * (blk.ndim - 1)
                blk = np.pad(blk, pad)
            outs.append(blk)
        mask = np.zeros(self.block_rows, self.dtype)
        mask[:m] = 1.0
        return outs, m, mask

    def _put(self, host_block):
        outs, m, mask = host_block
        dev = tuple(
            jax.device_put(a, s) for a, s in zip(outs, self._shardings)
        )
        return Block(dev, m, jax.device_put(mask, self._mask_sharding))

    def __iter__(self):
        order = np.arange(self.n_blocks)
        if self.shuffle:
            self.rng.shuffle(order)
        readers = None
        if not self.shuffle:
            try:
                readers = self._native_readers()
            except Exception:
                readers = None
        # k-deep prefetch: device_put is async, so issuing the next k
        # transfers before consuming the current block overlaps DMA with
        # compute (k=1 is the classic double buffer)
        from collections import deque

        pending = deque()
        try:
            for b in order:
                pending.append(self._put(self._block_host(b, readers)))
                if len(pending) > self.prefetch:
                    yield pending.popleft()
            while pending:
                yield pending.popleft()
        finally:
            if readers:
                for r in readers:
                    if r is not None:
                        r.close()

    def __len__(self):
        return self.n_blocks

    def epochs(self, n_epochs):
        for _ in range(n_epochs):
            yield from self


def streamed_map(X, block_rows, fn):
    """Map ``fn(block) -> host array (block_valid_rows, ...)`` over X's
    blocks and concatenate — the one stream→compute→host pattern shared by
    every streamed inference path (GLM decision values, KMeans labels /
    distances, PCA scores). ``fn`` receives the padded device block; its
    output is sliced to the block's logical rows here."""
    outs = []
    for blk in BlockStream((X,), block_rows=block_rows):
        outs.append(np.asarray(fn(blk))[: blk.n_rows])
    return np.concatenate(outs, axis=0)
