// Threaded readahead block reader for out-of-core streaming.
//
// Role: the host-side IO half of dask_ml_tpu's block streaming
// (parallel/streaming.py). The reference's analog is dask's worker
// threads materializing chunks from storage while other chunks compute;
// here a reader thread pread()s fixed-size row blocks from the backing
// file into a ring of buffers AHEAD of the consumer, so disk latency
// overlaps with the device_put + compute of the previous blocks even
// when the OS page cache is cold.
//
// C ABI (ctypes-friendly, no pybind11 in this image):
//   void* br_open(path, offset, row_bytes, n_rows, block_rows, depth)
//   int64 br_next(handle, out_buf)   -> rows copied, 0 at end, -1 error
//   void  br_close(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Block {
  std::vector<char> data;
  int64_t rows = 0;
  bool ready = false;
};

struct Reader {
  int fd = -1;
  int64_t offset = 0;
  int64_t row_bytes = 0;
  int64_t n_rows = 0;
  int64_t block_rows = 0;
  int64_t n_blocks = 0;

  std::vector<Block> ring;
  int64_t produced = 0;  // next block index the reader will fill
  int64_t consumed = 0;  // next block index the consumer will take
  std::atomic<bool> error{false};
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_can_produce, cv_can_consume;
  std::thread worker;

  void run() {
    while (true) {
      int64_t b;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_can_produce.wait(lk, [&] {
          return stop || (produced < n_blocks &&
                          produced - consumed < (int64_t)ring.size());
        });
        if (stop || produced >= n_blocks) return;
        b = produced;
      }
      Block &slot = ring[b % ring.size()];
      int64_t lo = b * block_rows;
      int64_t rows = std::min(block_rows, n_rows - lo);
      int64_t want = rows * row_bytes;
      int64_t got = 0;
      while (got < want) {
        ssize_t r = pread(fd, slot.data.data() + got, want - got,
                          offset + lo * row_bytes + got);
        if (r <= 0) { error = true; break; }
        got += r;
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        slot.rows = error ? -1 : rows;
        slot.ready = true;
        ++produced;
      }
      cv_can_consume.notify_one();
      if (error) return;
    }
  }
};

}  // namespace

extern "C" {

void *br_open(const char *path, int64_t offset, int64_t row_bytes,
              int64_t n_rows, int64_t block_rows, int32_t depth) {
  if (row_bytes <= 0 || n_rows <= 0 || block_rows <= 0) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  auto *r = new Reader();
  r->fd = fd;
  r->offset = offset;
  r->row_bytes = row_bytes;
  r->n_rows = n_rows;
  r->block_rows = block_rows;
  r->n_blocks = (n_rows + block_rows - 1) / block_rows;
  int32_t d = depth < 1 ? 1 : (depth > 16 ? 16 : depth);
  r->ring.resize((size_t)d + 1);
  for (auto &b : r->ring) b.data.resize((size_t)(block_rows * row_bytes));
  r->worker = std::thread([r] { r->run(); });
  return r;
}

int64_t br_next(void *h, char *out) {
  auto *r = static_cast<Reader *>(h);
  if (!r) return -1;
  if (r->consumed >= r->n_blocks) return 0;
  int64_t b = r->consumed;
  Block &slot = r->ring[b % r->ring.size()];
  {
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_can_consume.wait(lk, [&] { return slot.ready || r->error; });
  }
  if (r->error || slot.rows < 0) return -1;
  int64_t rows = slot.rows;
  std::memcpy(out, slot.data.data(), (size_t)(rows * r->row_bytes));
  {
    std::lock_guard<std::mutex> lk(r->mu);
    slot.ready = false;
    ++r->consumed;
  }
  r->cv_can_produce.notify_one();
  return rows;
}

void br_close(void *h) {
  auto *r = static_cast<Reader *>(h);
  if (!r) return;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stop = true;
  }
  r->cv_can_produce.notify_all();
  if (r->worker.joinable()) r->worker.join();
  if (r->fd >= 0) close(r->fd);
  delete r;
}

}  // extern "C"
