"""FleetServer: N replica ModelServers behind one admission door.

The production shape ROADMAP item 2 names, grown out of PR 2's single
worker thread: a fleet fronts a named model from a
:class:`~dask_ml_tpu.serving.registry.ModelRegistry` with

- **replicas** — N :class:`ModelServer` workers. With several local
  devices each replica's fitted-param pytrees are COMMITTED to its own
  device (true per-device data parallelism — XLA runs the replicas'
  programs concurrently); on one device the replicas are thread workers
  whose coalescing windows and host pack/demux overlap each other's
  device executions;
- **least-loaded routing** — ``submit`` ranks healthy replicas by
  queued ROWS (``serving_queue_depth`` is the scraped twin), so one
  slow replica collects less new work instead of a round-robin pile-up;
- **SLO-aware admission** — with ``config.serving_slo_ms`` set, the
  door predicts each candidate's completion time (queued rows x the
  windowed per-(method, bucket) execution quantile the live
  ``serving_latency_seconds`` histograms also render) and sheds with
  typed :class:`~dask_ml_tpu.serving.SloShed` when every replica would
  miss — backpressure BEFORE the latency collapse, not after;
- **zero-recompile hot-swap** — the fleet subscribes to its registry
  name; every publish/rollback rolls through the replicas swapping the
  param pytrees under the compiled entry points
  (``CompiledBatchFn.swap_params`` — programs close over shapes, not
  values), so a same-shape version flip under live traffic mints ZERO
  XLA compiles and loses zero requests. A shape-incompatible publish
  falls back to a rebuild (fresh compiles, warmed off the serving path,
  counted as ``serving_swap_rebuilds``);
- **failover** — a dead/stopped replica stops receiving new work
  (its queued requests resolve with typed ``ServerClosed``); traffic
  drains to the survivors, with ``serving_reroutes`` counting the hops.

Serve-while-training caps it: :func:`serve_while_training` drives an
``Incremental``/SGD ``partial_fit`` loop and publishes a snapshot to the
registry every pass, so an online model refreshes its serving version
under live traffic (see ``examples/10_fleet.py``).
"""

from __future__ import annotations

import threading

import numpy as np

from ..wrappers import ParamSwapError
from . import metrics as smetrics
from ._buckets import BucketLadder
from ._server import (
    ModelServer,
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    SloShed,
)
from .policy import admission_verdict, predict_completion_s
from .registry import ModelRegistry

__all__ = ["FleetServer", "NoHealthyReplicas", "serve_while_training"]


class NoHealthyReplicas(ServingError):
    """Every replica is stopped/dead: the fleet cannot place this
    request anywhere. Distinct from ServerOverloaded (transient load)
    — this needs replicas restarted, not a retry."""


def _auto_replicas(devices) -> int:
    """Default replica count: one per local device when several exist
    (per-device placement), else one worker (thread replicas are an
    explicit choice — they help when window sleeps / host work dominate,
    which the caller knows better than we do)."""
    return len(devices) if len(devices) > 1 else 1


class FleetServer:
    """Serve a registry model through N replica ModelServers.

    Parameters
    ----------
    model : fitted estimator, optional
        Convenience: published into ``registry`` under ``name`` as
        version 1. Omit it to front a name the registry already holds.
    registry : ModelRegistry, default a fresh private one
    name : str, the registry name this fleet follows
    methods : tuple of served method names
    replicas : int, default ``config.serving_replicas``
        (0 = auto: one per local device when several exist, else 1).
        More replicas than devices share devices round-robin.
    ladder / max_queue / batch_window_ms / timeout_ms
        forwarded to every replica (``max_queue`` is PER REPLICA).

    Use as a context manager::

        with FleetServer(clf, replicas=2).warmup() as fleet:
            y = fleet.predict(x)
            fleet.publish(new_clf)      # zero-recompile rolling swap
    """

    def __init__(self, model=None, registry=None, name="model",
                 methods=("predict",), replicas=None, ladder=None,
                 max_queue=None, batch_window_ms=None, timeout_ms=None,
                 supervise=None, autoscale=None):
        import jax

        from ..config import get_config

        cfg = get_config()
        self.name = str(name)
        self.registry = registry if registry is not None \
            else ModelRegistry()
        if model is not None:
            self.registry.publish(self.name, model)
        # the fleet is born from the registry's CURRENT version — a
        # registry-only construction requires one to exist
        current = self.registry.get(self.name)
        devices = list(jax.local_devices())
        n = int(cfg.serving_replicas if replicas is None else replicas)
        if n <= 0:
            n = _auto_replicas(devices)
        self.ladder = ladder if ladder is not None \
            else BucketLadder.from_config()
        self._slo_s = float(cfg.serving_slo_ms) / 1e3
        self._slo_shed = bool(cfg.serving_slo_shed)
        self._methods = tuple(methods)
        # replica ctor args, kept so the supervisor can rebuild a dead
        # replica slot with the fleet's exact configuration
        self._max_queue = max_queue
        self._batch_window_ms = batch_window_ms
        self._timeout_ms = timeout_ms
        self.replicas = tuple(
            self._make_replica(i, current.estimator, current.version)
            for i in range(n)
        )
        self.version = current.version
        self._lock = threading.Lock()   # serializes swaps vs stop
        self._started = False
        self._swaps = 0
        # replica supervision (reliability/supervisor.py): a dead
        # replica is rebuilt off the serving path instead of merely
        # routed around (config.serving_supervise; default off)
        self._supervise = bool(
            cfg.serving_supervise if supervise is None else supervise
        )
        self._supervisor = None
        # SLO-driven replica autoscaling (serving/autoscale.py): the
        # admission predictor ADDS/RETIRES replicas under hysteresis
        # bands (config.serving_autoscale; default off)
        self._autoscale = bool(
            cfg.serving_autoscale if autoscale is None else autoscale
        )
        self._autoscaler = None
        # follow the name: every publish/rollback becomes a rolling
        # swap (the immediate initial callback is version-matched away)
        self._sub = self.registry.subscribe(self.name, self._on_publish)

    def _make_replica(self, i, estimator, version):
        """One replica ModelServer for slot ``i`` with this fleet's
        configuration — shared by construction and the supervisor's
        rebuild path (a replacement must be configured IDENTICALLY to
        the replica it replaces, device placement included)."""
        import jax

        devices = list(jax.local_devices())
        r = ModelServer(
            estimator, methods=self._methods, ladder=self.ladder,
            max_queue=self._max_queue,
            batch_window_ms=self._batch_window_ms,
            timeout_ms=self._timeout_ms,
            device=devices[i % len(devices)]
            if len(devices) > 1 else None,
            replica_id=i, name=self.name,
        )
        r.model_version = int(version)
        return r

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        from ..observability.live import register_server, unregister_server

        with self._lock:
            for r in self.replicas:
                r.start()
            self._started = True
        register_server(self)
        for r in self.replicas:
            # /status lists the FLEET entry (whose stats() embeds every
            # replica's); a second standalone listing per replica would
            # both duplicate the view and double-consume each replica's
            # windowed-quantile cursor (two stats() readers fracture the
            # delta window)
            unregister_server(r)
        for r in self.replicas:
            smetrics.set_replica_gauges(r.replica_id,
                                        version=r.model_version,
                                        healthy=True)
        if self._supervise and self._supervisor is None:
            from ..reliability.supervisor import ReplicaSupervisor

            self._supervisor = ReplicaSupervisor(self).start()
        if self._autoscale and self._autoscaler is None:
            from .autoscale import ReplicaAutoscaler

            self._autoscaler = ReplicaAutoscaler(self).start()
        smetrics.set_replica_count_gauge(self.name, len(self.replicas))
        return self

    def stop(self, drain=True, timeout=None):
        from ..observability.live import unregister_server

        unregister_server(self)
        if self._autoscaler is not None:
            # the scaler stands down BEFORE replicas stop — a shutdown
            # emptying the queues must not read as a scale-down signal
            self._autoscaler.stop()
            self._autoscaler = None
        if self._supervisor is not None:
            # the supervisor must stand down BEFORE replicas stop, or
            # it would read the deliberate shutdown as a fleet-wide
            # crash and start rebuilding corpses
            self._supervisor.stop()
            self._supervisor = None
        self.registry.unsubscribe(self.name, self._sub)
        with self._lock:
            self._started = False
            for r in self.replicas:
                r.stop(drain=drain, timeout=timeout)
        for r in self.replicas:
            # unregistered replicas must not leave stale
            # serving_replica_*/queue gauge series latched on /metrics
            smetrics.drop_replica_gauges(r.replica_id)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop(drain=exc_type is None)
        return False

    def warmup(self):
        """Compile every replica's (method, bucket) grid — with
        per-device placement each replica owns its own programs, so the
        grid is warmed once per (method, bucket, device). After this, a
        ladder workload (including any number of same-shape swaps) pays
        zero new XLA compiles."""
        for r in self.replicas:
            r.warmup()
        return self

    # -- hot-swap ----------------------------------------------------------
    def _on_publish(self, mv):
        """Registry callback: roll the new version through the
        replicas. Zero-recompile swap when shapes match; rebuild (fresh
        compiles, warmed before install) when they don't. In-flight
        batches finish on their old version — no request is lost."""
        with self._lock:
            # notifications run outside the registry lock, so two
            # back-to-back publishes can deliver out of order; converge
            # to the registry's CURRENT version instead of the notified
            # one (a stale callback then lands as a version-matched
            # no-op, never a downgrade — rollback still applies, since
            # rollback re-points current itself)
            try:
                mv = self.registry.get(self.name)
            except KeyError:
                return
            changed = 0
            for r in self.replicas:
                if r.model_version == mv.version:
                    continue
                q = getattr(mv, "quantize", None)
                try:
                    r.swap_model(mv.estimator, version=mv.version,
                                 quantize=q)
                except ParamSwapError:
                    r.rebuild_model(mv.estimator, version=mv.version,
                                    quantize=q)
                smetrics.set_replica_gauges(r.replica_id,
                                            version=mv.version)
                changed += 1
            self.version = mv.version
            if changed:
                self._swaps += 1

    def publish(self, estimator, tag=None, quantize=None) -> int:
        """Publish a new version of this fleet's model (and hot-swap
        every replica before returning). ``quantize="int8"`` serves the
        version through the replicas' pre-warmed weight-quantized entry
        points (config.serving_warm_flavors)."""
        return self.registry.publish(self.name, estimator, tag=tag,
                                     quantize=quantize)

    def rollback(self, version=None) -> int:
        """Roll the fleet back to an archived registry version."""
        return self.registry.rollback(self.name, version=version)

    # -- request plane -----------------------------------------------------
    def _healthy(self):
        return [r for r in self.replicas if r.healthy]

    def submit(self, X, method="predict"):
        """Admit one request: SLO admission at the door, then
        least-loaded placement over healthy replicas with failover.
        Returns the chosen replica's Future."""
        from ..observability import _requests as rtrace

        X = np.asarray(X, np.float32)
        n_rows = 1 if X.ndim == 1 else int(X.shape[0])
        ranked = sorted(self._healthy(),
                        key=lambda r: (r.queue_rows, r._queue.depth))
        if not ranked:
            raise NoHealthyReplicas(
                f"no healthy replicas (0/{len(self.replicas)}); "
                "restart the fleet or its workers"
            )
        if self._slo_s > 0 and self._slo_shed:
            # shed only when EVERY replica's prediction misses (the
            # documented contract): with heterogeneous replicas the
            # least-QUEUED one can still be the slowest-predicted, and
            # shedding off it alone would refuse traffic a sibling
            # would serve inside the SLO. When some replica admits,
            # rotate it to the front so placement honors the
            # prediction (least-loaded order among the rest remains
            # the failover chain).
            admit_at = None
            best_predicted = None
            for i, r in enumerate(ranked):
                predicted = predict_completion_s(
                    r.queue_rows, n_rows, self.ladder.max_rows,
                    r.predict_exec_s(method, n_rows),
                )
                if best_predicted is None or predicted < best_predicted:
                    best_predicted = predicted
                if admission_verdict(predicted, self._slo_s):
                    admit_at = i
                    break
            if admit_at is None:
                smetrics.record_drop("slo_shed")
                if rtrace.tracing_enabled():
                    # a shed request never reaches a replica's _admit,
                    # so its trace is born AND finished at the door —
                    # the tail sampler always keeps slo_shed traces
                    tr = rtrace.new_trace(method, n_rows)
                    tr.tag(slo_shed=True)
                    tr.finish("slo_shed")
                raise SloShed(
                    f"predicted completion {best_predicted * 1e3:.1f}ms "
                    f"on the best of {len(ranked)} healthy replica(s) "
                    f"exceeds the {self._slo_s * 1e3:.0f}ms SLO; "
                    "request shed"
                )
            if admit_at:
                ranked = ranked[admit_at:] + ranked[:admit_at]
        last_exc = None
        rerouted_from = None
        for i, r in enumerate(ranked):
            try:
                if rerouted_from is not None:
                    # the surviving replica's trace records where the
                    # request was rerouted from (thread-local pending
                    # tag, picked up by _admit's new_trace)
                    with rtrace.tagging(rerouted_from=rerouted_from):
                        return r.submit(X, method=method)
                return r.submit(X, method=method)
            except ServerClosed as exc:
                # replica died between the health check and the put —
                # its own queue resolves with typed errors; THIS request
                # fails over to the next-least-loaded survivor. The dead
                # replica's gauge series are DROPPED, not left latched
                # at stale values forever (a supervisor restart re-adds
                # them at the new version)
                last_exc = exc
                smetrics.record_reroute()
                smetrics.drop_replica_gauges(r.replica_id)
                rerouted_from = r.replica_id
            except ServerOverloaded as exc:
                last_exc = exc
                if i + 1 < len(ranked):
                    smetrics.record_reroute()
                    rerouted_from = r.replica_id
        if isinstance(last_exc, ServerClosed):
            raise NoHealthyReplicas(
                f"every replica refused this request; last: {last_exc}"
            ) from last_exc
        raise last_exc

    # blocking conveniences ------------------------------------------------
    def _call(self, X, method):
        import concurrent.futures as cf

        fut = self.submit(X, method=method)
        timeout_s = self.replicas[0].timeout_s
        try:
            return fut.result(None if timeout_s <= 0
                              else 30.0 + timeout_s)
        except cf.TimeoutError:
            raise RequestTimeout(
                f"fleet {method} did not complete within the "
                f"{timeout_s * 1e3:.0f}ms deadline + 30s execution "
                "allowance"
            ) from None

    def predict(self, X):
        return self._call(X, "predict")

    def predict_proba(self, X):
        return self._call(X, "predict_proba")

    def decision_function(self, X):
        return self._call(X, "decision_function")

    def transform(self, X):
        return self._call(X, "transform")

    def _flush_quality(self):
        """Flush every replica's pending drift-fold sample (the fleet
        entry stands in for its unlisted replicas on the live plane —
        ``drift.compute`` reaches them through this)."""
        for r in self.replicas:
            r._flush_quality()

    # -- stats -------------------------------------------------------------
    def stats(self):
        """Fleet aggregate + per-replica breakdown (the /status view
        fleet_smoke asserts): totals sum over replicas; ``replicas``
        carries each worker's own stats() (windowed latency, exec
        predictions, version, health)."""
        per = [r.stats() for r in self.replicas]
        return {
            "fleet": self.name,
            "version": self.version,
            "n_replicas": len(self.replicas),
            "healthy_replicas": sum(1 for r in self.replicas
                                    if r.healthy),
            "swaps": self._swaps,
            "requests": sum(p["requests"] for p in per),
            "batches": sum(p["batches"] for p in per),
            "queue_depth": sum(p["queue_depth"] for p in per),
            "queue_rows": sum(p["queue_rows"] for p in per),
            "replicas": per,
        }


def serve_while_training(fleet, incremental, X, y=None, passes=1,
                         classes=None, on_pass=None):
    """The serve-while-training driver: run ``passes`` streamed
    ``partial_fit`` passes of an :class:`~dask_ml_tpu.wrappers.
    Incremental` (or any estimator exposing ``partial_fit`` +
    ``estimator_``) and publish a snapshot to ``fleet``'s registry after
    EVERY pass — each publish rolls a zero-recompile hot-swap through
    the replicas while they keep answering traffic.

    ``classes`` is required for classifiers on a fresh model (the first
    ``partial_fit`` needs the label universe). ``on_pass(pass_no,
    version)`` observes each flip (progress bars, tests). Returns the
    trained ``incremental``.
    """
    # pass-granular resume (ISSUE 11): with stream checkpointing armed
    # (config.stream_checkpoint_path) the wrapper tracks
    # ``completed_passes_`` across kills — the checkpoint is restored
    # BEFORE the first pass runs, and ``passes`` becomes the TOTAL pass
    # target: already-completed passes are not re-trained (a driver
    # killed after its final pass but before the clear resumes to ZERO
    # remaining work, not one extra pass). Without checkpointing (the
    # default) the loop is byte-for-byte the old fixed-count behavior.
    done = 0
    resume = getattr(incremental, "resume_from_checkpoint", None)
    if resume is not None:
        try:
            kw = {} if classes is None else {"classes": classes}
            done = int(resume(X, y, **kw) or 0)
        except Exception:
            done = 0
    p_done = done
    for _ in range(max(int(passes) - done, 0) if done
                   else int(passes)):
        if classes is not None:
            incremental.partial_fit(X, y, classes=classes)
        elif y is not None:
            incremental.partial_fit(X, y)
        else:
            incremental.partial_fit(X)
        tracked = getattr(incremental, "completed_passes_", None)
        p_done = int(tracked) if tracked is not None else p_done + 1
        est = getattr(incremental, "estimator_", incremental)
        version = fleet.publish(est, tag=f"pass{p_done}")
        if on_pass is not None:
            on_pass(p_done, version)
        if tracked is not None and p_done >= int(passes):
            break
    # the pass sequence completed: the checkpoint slot must not resume
    # into a future training run
    getattr(incremental, "_clear_pass_checkpoint", lambda: None)()
    return incremental
