"""Text feature extraction: HashingVectorizer, FeatureHasher,
CountVectorizer.

Reference: ``dask_ml/feature_extraction/text.py`` (SURVEY.md §2a Text
row): stateless hashing mapped per block producing scipy.sparse CSR
blocks; CountVectorizer is embarrassingly parallel given a vocabulary,
else builds the vocabulary distributedly.

TPU design decision (SURVEY.md §7 hard parts, "Sparse"): tokenization and
hashing are host-side string work (sklearn's C kernels per block — same
per-block engine as the reference); the TPU-facing bridge is STREAMING:
a CSR corpus fed to any streamed fit (``LogisticRegression().fit(csr,
y)``, ``Incremental(SGDClassifier())``) densifies ONE fixed-shape block
at a time into the prefetched device buffer (``parallel.streaming``),
so peak host/device memory is O(block) at any ``n_features`` — the
analog of the reference streaming CSR chunks through per-block sklearn
partial_fit. ``to_sharded_dense`` remains the small-corpus shortcut.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp
import sklearn.feature_extraction.text as sktext
from sklearn.feature_extraction import FeatureHasher as SkFeatureHasher

from ..base import BaseEstimator, TransformerMixin
from ..parallel.sharded import ShardedArray, as_sharded

__all__ = ["HashingVectorizer", "FeatureHasher", "CountVectorizer",
           "to_sharded_dense", "DenseBudgetExceeded"]


class DenseBudgetExceeded(ValueError):
    """A one-shot dense materialization of a sparse corpus would exceed
    ``config.to_dense_byte_budget`` — use the streamed sparse path
    (feed the CSR / ``transform_sparse`` output straight to a streamed
    fit, or ``transform_blocks`` for custom block loops) instead of
    densifying the whole corpus."""


def _blocks(raw_documents, block_size=10_000):
    docs = list(raw_documents) if not isinstance(
        raw_documents, (list, np.ndarray)
    ) else raw_documents
    for i in range(0, len(docs), block_size):
        yield docs[i:i + block_size]


def to_sharded_dense(csr, mesh=None, dtype=np.float32) -> ShardedArray:
    """Densify a (host) CSR matrix onto the mesh — the SMALL-corpus
    bridge from text hashing to TPU estimators. Refuses (typed
    :class:`DenseBudgetExceeded`) when the dense form would exceed
    ``config.to_dense_byte_budget``: every streamed fit consumes the
    CSR directly at O(block) memory (and, with ``config.stream_sparse``
    on, at nnz-proportional device cost), so a silent tens-of-GB host
    allocation is never the right answer."""
    from ..config import get_config

    n, d = int(csr.shape[0]), int(csr.shape[1])
    nbytes = n * d * np.dtype(dtype).itemsize
    budget = int(get_config().to_dense_byte_budget)
    if budget > 0 and nbytes > budget:
        raise DenseBudgetExceeded(
            f"densifying a {n} x {d} sparse corpus needs {nbytes >> 20} "
            f"MiB > config.to_dense_byte_budget ({budget >> 20} MiB); "
            "pass the sparse matrix straight to a streamed fit (it "
            "densifies one block at a time — with config.stream_sparse "
            "it streams device-resident at nnz cost), or raise the "
            "budget explicitly"
        )
    return as_sharded(np.asarray(csr.todense(), dtype=dtype), mesh=mesh)


class HashingVectorizer(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/feature_extraction/text.py::HashingVectorizer."""

    def __init__(self, input="content", encoding="utf-8",
                 decode_error="strict", strip_accents=None, lowercase=True,
                 preprocessor=None, tokenizer=None, stop_words=None,
                 token_pattern=r"(?u)\b\w\w+\b", ngram_range=(1, 1),
                 analyzer="word", n_features=2 ** 20, binary=False,
                 norm="l2", alternate_sign=True, dtype=np.float64):
        self.input = input
        self.encoding = encoding
        self.decode_error = decode_error
        self.strip_accents = strip_accents
        self.lowercase = lowercase
        self.preprocessor = preprocessor
        self.tokenizer = tokenizer
        self.stop_words = stop_words
        self.token_pattern = token_pattern
        self.ngram_range = ngram_range
        self.analyzer = analyzer
        self.n_features = n_features
        self.binary = binary
        self.norm = norm
        self.alternate_sign = alternate_sign
        self.dtype = dtype

    def _inner(self):
        return sktext.HashingVectorizer(**self.get_params())

    def fit(self, raw_documents, y=None):
        return self  # stateless

    def transform(self, raw_documents):
        inner = self._inner()
        parts = [inner.transform(b) for b in _blocks(raw_documents)]
        return sp.vstack(parts).tocsr()

    def transform_blocks(self, raw_documents, block_size=10_000):
        """Yield per-block CSR matrices directly — the streamed
        emitter: no ``sp.vstack`` of the whole corpus, no giant host
        CSR. Each yielded block is what sklearn's hashing kernel
        produced for ``block_size`` documents; feed them to
        :class:`~dask_ml_tpu.parallel.streaming.SparseBlocks` (or use
        :meth:`transform_sparse`) to stream a fit at O(block) host
        memory."""
        inner = self._inner()
        for b in _blocks(raw_documents, block_size):
            yield inner.transform(b).tocsr()

    def transform_sparse(self, raw_documents, block_size=10_000):
        """The corpus as a
        :class:`~dask_ml_tpu.parallel.streaming.SparseBlocks` view over
        the hashed per-block CSRs — a row-concatenated source every
        streamed fit consumes WITHOUT the ``sp.vstack`` copy
        ``transform`` pays (and, with ``config.stream_sparse`` on,
        without ever densifying a block: the stream stages bucketed-nnz
        device slabs straight from these blocks)."""
        from ..parallel.streaming import SparseBlocks

        return SparseBlocks(
            list(self.transform_blocks(raw_documents, block_size))
        )

    def fit_transform(self, raw_documents, y=None):
        return self.transform(raw_documents)


class FeatureHasher(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/feature_extraction/text.py::FeatureHasher."""

    def __init__(self, n_features=2 ** 20, input_type="dict",
                 dtype=np.float64, alternate_sign=True):
        self.n_features = n_features
        self.input_type = input_type
        self.dtype = dtype
        self.alternate_sign = alternate_sign

    def fit(self, X=None, y=None):
        return self

    def transform(self, raw_X):
        inner = SkFeatureHasher(**self.get_params())
        parts = [inner.transform(b) for b in _blocks(list(raw_X))]
        return sp.vstack(parts).tocsr()

    def fit_transform(self, raw_X, y=None):
        return self.transform(raw_X)


class CountVectorizer(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/feature_extraction/text.py::CountVectorizer — with a
    given ``vocabulary`` the transform is embarrassingly parallel; else
    the vocabulary is the union of per-block vocabularies (the
    reference's distributed vocabulary build, here a host reduce)."""

    def __init__(self, input="content", encoding="utf-8",
                 decode_error="strict", strip_accents=None, lowercase=True,
                 preprocessor=None, tokenizer=None, stop_words=None,
                 token_pattern=r"(?u)\b\w\w+\b", ngram_range=(1, 1),
                 analyzer="word", max_df=1.0, min_df=1, max_features=None,
                 vocabulary=None, binary=False, dtype=np.int64):
        self.input = input
        self.encoding = encoding
        self.decode_error = decode_error
        self.strip_accents = strip_accents
        self.lowercase = lowercase
        self.preprocessor = preprocessor
        self.tokenizer = tokenizer
        self.stop_words = stop_words
        self.token_pattern = token_pattern
        self.ngram_range = ngram_range
        self.analyzer = analyzer
        self.max_df = max_df
        self.min_df = min_df
        self.max_features = max_features
        self.vocabulary = vocabulary
        self.binary = binary
        self.dtype = dtype

    def fit(self, raw_documents, y=None):
        self.fit_transform(raw_documents)
        return self

    def _build_vocabulary(self, raw_documents):
        """Union of per-block vocabularies with GLOBAL document/term
        frequencies, then sklearn's own pruning semantics applied to the
        merged counts: min_df/max_df filter on corpus-wide document
        frequency and max_features keeps the top terms by corpus term
        frequency (ties alphabetical) — matching what sklearn computes on
        the concatenated corpus (ref CountVectorizer._limit_features).
        Removed terms land in ``stop_words_``, as in sklearn."""
        from collections import Counter

        df = Counter()  # document frequency per term
        tf = Counter()  # corpus term frequency (max_features ranking)
        n_docs = 0
        for block in _blocks(raw_documents):
            cv = sktext.CountVectorizer(**self.get_params())
            cv.set_params(vocabulary=None, max_df=1.0, min_df=1,
                          max_features=None)
            Xb = cv.fit_transform(block)
            n_docs += Xb.shape[0]
            terms = cv.get_feature_names_out()
            dfs = np.asarray((Xb > 0).sum(axis=0)).ravel()
            tfs = np.asarray(Xb.sum(axis=0)).ravel()
            for t, d, c in zip(terms, dfs, tfs):
                df[t] += int(d)
                tf[t] += int(c)
        # sklearn threshold semantics: integer = absolute count, float =
        # fraction of documents (no rounding)
        min_c = (self.min_df if isinstance(self.min_df, (int, np.integer))
                 else self.min_df * n_docs)
        max_c = (self.max_df if isinstance(self.max_df, (int, np.integer))
                 else self.max_df * n_docs)
        if max_c < min_c:
            raise ValueError("max_df corresponds to < documents than min_df")
        kept = {t for t, c in df.items() if min_c <= c <= max_c}
        removed = set(df) - kept
        if self.max_features is not None and len(kept) > self.max_features:
            ranked = sorted(kept, key=lambda t: (-tf[t], t))
            cut = set(ranked[int(self.max_features):])
            removed |= cut
            kept -= cut
        if not kept:
            raise ValueError(
                "After pruning, no terms remain. Try a lower min_df or a "
                "higher max_df."
            )
        self.stop_words_ = removed
        return {t: i for i, t in enumerate(sorted(kept))}

    def fit_transform(self, raw_documents, y=None):
        if self.vocabulary is not None:
            vocab = self.vocabulary
            if not isinstance(vocab, dict):
                vocab = {t: i for i, t in enumerate(vocab)}
        else:
            vocab = self._build_vocabulary(raw_documents)
        self.vocabulary_ = vocab
        return self.transform(raw_documents)

    def transform(self, raw_documents):
        if not hasattr(self, "vocabulary_"):
            if self.vocabulary is None:
                raise ValueError("CountVectorizer is not fitted")
            self.vocabulary_ = (
                self.vocabulary if isinstance(self.vocabulary, dict)
                else {t: i for i, t in enumerate(self.vocabulary)}
            )
        params = self.get_params()
        params["vocabulary"] = self.vocabulary_
        inner = sktext.CountVectorizer(**params)
        parts = [inner.transform(b) for b in _blocks(raw_documents)]
        return sp.vstack(parts).tocsr()

    def get_feature_names_out(self, input_features=None):
        return np.asarray(
            sorted(self.vocabulary_, key=self.vocabulary_.get), dtype=object
        )
