"""KMeans with k-means‖ (scalable k-means++) initialization.

Reference: ``dask_ml/cluster/k_means.py`` (SURVEY.md §2a KMeans row, §3.1
call stack): Lloyd's iterations over row-chunked arrays with a global
barrier per iteration, k-means‖ init (Bahmani 2012) with
``oversampling_factor``, plus ``init='k-means++'`` (on a sample) and
``'random'``.

TPU design (SURVEY.md §3.1 "boundary pattern" + §7 hard parts):

- The ENTIRE Lloyd loop is one jitted program (``lax.while_loop``):
  distance+argmin fuses into the MXU matmul, centroid sums/counts are
  ``segment_sum`` (memory-light — no (n, k) one-hot materialized), centers
  stay replicated, the tol test runs on device. The reference pays a
  cluster round-trip per iteration; here the host is only touched once.
- k-means‖ sampling draws a FIXED ``l = oversampling_factor * k`` points
  per round via Gumbel top-l with weights ∝ d² (weighted sampling without
  replacement), writing into a static-shape candidate buffer — XLA-friendly
  static shapes instead of the reference's variable-size Bernoulli draws
  (expected size l), same distribution in spirit.
- The final "cluster the candidates" step runs sklearn's k-means++ on the
  ≤(1 + l·rounds) weighted candidates on host, exactly the reference's
  pattern of running a local solver on the tiny candidate set.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, ClusterMixin, TransformerMixin, to_host
from ..ops.pairwise import euclidean_distances, euclidean_distances_sq
from ..ops.reductions import masked_mean_var
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_array, check_is_fitted


# -- jitted kernels ---------------------------------------------------------

from ..observability import emit_jit_step, span, track_program
from ..plans import tracked as plan_tracked


@track_program("kmeans.lloyd")
@partial(jax.jit, static_argnames=("log", "mxu_dtype"))
def _lloyd_run(X, mask, centers0, max_iter, tol2, log=False,
               mxu_dtype=None):
    """Full Lloyd loop on device. Returns (centers, n_iter, final_shift2).

    ``mxu_dtype=jnp.bfloat16`` (config.dtype="bfloat16"): the distance
    cross-term matmul — the loop's FLOPs — runs at bf16 with f32
    accumulation; centroid sums/counts and the shift stay f32 (input
    data is untouched). Center parity vs f32 is ~1e-2 relative (bf16
    input rounding on distances can flip assignments of near-equidistant
    points)."""
    k = centers0.shape[0]

    def assign(centers):
        d2 = euclidean_distances_sq(X, centers, mxu_dtype=mxu_dtype)
        return jnp.argmin(d2, axis=1), jnp.min(d2, axis=1)

    def cond(carry):
        centers, it, shift2 = carry
        return (it < max_iter) & (shift2 > tol2)

    def body(carry):
        centers, it, _ = carry
        labels, _ = assign(centers)
        sums = jax.ops.segment_sum(X * mask[:, None], labels, num_segments=k)
        counts = jax.ops.segment_sum(mask, labels, num_segments=k)
        new = jnp.where(counts[:, None] > 0, sums / counts[:, None], centers)
        shift2 = jnp.sum((new - centers) ** 2)
        if log:
            emit_jit_step(it, center_shift2=shift2)
        return new, it + 1, shift2

    inf = jnp.asarray(jnp.inf, X.dtype)
    centers, it, shift2 = jax.lax.while_loop(cond, body, (centers0, 0, inf))
    return centers, it, shift2


@track_program("kmeans.lloyd_pallas")
@partial(jax.jit, static_argnames=("mesh", "interpret", "log"))
def _lloyd_run_pallas(X, mask, centers0, max_iter, tol2, mesh,
                      interpret=False, log=False):
    """Lloyd loop where each iteration's data pass is the fused Pallas
    kernel (ops/pallas_fused.py): X streams through VMEM once per
    iteration; sums/counts psum over ICI."""
    from jax.sharding import PartitionSpec as P

    from ..ops.linalg import shard_map
    from ..ops.pallas_fused import fused_lloyd_stats
    from ..parallel.mesh import DATA_AXIS

    k = centers0.shape[0]

    def shard_step(xs, ms, c):
        # per-shard valid-row count (valid rows are a prefix of each
        # shard's padded rows by construction) — the stats-only kernel
        # takes this scalar instead of an (n, 1) mask operand whose TPU
        # layout would pad 128x in HBM. Integer sum: an f32 accumulator
        # saturates at 2^24 rows, silently dropping rows past 16.7M
        nv = jnp.sum(ms.astype(jnp.int32))
        sums, counts, _ = fused_lloyd_stats(
            xs, nv, c, interpret=interpret
        )
        return (jax.lax.psum(sums, DATA_AXIS),
                jax.lax.psum(counts, DATA_AXIS))

    step = shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=(P(), P()),
    )

    def cond(carry):
        centers, it, shift2 = carry
        return (it < max_iter) & (shift2 > tol2)

    def body(carry):
        centers, it, _ = carry
        sums, counts = step(X, mask, centers)
        new = jnp.where(counts[:, None] > 0, sums / counts[:, None], centers)
        shift2 = jnp.sum((new - centers) ** 2)
        if log:
            emit_jit_step(it, center_shift2=shift2)
        return new, it + 1, shift2

    inf = jnp.asarray(jnp.inf, X.dtype)
    centers, it, shift2 = jax.lax.while_loop(cond, body, (centers0, 0, inf))
    return centers, it, shift2


@track_program("kmeans.labels_inertia")
@jax.jit
def _labels_inertia(X, mask, centers):
    d2 = euclidean_distances_sq(X, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * mask)
    return labels, inertia


@jax.jit
def _cost_to_candidates(X, mask, cands, cand_valid):
    d2 = euclidean_distances_sq(X, cands)
    d2 = jnp.where(cand_valid[None, :] > 0, d2, jnp.inf)
    dmin = jnp.min(d2, axis=1) * mask
    return dmin, jnp.sum(dmin)


def _gumbel_keys(weights, key):
    """Gumbel-perturbed log-weights: top-l of these keys IS a weighted
    sample of l items without replacement (P ∝ weights)."""
    g = jax.random.gumbel(key, weights.shape, dtype=jnp.float32)
    return jnp.where(weights > 0, jnp.log(weights) + g, -jnp.inf)


@partial(jax.jit, static_argnames=("l",))
def _gumbel_top_l(weights, key, l):
    """Indices of l draws without replacement with prob ∝ weights."""
    _, idx = jax.lax.top_k(_gumbel_keys(weights, key), l)
    return idx


@jax.jit
def _candidate_weights(X, mask, cands, cand_valid):
    d2 = euclidean_distances_sq(X, cands)
    d2 = jnp.where(cand_valid[None, :] > 0, d2, jnp.inf)
    labels = jnp.argmin(d2, axis=1)
    return jax.ops.segment_sum(mask, labels, num_segments=cands.shape[0])


# -- streamed (out-of-core) kernels ----------------------------------------
# Host X (np.memmap / big ndarray) streams through BlockStream; each
# kernel returns the per-block partial sums the in-memory while_loop
# computes on the resident array, accumulated across blocks on device.
# The reference's analog IS its normal mode: per-chunk tasks +
# tree-reduce (SURVEY.md §3.1). One Lloyd iteration = one pass.

@track_program("kmeans.stream.block_assign")
@partial(jax.jit, static_argnames=("mxu_dtype",))
def _block_assign_stats(X, mask, centers, mxu_dtype=None):
    """(Σ_block x per label, count per label, Σ_block min-dist²).
    ``mxu_dtype``: same bf16 distance-matmul policy as ``_lloyd_run``;
    stats stay f32."""
    k = centers.shape[0]
    d2 = euclidean_distances_sq(X, centers, mxu_dtype=mxu_dtype)
    labels = jnp.argmin(d2, axis=1)
    sums = jax.ops.segment_sum(X * mask[:, None], labels, num_segments=k)
    counts = jax.ops.segment_sum(mask, labels, num_segments=k)
    inertia = jnp.sum(jnp.min(d2, axis=1) * mask)
    return sums, counts, inertia


@jax.jit
def _block_moments(X, mask):
    return jnp.tensordot(mask, X, axes=(0, 0)), \
        jnp.tensordot(mask, X * X, axes=(0, 0))


@plan_tracked("superblock.kmeans_assign")
@partial(jax.jit, static_argnames=("mxu_dtype",), donate_argnums=(0,))
def _sb_assign_stats(acc, Xs, counts, centers, mxu_dtype=None):
    """Super-block Lloyd pass (ISSUE 3): scan the (K, S, d) stack
    through the per-block assign+update kernel, accumulating
    (sums, counts, inertia) in a DONATED carry — one dispatch per K
    blocks; all-padding slots (counts == 0) contribute zero. ``Xs`` may
    be a K-tuple of blocks (the CPU layout, see
    ``streaming.superblock_unrolled``): the chain unrolls at trace time
    into the same single program."""
    unrolled = isinstance(Xs, (tuple, list))
    r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])

    def step(acc, X, c):
        mask = (r < c).astype(X.dtype)
        s, cnt, i = _block_assign_stats.__wrapped__(
            X, mask, centers, mxu_dtype=mxu_dtype
        )
        return (acc[0] + s, acc[1] + cnt, acc[2] + i)

    if unrolled:
        for j in range(len(Xs)):
            acc = step(acc, Xs[j], counts[j])
        return acc

    def scan_step(acc, inp):
        return step(acc, *inp), jnp.float32(0.0)

    acc, _ = jax.lax.scan(scan_step, acc, (Xs, counts))
    return acc


import functools as _ft


@_ft.lru_cache(maxsize=16)
def _sb_assign_stats_sharded(mesh, mxu_dtype=None, fused=False,
                             interpret=False):
    """Data-parallel flavor of :func:`_sb_assign_stats` (ISSUE 9): the
    K-step assign+accumulate scan runs under ``shard_map`` over the
    stream mesh's "data" axis — each device scans only its own row slab
    of every block (local masks from the per-shard valid-row counts),
    the (sums, counts, inertia) carry stays REPLICATED, and the whole
    super-block pays exactly ONE ``lax.psum`` over "data" to fold the
    local delta into the running carry. Donated at the jit level like
    the single-device flavor.

    ``fused=True`` (ISSUE 12): each shard's block stats come from the
    fused Pallas assign-and-accumulate kernel running INSIDE the
    shard_map on its own (S/D, d) slab — one VMEM pass per block where
    the XLA body reads X twice — with the identical single psum per
    super-block; tracked as ``pallas.kmeans_stream.psum``. The
    replication checker is disabled on the fused trace only
    (pallas_call has no replication rule)."""
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS, data_shard_spec as spec_of

    if fused:
        from ..ops.pallas_fused import fused_kmeans_block_stats

    def body(acc, Xs, counts, centers):
        unrolled = isinstance(Xs, (tuple, list))
        r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])
        cts = counts[0]
        local = jax.tree.map(jnp.zeros_like, acc)

        def step(lacc, X, c):
            if fused:
                s, cnt, i = fused_kmeans_block_stats(
                    X, c, centers, mxu=mxu_dtype, interpret=interpret
                )
            else:
                mask = (r < c).astype(X.dtype)
                s, cnt, i = _block_assign_stats.__wrapped__(
                    X, mask, centers, mxu_dtype=mxu_dtype
                )
            return (lacc[0] + s, lacc[1] + cnt, lacc[2] + i)

        if unrolled:
            for j in range(len(Xs)):
                local = step(local, Xs[j], cts[j])
        else:
            def scan_step(lacc, inp):
                return step(lacc, *inp), jnp.float32(0.0)

            local, _ = jax.lax.scan(scan_step, local, (Xs, cts))
        local = jax.lax.psum(local, DATA_AXIS)
        return tuple(a + l for a, l in zip(acc, local))

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, Xs, counts, centers):
        unrolled = isinstance(Xs, (tuple, list))
        xs_spec = tuple(spec_of(a, 0) for a in Xs) if unrolled \
            else spec_of(Xs, 1)
        f = shard_map(
            body, mesh,
            in_specs=(P(), xs_spec, P(DATA_AXIS, None), P()),
            out_specs=P(),
            check_vma=False if fused else None,
        )
        return f(acc, Xs, counts, centers)

    name = ("pallas.kmeans_stream.psum" if fused
            else "superblock.kmeans_assign.psum")
    return plan_tracked(name, run)


def _sparse_block_assign_stats(db, cb, rb, c, centers, S):
    """(Σ x per label, count per label, Σ min-dist²) of one bucketed-nnz
    sparse block (ISSUE 13): distances via the expanded form with the
    x·c matmul and ||x||² computed from the nnz alone
    (ops/sparse_kernels), label-bucketed feature sums as one flat
    segment_sum — nnz·k cost, no (S, d) densification."""
    from ..ops.sparse_kernels import (sparse_center_dots,
                                      sparse_label_sums, sparse_sq_norms)

    k = centers.shape[0]
    mask = (jnp.arange(S) < c).astype(jnp.float32)
    xx = sparse_sq_norms(db, rb, S)
    cc = jnp.sum(centers * centers, axis=1)[None, :]
    d2 = jnp.maximum(
        xx[:, None] + cc - 2.0 * sparse_center_dots(db, cb, rb, centers,
                                                    S),
        0.0,
    )
    labels = jnp.argmin(d2, axis=1)
    sums = sparse_label_sums(db, cb, rb, labels, k, centers.shape[1])
    counts = jax.ops.segment_sum(mask, labels, num_segments=k)
    inertia = jnp.sum(jnp.min(d2, axis=1) * mask)
    return sums, counts, inertia


@_ft.lru_cache(maxsize=16)
def _sb_assign_stats_sparse(S, mesh=None):
    """Sparse flavor of :func:`_sb_assign_stats`: the K-step
    assign+accumulate scan over bucketed-nnz COO stacks with the same
    donated (sums, counts, inertia) carry — one dispatch per
    super-block, zero compiles after pass 1. ``mesh`` selects the
    shard_map flavor (each device scans its own nnz segments/local row
    ids; ONE psum per super-block, the dense sharded flavor's exact
    collective shape)."""
    S = int(S)

    if mesh is None:
        @partial(jax.jit, donate_argnums=(0,))
        def run(acc, data, cols, rows, counts, centers):
            def scan_step(acc, inp):
                db, cb, rb, c = inp
                s, cnt, i = _sparse_block_assign_stats(db, cb, rb, c,
                                                       centers, S)
                return (acc[0] + s, acc[1] + cnt, acc[2] + i), \
                    jnp.float32(0.0)

            acc, _ = jax.lax.scan(scan_step, acc,
                                  (data, cols, rows, counts))
            return acc

        return plan_tracked("superblock.sparse.kmeans_assign", run)

    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..parallel.mesh import DATA_AXIS

    def body(acc, data, cols, rows, counts, centers):
        cts = counts[0]
        local = jax.tree.map(jnp.zeros_like, acc)

        def scan_step(lacc, inp):
            db, cb, rb, c = inp
            s, cnt, i = _sparse_block_assign_stats(db, cb, rb, c,
                                                   centers, S)
            return (lacc[0] + s, lacc[1] + cnt, lacc[2] + i), \
                jnp.float32(0.0)

        local, _ = jax.lax.scan(scan_step, local,
                                (data, cols, rows, cts))
        local = jax.lax.psum(local, DATA_AXIS)
        return tuple(a + l for a, l in zip(acc, local))

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, data, cols, rows, counts, centers):
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(None, DATA_AXIS), P(DATA_AXIS, None), P()),
            out_specs=P(),
        )
        return f(acc, data, cols, rows, counts, centers)

    return plan_tracked("superblock.sparse.kmeans_assign.psum", run)


@plan_tracked("pallas.kmeans_stream")
@partial(jax.jit, static_argnames=("mxu_dtype", "interpret"),
        donate_argnums=(0,))
def _sb_assign_stats_pallas(acc, Xs, counts, centers, mxu_dtype=None,
                            interpret=False):
    """Pallas flavor of :func:`_sb_assign_stats` (ISSUE 8): each scan
    step is the fused assign-and-accumulate kernel — X streams through
    VMEM ONCE per block (the XLA flavor reads it twice: distance matmul
    + segment_sum) and only (tile, k) distances ever materialize.
    Selected by ``_streamed_lloyd`` on real TPU when the block shape
    fits ``kmeans_stream_tile``; parity within float tolerance
    (tests/test_precision.py)."""
    from ..ops.pallas_fused import fused_kmeans_block_stats

    unrolled = isinstance(Xs, (tuple, list))

    def step(acc, X, c):
        s, cnt, i = fused_kmeans_block_stats(
            X, c, centers, mxu=mxu_dtype, interpret=interpret
        )
        return (acc[0] + s, acc[1] + cnt, acc[2] + i)

    if unrolled:
        for j in range(len(Xs)):
            acc = step(acc, Xs[j], counts[j])
        return acc

    def scan_step(acc, inp):
        return step(acc, *inp), jnp.float32(0.0)

    acc, _ = jax.lax.scan(scan_step, acc, (Xs, counts))
    return acc


@partial(jax.jit, static_argnames=("l",))
def _block_weighted_topl(X, weights, key, l):
    """Per-block Gumbel top-l: (keys, rows). Global weighted sampling
    without replacement = top-l of the per-block top-l keys (the Gumbel
    keys are independent across blocks), so blocks merge exactly."""
    kv, idx = jax.lax.top_k(_gumbel_keys(weights, key), l)
    return kv, jnp.take(X, idx, axis=0)


def _proc_key(key, b):
    """Per-block Gumbel key, decorrelated ACROSS processes — identical
    key sequences on every process would correlate the sampling noise of
    different shards' rows. Nested fold_in (not an offset, which would
    collide past the offset's stride)."""
    from ..parallel import distributed as dist

    pid = dist.process_index()
    pkey = key if pid == 0 else jax.random.fold_in(key, 1_000_000 + pid)
    return jax.random.fold_in(pkey, b)


def _global_topl(kvs, rows, l):
    """Top-l rows by Gumbel key across ALL processes: local top-l pads
    to fixed l (−inf keys), one allgather, re-top — the exact global
    weighted sample, identical on every process (the Gumbel top-l merge
    is associative)."""
    from ..parallel import distributed as dist

    top = np.argsort(-kvs)[:l]
    top = top[np.isfinite(kvs[top])]
    if dist.process_count() == 1:
        return rows[top]
    d = rows.shape[1]
    kv_p = np.full(l, -np.inf, np.float32)
    kv_p[: top.size] = kvs[top]
    rw_p = np.zeros((l, d), np.float32)
    rw_p[: top.size] = rows[top]
    kv_all = dist.allgather_host(kv_p).ravel()
    rw_all = dist.allgather_host(rw_p).reshape(-1, d)
    t = np.argsort(-kv_all)[:l]
    t = t[np.isfinite(kv_all[t])]
    return rw_all[t]


def _streamed_sample(stream, weights_fn, key, l):
    """Draw l rows without replacement, P ∝ weights_fn(block), across a
    BlockStream — across every process's stream under a live multi-host
    runtime. Returns (≤l, d) host-merged rows, identical everywhere."""
    kvs, rows = [], []
    for b, blk in enumerate(stream):
        Xb = blk.arrays[0]
        w = weights_fn(blk)
        lb = min(l, Xb.shape[0])
        kv, r = _block_weighted_topl(Xb, w, _proc_key(key, b), lb)
        kvs.append(np.asarray(kv))
        rows.append(np.asarray(r))
    kvs = np.concatenate(kvs)
    rows = np.concatenate(rows, axis=0)
    return _global_topl(kvs, rows, l)


class _LloydCheckpoint:
    """Mid-run Lloyd checkpointing (SURVEY.md §5 checkpoint row): saves
    (centers, it) every k iterations under an IDENTITY TOKEN — a stale
    checkpoint from a different fit (other data, init, budget, shapes)
    is ignored rather than silently resumed, the same contract as the
    adaptive-search checkpoints (_incremental.py). Cleared on
    completion."""

    def __init__(self, path, every, token, k, d):
        self.path = path
        self.every = int(every)
        self.token = np.frombuffer(token.encode()[:40].ljust(40), np.uint8)
        self.k, self.d = k, d

    def restore(self):
        """(centers, it) if a matching checkpoint exists, else None."""
        from ..utils import checkpoint as ckpt

        # checkpoint_exists covers the atomic writer's crash window
        # (state parked at <path>.old after a kill mid-publish)
        if not ckpt.checkpoint_exists(self.path):
            return None
        like = {"token": np.zeros(40, np.uint8),
                "centers": jnp.zeros((self.k, self.d), jnp.float32),
                "it": 0}
        try:
            state = ckpt.restore_pytree(self.path, like=like)
        except Exception:
            return None  # different shapes = different fit: start fresh
        if not np.array_equal(np.asarray(state["token"]), self.token):
            return None
        return jnp.asarray(np.asarray(state["centers"])), int(state["it"])

    def save(self, centers, it):
        from ..utils import checkpoint as ckpt

        ckpt.save_pytree(self.path, {
            "token": self.token, "centers": centers, "it": it,
        })

    def clear(self):
        import os
        import shutil

        for suffix in ("", ".old", ".tmp"):
            shutil.rmtree(os.path.abspath(self.path) + suffix,
                          ignore_errors=True)


def _streamed_lloyd(stream, centers0, max_iter, tol2, logger=None,
                    ckpt=None, start_it=0, fit_dtype=None):
    """Host-loop Lloyd over streamed blocks; ``ckpt`` (a
    _LloydCheckpoint) persists every k passes so a killed multi-hour fit
    resumes mid-run, and clears on completion."""
    from ..config import mxu_dtype
    from ..parallel import distributed as dist

    mxu = mxu_dtype(fit_dtype)
    multi = dist.process_count() > 1
    centers = jnp.asarray(centers0)
    n_iter = start_it
    use_sb = hasattr(stream, "use_superblocks") and stream.use_superblocks()
    from ..observability import record_superblock_donation

    # fused Pallas scan flavor (one VMEM pass per block) when opted in
    # (real TPU, or interpret mode via pallas_stream_interpret) and the
    # PER-SHARD slab shape fits its grid — composed with the sharded
    # flavor by running inside its shard_map (ISSUE 12) — else the XLA
    # flavor, which with mxu=None traces byte-identically to the
    # pre-feature program
    from ..ops.pallas_fused import kmeans_stream_tile, stream_kernel_mode

    k0, d0 = jnp.asarray(centers0).shape
    sharded = bool(
        use_sb and getattr(stream, "sb_sharded", lambda: False)()
    )
    # bucketed-nnz sparse staging (ISSUE 13): assign-stats at nnz*k
    # cost through the superblock.sparse.kmeans_assign programs; the
    # fused Pallas flavor is a dense-slab feature and stays off
    sb_sparse = bool(
        use_sb and getattr(stream, "sb_sparse", lambda: False)()
    )
    use_k, interp = stream_kernel_mode()
    slab_rows = int(stream.block_rows) // (
        int(stream.sb_data_shards()) if sharded else 1
    )
    fused = bool(
        use_sb and use_k and not sb_sparse
        and kmeans_stream_tile(slab_rows, int(d0), int(k0)) is not None
    )
    sb_run = _sb_assign_stats_pallas if fused else _sb_assign_stats
    sparse_run = None
    if sb_sparse:
        sparse_run = _sb_assign_stats_sparse(
            slab_rows, mesh=stream.mesh if sharded else None
        )
    rep = None
    if sharded:
        # data-parallel flavor (ISSUE 9): one psum over "data" per
        # super-block; carry AND centers committed replicated so every
        # dispatch of the fit reuses one executable
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..config import resolve_dtype

        _, src = resolve_dtype(fit_dtype)
        if src.startswith("auto"):
            # mirror the resident auto-gate: under dtype="auto" the
            # single-device streamed flavor this displaces is the f32
            # Pallas kernel, so the sharded body stays f32 too —
            # bf16 distance assignments would put sharded-vs-single
            # parity at the mercy of argmin ties, not reassociation.
            # An EXPLICIT bfloat16 request is still honored
            mxu = None
        rep = NamedSharding(stream.mesh, P())
        centers = jax.device_put(centers, rep)
        sharded_run = _sb_assign_stats_sharded(stream.mesh, mxu,
                                               fused=fused,
                                               interpret=interp)

    for it in range(start_it, int(max_iter)):
        if use_sb:
            # the streamed hot loop as donated-carry super-block scans:
            # one dispatch per K blocks instead of K
            k_clusters, d = centers.shape
            acc = (jnp.zeros((k_clusters, d), jnp.float32),
                   jnp.zeros((k_clusters,), jnp.float32),
                   jnp.zeros((), jnp.float32))
            acc_bytes = 4 * (k_clusters * d + k_clusters + 1)
            if sb_sparse:
                if sharded:
                    acc = jax.device_put(acc, rep)
                for sb in stream.superblocks():
                    slab = sb.arrays[0]
                    cts = sb.shard_counts if sharded else sb.counts
                    acc = sparse_run(acc, slab.data, slab.cols,
                                     slab.rows, cts, centers)
                    record_superblock_donation(acc_bytes)
            elif sharded:
                acc = jax.device_put(acc, rep)
                for sb in stream.superblocks():
                    acc = sharded_run(acc, sb.arrays[0],
                                      sb.shard_counts, centers)
                    record_superblock_donation(acc_bytes)
            elif fused:
                for sb in stream.superblocks():
                    acc = sb_run(acc, sb.arrays[0], sb.counts,
                                 centers, mxu_dtype=mxu,
                                 interpret=interp)
                    record_superblock_donation(acc_bytes)
            else:
                for sb in stream.superblocks():
                    acc = sb_run(acc, sb.arrays[0], sb.counts,
                                 centers, mxu_dtype=mxu)
                    record_superblock_donation(acc_bytes)
            sums, counts, inertia = acc
        else:
            sums = counts = inertia = None
            for blk in stream:
                s, c, i = _block_assign_stats(blk.arrays[0], blk.mask,
                                              centers, mxu_dtype=mxu)
                sums = s if sums is None else sums + s
                counts = c if counts is None else counts + c
                inertia = i if inertia is None else inertia + i
        if multi:
            # per-process block stats → global (bit-identical on every
            # process, so centers never diverge across hosts)
            sums, counts, inertia = (
                jnp.asarray(np.asarray(a, np.float32)) for a in
                dist.psum_host(np.asarray(sums, np.float64),
                               np.asarray(counts, np.float64),
                               np.asarray(inertia, np.float64))
            )
        new = jnp.where(counts[:, None] > 0, sums / counts[:, None], centers)
        shift2 = float(jnp.sum((new - centers) ** 2))
        centers = new
        n_iter = it + 1
        if logger is not None:
            logger.log(step=it, inertia=float(inertia), center_shift2=shift2)
        if ckpt is not None and n_iter % ckpt.every == 0:
            # (multi-host passes ckpt=None — see _fit_streamed)
            ckpt.save(centers, n_iter)
        if shift2 <= tol2:
            break
    if ckpt is not None:
        ckpt.clear()
    return centers, n_iter


def init_scalable_streamed(stream, n_clusters, random_state, max_iter=None,
                           oversampling_factor=2):
    """k-means‖ over streamed blocks: the same fixed-budget Gumbel top-l
    rounds as ``init_scalable``, with each round's cost/sampling pass
    running block-by-block and merging exactly (see _block_weighted_topl)."""
    from sklearn.cluster import KMeans as SkKMeans

    l = max(int(oversampling_factor * n_clusters), 1)
    key = jax.random.PRNGKey(0 if random_state is None else int(random_state))
    key, k0 = jax.random.split(key)
    first = _streamed_sample(stream, lambda blk: blk.mask, k0, 1)
    cands_list = [first]
    rounds = 5 if max_iter is None else max(int(max_iter), 1)
    for r in range(rounds):
        cands = jnp.asarray(np.concatenate(cands_list, axis=0))
        valid = jnp.ones((cands.shape[0],), jnp.float32)
        key, kr = jax.random.split(key)
        phi = 0.0
        kvs, rows = [], []
        for b, blk in enumerate(stream):
            Xb = blk.arrays[0]
            dmin, phi_b = _cost_to_candidates(Xb, blk.mask, cands, valid)
            phi += float(phi_b)
            lb = min(l, Xb.shape[0])
            kv, rw = _block_weighted_topl(Xb, dmin, _proc_key(kr, b), lb)
            kvs.append(np.asarray(kv))
            rows.append(np.asarray(rw))
        from ..parallel import distributed as dist

        phi = float(dist.psum_host(np.asarray(phi)))  # global cost
        if phi <= 0.0:
            break
        kvs = np.concatenate(kvs)
        rows = np.concatenate(rows, axis=0)
        picked = _global_topl(kvs, rows, l)
        if len(picked):
            cands_list.append(picked)
    cands_h = np.concatenate(cands_list, axis=0)
    cands = jnp.asarray(cands_h)
    valid = jnp.ones((cands.shape[0],), jnp.float32)
    weights = None
    for blk in stream:
        w = _candidate_weights(blk.arrays[0], blk.mask, cands, valid)
        weights = w if weights is None else weights + w
    from ..parallel import distributed as dist

    w_h = np.asarray(dist.psum_host(np.asarray(weights, np.float64)))
    w_h = np.where(w_h > 0, w_h, 1e-6)
    # DETERMINISTIC seed even when random_state is None: the candidate
    # sampling above already pins PRNGKey(0) in that case, and under
    # multi-host every process must reduce the (identical) candidate set
    # to the IDENTICAL centers — an unseeded draw would diverge them
    local = SkKMeans(
        n_clusters=n_clusters, init="k-means++", n_init=1,
        random_state=0 if random_state is None else int(random_state),
    ).fit(cands_h, sample_weight=w_h)
    return jnp.asarray(local.cluster_centers_, cands.dtype)


def init_scalable(X: ShardedArray, n_clusters, random_state, max_iter=None,
                  oversampling_factor=2):
    """k-means‖ candidate harvesting; ref
    dask_ml/cluster/k_means.py::init_scalable."""
    from sklearn.cluster import KMeans as SkKMeans

    data, mask = X.data, X.row_mask(X.dtype)
    n, d = X.shape
    n_pad = data.shape[0]
    # top_k needs l <= array length; tiny datasets clamp the oversample
    l = min(max(int(oversampling_factor * n_clusters), 1), n_pad)
    key = jax.random.PRNGKey(0 if random_state is None else int(random_state))

    # step 1: one uniform-random valid row
    key, k0 = jax.random.split(key)
    first = data[_gumbel_top_l(mask, k0, 1)[0]]

    # candidate buffer with static shape (SURVEY.md §7 hard parts)
    if max_iter is None:
        # rounds ≈ log(phi); phi ≤ n * max_dist² — 5 is the practical
        # regime for sane data, matching the reference's few-round behavior
        rounds = 5
    else:
        rounds = max(int(max_iter), 1)
    c_max = 1 + rounds * l
    cands = jnp.zeros((c_max, d), data.dtype).at[0].set(first)
    cand_valid = jnp.zeros((c_max,), jnp.float32).at[0].set(1.0)

    for r in range(rounds):
        dmin, phi = _cost_to_candidates(data, mask, cands, cand_valid)
        if float(phi) <= 0.0:
            break
        key, kr = jax.random.split(key)
        idx = _gumbel_top_l(dmin, kr, l)
        rows = jnp.take(data, idx, axis=0)
        start = 1 + r * l
        cands = jax.lax.dynamic_update_slice(cands, rows, (start, 0))
        cand_valid = jax.lax.dynamic_update_slice(
            cand_valid, jnp.ones((l,), jnp.float32), (start,)
        )

    weights = _candidate_weights(data, mask, cands, cand_valid)
    cands_h = to_host(cands)
    valid_h = to_host(cand_valid) > 0
    w_h = to_host(weights)[valid_h]
    pts = cands_h[valid_h]
    w_h = np.where(w_h > 0, w_h, 1e-6)
    local = SkKMeans(
        n_clusters=n_clusters, init="k-means++", n_init=1,
        random_state=None if random_state is None else int(random_state),
    ).fit(pts, sample_weight=w_h)
    return jnp.asarray(local.cluster_centers_, data.dtype)


def init_pp(X: ShardedArray, n_clusters, random_state):
    """k-means++ on a device-drawn uniform sample (ref ::init_pp)."""
    from sklearn.cluster import kmeans_plusplus

    data, mask = X.data, X.row_mask(X.dtype)
    m = min(X.n_rows, max(10 * n_clusters, 500), data.shape[0])
    key = jax.random.PRNGKey(1 if random_state is None else int(random_state))
    idx = _gumbel_top_l(mask, key, m)
    sample = to_host(jnp.take(data, idx, axis=0))
    centers, _ = kmeans_plusplus(
        sample, n_clusters,
        random_state=None if random_state is None else int(random_state),
    )
    return jnp.asarray(centers, data.dtype)


def init_random(X: ShardedArray, n_clusters, random_state):
    data, mask = X.data, X.row_mask(X.dtype)
    key = jax.random.PRNGKey(2 if random_state is None else int(random_state))
    idx = _gumbel_top_l(mask, key, n_clusters)
    return jnp.take(data, idx, axis=0)


def k_means(X, n_clusters, init="k-means||", max_iter=300, tol=1e-4,
            random_state=None, oversampling_factor=2, init_max_iter=None,
            return_n_iter=False):
    """Functional API (ref: dask_ml/cluster/k_means.py::k_means):
    returns (centroids, labels, inertia[, n_iter])."""
    est = KMeans(
        n_clusters=n_clusters, init=init, max_iter=max_iter, tol=tol,
        random_state=random_state, oversampling_factor=oversampling_factor,
        init_max_iter=init_max_iter,
    ).fit(X)
    if return_n_iter:
        return est.cluster_centers_, est.labels_, est.inertia_, est.n_iter_
    return est.cluster_centers_, est.labels_, est.inertia_


class KMeans(TransformerMixin, ClusterMixin, BaseEstimator):
    """Ref: dask_ml/cluster/k_means.py::KMeans."""

    def __init__(self, n_clusters=8, init="k-means||", oversampling_factor=2,
                 max_iter=300, tol=1e-4, precompute_distances="auto",
                 random_state=None, copy_x=True, n_jobs=1, algorithm="full",
                 init_max_iter=None, use_pallas=None, checkpoint_path=None,
                 checkpoint_every=0, fit_dtype=None):
        self.n_clusters = n_clusters
        self.init = init
        self.oversampling_factor = oversampling_factor
        self.max_iter = max_iter
        self.tol = tol
        self.precompute_distances = precompute_distances
        self.random_state = random_state
        self.copy_x = copy_x
        self.n_jobs = n_jobs
        self.algorithm = algorithm
        self.init_max_iter = init_max_iter
        self.use_pallas = use_pallas
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        # per-estimator precision override (None = config.dtype policy;
        # "float32" opts out of the TPU bf16 default, "bfloat16" forces
        # it); resolved choice lands on fit_dtype_
        self.fit_dtype = fit_dtype

    def _init_centers(self, X: ShardedArray):
        if isinstance(self.init, np.ndarray) or isinstance(
            self.init, jnp.ndarray
        ):
            centers = jnp.asarray(self.init, X.dtype)
            if centers.shape != (self.n_clusters, X.shape[1]):
                raise ValueError(
                    f"init array has shape {centers.shape}, expected "
                    f"{(self.n_clusters, X.shape[1])}"
                )
            return centers
        if self.init == "k-means||":
            return init_scalable(X, self.n_clusters, self.random_state,
                                 self.init_max_iter, self.oversampling_factor)
        if self.init == "k-means++":
            return init_pp(X, self.n_clusters, self.random_state)
        if self.init == "random":
            return init_random(X, self.n_clusters, self.random_state)
        raise ValueError(f"Unknown init {self.init!r}")

    def _make_ckpt(self, X, n, d):
        """A _LloydCheckpoint when the knobs are set, else None. The
        identity token covers the init CONFIG (not the computed centers —
        resume must be able to skip init), the budget, and a data-content
        fingerprint."""
        if not (self.checkpoint_path and self.checkpoint_every):
            return None
        import hashlib

        from ..utils.validation import data_fingerprint

        if isinstance(self.init, (np.ndarray, jnp.ndarray)):
            init_piece = hashlib.sha1(np.ascontiguousarray(
                np.asarray(self.init, np.float32)).tobytes()).hexdigest()
        else:
            init_piece = f"{self.init}|{self.random_state}|"                          f"{self.oversampling_factor}|{self.init_max_iter}"
        token = hashlib.sha1("|".join([
            init_piece, str(self.n_clusters), str(n), str(d),
            str(self.max_iter), str(self.tol), data_fingerprint(X),
        ]).encode()).hexdigest()
        return _LloydCheckpoint(self.checkpoint_path, self.checkpoint_every,
                                token, self.n_clusters, d)

    def _init_centers_streamed(self, stream, n_features):
        if isinstance(self.init, (np.ndarray, jnp.ndarray)):
            centers = jnp.asarray(self.init, jnp.float32)
            if centers.shape != (self.n_clusters, n_features):
                raise ValueError(
                    f"init array has shape {centers.shape}, expected "
                    f"{(self.n_clusters, n_features)}"
                )
            return centers
        if self.init == "k-means||":
            return init_scalable_streamed(
                stream, self.n_clusters, self.random_state,
                self.init_max_iter, self.oversampling_factor,
            )
        seed_base = {"k-means++": 1, "random": 2}
        if self.init in seed_base:
            key = jax.random.PRNGKey(
                seed_base[self.init] if self.random_state is None
                else int(self.random_state)
            )
            if self.init == "random":
                return jnp.asarray(_streamed_sample(
                    stream, lambda blk: blk.mask, key, self.n_clusters
                ))
            from sklearn.cluster import kmeans_plusplus

            from ..parallel import distributed as dist

            # GLOBAL row count sizes the sample so every process's
            # _global_topl allgather payload has the same shape; the
            # deterministic seed keeps centers0 identical everywhere
            # (same rule as init_scalable_streamed)
            n_glob = int(dist.psum_host(np.asarray(float(stream.n_rows))))
            m = min(n_glob, max(10 * self.n_clusters, 500))
            sample = _streamed_sample(stream, lambda blk: blk.mask, key, m)
            centers, _ = kmeans_plusplus(
                sample, self.n_clusters,
                random_state=0 if self.random_state is None
                else int(self.random_state),
            )
            return jnp.asarray(centers, jnp.float32)
        raise ValueError(f"Unknown init {self.init!r}")

    def _fit_streamed(self, X, block_rows):
        """Out-of-core Lloyd: X stays host-resident (np.memmap / large
        ndarray); every pass streams fixed-shape blocks through the
        per-block assign+update kernel and accumulates (sums, counts) on
        device — the reference's per-chunk tasks + tree-reduce shape
        (SURVEY.md §3.1) without materializing X in HBM. ``labels_`` is a
        host int32 array (X's own size /(4·d) — small next to X)."""
        from ..parallel.streaming import BlockStream
        from ..observability import fit_logger

        n_local, d = X.shape
        from ..config import fit_dtype_info
        from ..parallel import distributed as dist

        # resolved precision on record (auto falls back to f32 off-TPU)
        self.fit_dtype_ = fit_dtype_info(self.fit_dtype)["fit_dtype"]
        multi = dist.process_count() > 1
        # multi-host: X is the process-local memmap shard; every global
        # statistic (n, variance, Lloyd stats, inertia, the k-means||
        # sampling) merges over the psum/allgather plane
        n = int(dist.psum_host(np.asarray(float(n_local)))) if multi \
            else n_local
        if self.n_clusters > n:
            raise ValueError(
                f"n_clusters={self.n_clusters} > n_samples={n}"
            )
        stream = BlockStream((X,), block_rows=block_rows)
        # sklearn-style tol scaling needs the global per-feature variance:
        # one moments pass
        s = ss = None
        for blk in stream:
            bs, bss = _block_moments(blk.arrays[0], blk.mask)
            s = bs if s is None else s + bs
            ss = bss if ss is None else ss + bss
        if multi:
            s, ss = (np.asarray(a) for a in dist.psum_host(
                np.asarray(s, np.float64), np.asarray(ss, np.float64)
            ))
        mean = s / n
        var = ss / n - mean * mean
        tol2 = float(self.tol * jnp.mean(jnp.asarray(var)))
        # multi-host checkpointing is OFF: resume must be a COLLECTIVE
        # decision (a coordinator-only resume would desync every
        # process's collective schedule); needs shared-FS coordination
        ckpt = None if multi else self._make_ckpt(X, n, d)
        resume = ckpt.restore() if ckpt is not None else None
        if resume is not None:
            # resume SKIPS init entirely — k-means|| costs ~10 full
            # passes over an out-of-core dataset
            centers0, start_it = resume
        else:
            with span("kmeans.init", streamed=True, init=str(self.init)):
                centers0, start_it = (
                    self._init_centers_streamed(stream, d), 0
                )
        with span("fit", component="KMeans", streamed=True, n_rows=n,
                  n_clusters=self.n_clusters) as sp, \
                fit_logger("KMeans", streamed=True, n_rows=n,
                           n_clusters=self.n_clusters) as logger:
            centers, n_iter = _streamed_lloyd(
                stream, centers0, self.max_iter, tol2, logger=logger,
                ckpt=ckpt, start_it=start_it, fit_dtype=self.fit_dtype,
            )
            sp.add(n_iter=int(n_iter))
        labels = np.empty(n_local, np.int32)  # labels stay process-local
        inertia = 0.0
        cursor = 0
        for blk in stream:
            lb, ib = _labels_inertia(blk.arrays[0], blk.mask, centers)
            m = blk.n_rows
            labels[cursor:cursor + m] = np.asarray(lb)[:m]
            inertia += float(ib)
            cursor += m
        if multi:
            inertia = float(dist.psum_host(np.asarray(inertia)))
        if not np.isfinite(inertia) or \
                not bool(jnp.isfinite(centers).all()):
            raise FloatingPointError(
                "KMeans produced non-finite centers/inertia: the input "
                "contains NaN/Inf"
            )
        self.cluster_centers_ = np.asarray(centers)
        self.labels_ = labels
        self.inertia_ = inertia
        self.n_iter_ = int(n_iter)
        self.n_features_in_ = d
        # per-feature training profile for train-vs-serve drift scoring
        self.training_profile_ = stream.profile_snapshot()
        return self

    def fit(self, X, y=None):
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._fit_streamed(X, block_rows)
        X = check_array(X, dtype=np.float32)
        if self.n_clusters > X.n_rows:
            raise ValueError(
                f"n_clusters={self.n_clusters} > n_samples={X.n_rows}"
            )
        mask = X.row_mask(X.dtype)
        centers0 = self._init_centers(X)
        # sklearn-style tol scaling: tol * mean per-feature variance
        _, var = masked_mean_var(X.data, mask, X.n_rows)
        tol2 = jnp.asarray(self.tol, X.dtype) * jnp.mean(var)
        from ..config import fit_dtype_info, mxu_dtype as _mxu_dtype

        dt_info = fit_dtype_info(self.fit_dtype)
        auto_pol = dt_info["fit_dtype_source"].startswith("auto")
        mxu = _mxu_dtype(self.fit_dtype)
        use_pallas = self.use_pallas
        if use_pallas is None:
            # auto: fused kernel on real TPU only — an EXPLICIT bf16
            # request routes to the XLA distance path instead (the
            # resident Pallas kernel's VMEM tiling is f32); under the
            # default "auto" policy the f32 Pallas kernel keeps
            # priority — one X pass per Lloyd iteration beats a bf16
            # cross-term at this arithmetic intensity
            use_pallas = jax.default_backend() == "tpu" \
                and (mxu is None or auto_pol)
        elif use_pallas and mxu is not None and not auto_pol:
            import warnings

            warnings.warn(
                "KMeans(use_pallas=True) runs the f32 Pallas kernel; "
                "config.dtype='bfloat16' is ignored on this path",
                RuntimeWarning,
            )
        if use_pallas and mxu is not None:
            mxu = None
            dt_info = {"fit_dtype": "float32",
                       "fit_dtype_source": "pallas-resident"}
        self.fit_dtype_ = dt_info["fit_dtype"]
        from ..observability import (
            active_logger, fit_logger, jit_callbacks_supported,
        )

        with span("fit", component="KMeans", n_rows=X.n_rows,
                  n_clusters=self.n_clusters) as sp, \
                fit_logger("KMeans", n_rows=X.n_rows,
                           n_clusters=self.n_clusters) as logger, \
                active_logger(logger):
            # per-step callbacks need backend support (axon PJRT lacks
            # host callbacks); degrade to one summary record per fit
            log_steps = logger is not None and jit_callbacks_supported()

            # bf16 distance matmuls (XLA path only, see use_pallas
            # resolution above)
            mxu_dtype = None if use_pallas else mxu

            def run_lloyd(c0, iters):
                if use_pallas:
                    return _lloyd_run_pallas(
                        X.data, mask, c0, jnp.asarray(iters), tol2, X.mesh,
                        interpret=jax.default_backend() != "tpu",
                        log=log_steps,
                    )
                return _lloyd_run(
                    X.data, mask, c0, jnp.asarray(iters), tol2,
                    log=log_steps, mxu_dtype=mxu_dtype,
                )

            ckpt = self._make_ckpt(X, X.n_rows, X.shape[1])
            if ckpt is None:
                centers, n_iter, shift2 = run_lloyd(centers0, self.max_iter)
            else:
                # chunked while_loops: every k iterations the (centers,
                # it) state hits stable storage — the resident analog of
                # the streamed path's per-pass checkpointing
                resume = ckpt.restore()
                centers, n_iter = (resume if resume is not None
                                   else (centers0, 0))
                shift2 = jnp.asarray(jnp.inf, X.dtype)
                while n_iter < self.max_iter:
                    chunk = min(int(self.checkpoint_every),
                                self.max_iter - n_iter)
                    centers, it_c, shift2 = run_lloyd(centers, chunk)
                    n_iter += int(it_c)
                    ckpt.save(centers, n_iter)
                    if int(it_c) < chunk:
                        break  # converged inside the chunk
                ckpt.clear()
            sp.add(n_iter=int(n_iter))
            if logger is not None and not log_steps:
                logger.log(step=int(n_iter), center_shift2=float(shift2),
                           summary=True)
            # active_logger's exit runs jax.effects_barrier(), draining
            # the per-iteration callbacks before the sink unbinds
        labels, inertia = _labels_inertia(X.data, mask, centers)
        # NaN sanitizer (SURVEY.md §5): a NaN makes the tol while_loop
        # exit as "converged" (NaN comparisons are False) — check the
        # final inertia/centers instead of trusting convergence
        if not bool(jnp.isfinite(inertia)) or \
                not bool(jnp.isfinite(centers).all()):
            raise FloatingPointError(
                "KMeans produced non-finite centers/inertia: the input "
                "contains NaN/Inf"
            )
        self.cluster_centers_ = to_host(centers)
        self.labels_ = ShardedArray(labels, X.n_rows, X.mesh)
        self.inertia_ = float(inertia)
        self.n_iter_ = int(n_iter)
        self.n_features_in_ = X.shape[1]
        return self

    def predict(self, X):
        check_is_fitted(self, "cluster_centers_")
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:
            c = jnp.asarray(self.cluster_centers_, jnp.float32)
            return streamed_map(
                X, block_rows,
                lambda blk: _labels_inertia(blk.arrays[0], blk.mask, c)[0],
            )
        X = check_array(X, dtype=np.float32)
        centers = jnp.asarray(self.cluster_centers_, X.dtype)
        labels, _ = _labels_inertia(X.data, X.row_mask(X.dtype), centers)
        return ShardedArray(labels, X.n_rows, X.mesh)

    def fit_predict(self, X, y=None):
        return self.fit(X).labels_

    def transform(self, X):
        check_is_fitted(self, "cluster_centers_")
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:
            c = jnp.asarray(self.cluster_centers_, jnp.float32)
            return streamed_map(
                X, block_rows,
                lambda blk: euclidean_distances(blk.arrays[0], c),
            )
        X = check_array(X, dtype=np.float32)
        centers = jnp.asarray(self.cluster_centers_, X.dtype)
        d = euclidean_distances(X.data, centers)
        return ShardedArray(d, X.n_rows, X.mesh)

    def score(self, X, y=None):
        check_is_fitted(self, "cluster_centers_")
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:
            c = jnp.asarray(self.cluster_centers_, jnp.float32)
            per_block = streamed_map(
                X, block_rows,
                lambda blk: _labels_inertia(blk.arrays[0], blk.mask, c)[1][None],
            )
            return -float(per_block.sum())
        X = check_array(X, dtype=np.float32)
        centers = jnp.asarray(self.cluster_centers_, X.dtype)
        _, inertia = _labels_inertia(X.data, X.row_mask(X.dtype), centers)
        return -float(inertia)
