"""Distributed linear algebra: TSQR and randomized SVD.

Reference equivalent: ``dask/array/linalg.py::tsqr`` /
``svd_compressed`` (SURVEY.md §2b row 2 and §3.3) — the backbone of
PCA/TruncatedSVD/spectral embedding. The TPU design (SURVEY.md §7 B1):

- ``tsqr``: per-shard ``jnp.linalg.qr`` inside ``shard_map``, ``all_gather``
  of the small R factors over ICI, replicated second-stage QR. The reference
  builds the same two-level shape as a task graph with inter-worker shuffles;
  here it is one XLA program.
- ``randomized_svd``: Halko range-finder with power iterations, each pass a
  psum-reduced matmul; the final small SVD is replicated (the reference runs
  it on the client).

Inputs are *padded* row-sharded arrays whose padding rows are exactly zero
(zero rows leave R and the spanned range unchanged), so no masks are needed
here — callers zero padding, e.g. after mean-centering.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS

from .._compat import shard_map as _shard_map


def shard_map(f, mesh, in_specs, out_specs):
    # check_vma=False: we return all_gather/pmean results with replicated
    # out_specs, which the static replication checker cannot infer.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )


def tsqr(x: jax.Array, mesh: Mesh, axis_name: str = DATA_AXIS):
    """Tall-skinny QR of a row-sharded (n, d) array; n >> d required.

    Returns (Q, R): Q row-sharded (n, d) with orthonormal columns, R (d, d)
    replicated and upper-triangular.
    """
    d = x.shape[1]

    def _tsqr(xs):
        # reduced QR: local R is (r, d) with r = min(m, d), so shards with
        # fewer rows than columns still compose correctly
        q1, r1 = jnp.linalg.qr(xs)  # (m, r), (r, d)
        r = r1.shape[0]
        rs = jax.lax.all_gather(r1, axis_name)  # (S, r, d) over ICI
        s = rs.shape[0]
        q2, r_final = jnp.linalg.qr(rs.reshape(s * r, d))
        i = jax.lax.axis_index(axis_name)
        q2_i = jax.lax.dynamic_slice_in_dim(q2, i * r, r)
        return q1 @ q2_i, r_final

    return shard_map(
        _tsqr,
        mesh=mesh,
        in_specs=P(axis_name, None),
        out_specs=(P(axis_name, None), P()),
    )(x)


def svd_tall(x: jax.Array, mesh: Mesh):
    """Exact SVD of a tall-skinny row-sharded (n, d) array via TSQR.

    Reference: ``da.linalg.svd`` = tsqr + small SVD of R (SURVEY.md §3.3).
    Returns (U row-sharded (n, d), s (d,), Vt (d, d) replicated).
    """
    q, r = tsqr(x, mesh)
    u_r, s, vt = jnp.linalg.svd(r, full_matrices=False)
    return q @ u_r, s, vt


def randomized_range_finder(x, size, key, n_iter, mesh):
    """Orthonormal basis Q (n, size) approximately spanning range(x).

    Halko et al. 2011 randomized range finder with power iterations and
    QR re-orthonormalization each half-iteration, as in
    ``da.linalg.svd_compressed`` (SURVEY.md §3.3).
    """
    d = x.shape[1]
    omega = jax.random.normal(key, (d, size), dtype=x.dtype)
    y = x @ omega  # psum-reduced matmul pass
    q, _ = tsqr(y, mesh)
    for _ in range(n_iter):
        z = x.T @ q  # (d, size); XLA inserts the ICI reduction
        qz, _ = jnp.linalg.qr(z)  # replicated small QR
        y = x @ qz
        q, _ = tsqr(y, mesh)
    return q


def randomized_svd(x, n_components, key, mesh, n_oversamples=10, n_iter=4):
    """Halko randomized SVD of row-sharded (n, d) x.

    Returns (U (n, k) row-sharded, s (k,), Vt (k, d) replicated).
    """
    size = min(n_components + n_oversamples, min(x.shape))
    q = randomized_range_finder(x, size, key, n_iter, mesh)
    b = q.T @ x  # (size, d), psum-reduced second data pass
    u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ u_b
    k = n_components
    return u[:, :k], s[:k], vt[:k]


# Jitted entry points: the eager versions above dispatch one program per
# op — dozens of launches per SVD — which dominates wall clock on
# runtimes with high per-launch overhead (tunneled TPU). These compile
# the whole decomposition into one program; mesh/sizes are static.
# count_recompiles is identity when jax.monitoring tracks compiles; on
# runtimes without it, the wrapper counts jit-cache growth instead.
from ..observability import count_recompiles

svd_tall_jit = count_recompiles(jax.jit(svd_tall, static_argnums=(1,)))
randomized_svd_jit = count_recompiles(jax.jit(
    randomized_svd, static_argnums=(1, 3, 4, 5)
))


def svd_flip(u, vt):
    """Deterministic SVD signs, V-based (matches sklearn's
    ``svd_flip(u_based_decision=False)``): flip so each row of Vt has its
    largest-|.| entry positive. V-based avoids an argmax over the sharded
    row axis of U."""
    max_abs = jnp.argmax(jnp.abs(vt), axis=1)
    signs = jnp.sign(vt[jnp.arange(vt.shape[0]), max_abs])
    signs = jnp.where(signs == 0, 1.0, signs).astype(vt.dtype)
    return u * signs[None, :], vt * signs[:, None]
