"""Text classification at reference scale without a dense corpus.

HashingVectorizer produces a scipy CSR matrix at a width (2**18 here,
2**20 in dask-ml's default) whose dense form would not fit in memory.
Feeding the CSR straight to a streamed fit densifies ONE fixed-shape
block at a time into the prefetched device buffer — peak host/device
memory is O(block) at any n_features. The same corpus then trains an
Incremental(SGDClassifier) pass and scores with the device-resident
roc_auc scorer (no host gathers).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

import dask_ml_tpu.config as config
from dask_ml_tpu.feature_extraction.text import HashingVectorizer
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.metrics import roc_auc_score
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.wrappers import Incremental

N = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 20_000))

rng = np.random.RandomState(0)
vocab = [f"token{i}" for i in range(2000)]
docs, labels = [], []
for i in range(N):
    cls = i % 2
    lo = 0 if cls == 0 else 1000  # class-dependent vocabulary halves
    docs.append(" ".join(rng.choice(vocab[lo:lo + 1000], size=20)))
    labels.append(float(cls))
y = np.asarray(labels, np.float32)

hv = HashingVectorizer(n_features=2 ** 18)
Xs = hv.transform(docs)  # CSR: ~N*20 nonzeros; dense would be N*1M bytes
print(f"corpus: {Xs.shape}, {Xs.nnz} nnz "
      f"(dense would be {Xs.shape[0] * Xs.shape[1] * 4 / 1e9:.1f} GB)")

with config.set(stream_block_rows=max(N // 16, 1)):
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(Xs, y)
    print("streamed logreg acc:", round((clf.predict(Xs) == y).mean(), 4),
          "auc:", round(roc_auc_score(y, clf.decision_function(Xs)), 4))

    inc = Incremental(SGDClassifier(loss="log_loss", max_iter=3,
                                    random_state=0), shuffle_blocks=False)
    inc.fit(Xs, y)
    print("incremental sgd acc:",
          round((inc.estimator_.predict(Xs) == y).mean(), 4))
