"""Live telemetry plane: metric registry + /metrics + /status exporter.

Everything before this module was post-hoc: spans, counters and program
rows land in JSONL and are read AFTER the run by the report CLI. A
production serving system (ROADMAP north star) is scraped LIVE — when a
fit wedges or a server sheds load, the operator curls an endpoint while
it happens instead of tailing a trace after the kill. This module is
that plane:

- a process-wide **metric registry** unifying three kinds of signal:
  the existing flat counter registry (``_counters``), new **gauges**
  (last-value-wins: fit progress, queue depth, inflight rows), and
  log-spaced **histograms** (``_hist``: serving latency per
  (method, bucket), fit pass seconds);
- a background :class:`TelemetryServer` — stdlib ``http.server`` on a
  daemon thread, armed by ``config.obs_http_port`` (0 = off =
  the pre-existing zero-overhead path), serving

  ========== =============================================
  endpoint   content
  ========== =============================================
  /metrics   Prometheus text exposition v0.0.4 (counters,
             gauges, histograms)
  /healthz   liveness (200 "ok" even mid-stall — the
             server thread never touches the device)
  /status    JSON: open-span stack, recent-span report
             (program/span/counter tables via
             ``report.report_data``), serving windows,
             watchdog stalls
  ========== =============================================

- **fit progress publication** with zero new device syncs: a span-close
  observer (``_spans.add_span_observer``) turns the pass records the
  streamed fits already emit (``stream_pass`` / ``n_rows`` / ``pass_s``
  — host floats) into ``fit_pass`` / ``fit_rows_per_sec`` /
  ``fit_eta_seconds`` gauges and a ``fit_pass_seconds`` histogram;
  solvers with host-resident loss publish ``fit_loss`` the same way.

Overhead contract: with the port unset nothing here ever runs — no
observer is registered, every ``publish_*`` call is one module-global
bool check, no thread exists, and no jaxpr changes (asserted in
``tests/test_observability.py``). The scrape path reads pure host
dicts: serving a request can never trigger an XLA compile (asserted by
``tests/test_live_telemetry.py`` via the recompile counter).
"""

from __future__ import annotations

import http.server
import json
import math
import os
import re
import threading
import time
from collections import deque

from ._counters import counters_snapshot
from ._hist import DEFAULT_BOUNDS, Histogram
from ._spans import add_span_observer, open_spans_snapshot, \
    remove_span_observer

__all__ = [
    "TelemetryServer", "ensure_telemetry", "stop_telemetry",
    "telemetry_server", "live_publishing", "gauge_set", "gauges_snapshot",
    "histogram", "histograms_snapshot", "drop_labeled_series",
    "render_prometheus",
    "status_data", "fleet_status_data", "publish_progress", "note_stall",
    "register_server", "unregister_server", "register_registry",
    "register_fleet_provider", "unregister_fleet_provider",
]

_PREFIX = "dask_ml_tpu_"
_T0 = time.time()

# -- registry ----------------------------------------------------------------
# Counters stay in _counters (the span-delta / report machinery reads
# them there); this module adds the other two metric kinds and the one
# exposition view over all three.

_lock = threading.Lock()
_gauges: dict[tuple, float] = {}          # (name, labels) -> value
_hists: dict[tuple, Histogram] = {}       # (name, labels) -> Histogram
# labeled-series count per family name: the cardinality guard's ledger.
# Per-feature drift gauges (and any future labeled family) could mint
# unbounded series from unbounded label values; past
# config.obs_max_series new labeled children of a family are DROPPED
# and counted (telemetry_series_dropped_total) instead of growing the
# registry without bound. Unlabeled series are never capped.
_family_series: dict[str, int] = {}
# shared sink for rejected histogram series: callers still get a
# working Histogram, its observations just never render
_overflow_hist: Histogram | None = None
# series keys already rejected by the cap: the drop counter counts
# DROPPED SERIES, not rejected writes — a publisher re-setting the same
# over-cap gauge every monitor tick must not inflate it forever (and
# the known-rejected path must stay one set lookup, no config read)
_dropped_series: set = set()

# recent closed-span records (the observer feeds it while a server is
# live): /status renders them through report.report_data so the live
# view and the post-hoc CLI agree on shape
_recent_spans: deque = deque(maxlen=256)
# recent watchdog stall dumps (fed by _watchdog._report)
_recent_stalls: deque = deque(maxlen=8)

# live ModelServer instances (weakly referenced): /status lists their
# stats() windows
_servers: "weakref.WeakSet" = None  # type: ignore[name-defined]


def _server_set():
    global _servers
    if _servers is None:
        import weakref

        _servers = weakref.WeakSet()
    return _servers


def register_server(srv) -> None:
    """A ModelServer announces itself for the /status serving window."""
    try:
        _server_set().add(srv)
    except Exception:
        pass


# live ModelRegistry instances (weakly referenced): /status renders
# their per-name current/archived versions in the ``registry`` block
_registries = None


def _registry_set():
    global _registries
    if _registries is None:
        import weakref

        _registries = weakref.WeakSet()
    return _registries


def register_registry(reg) -> None:
    """A ModelRegistry announces itself for the /status registry block
    (what is serving, archived versions, last publish, publisher)."""
    try:
        _registry_set().add(reg)
    except Exception:
        pass


def unregister_server(srv) -> None:
    try:
        _server_set().discard(srv)
    except Exception:
        pass


# fleet-metrics providers (observability/fleet.MetricsFederator,
# registered by a FederatedFleet router with obs_fleet_federate on):
# each contributes merged dask_ml_tpu_fleet_* exposition lines to
# /metrics and a JSON block to /status + /status/fleet. Strong refs on
# purpose — the fed's stop() unregisters; a weak set could drop the
# provider mid-scrape
_fleet_providers: list = []


def register_fleet_provider(provider) -> None:
    """A MetricsFederator (or anything with ``render_lines()`` +
    ``fleet_block()``) joins the router's own exposition."""
    with _lock:
        if provider not in _fleet_providers:
            _fleet_providers.append(provider)


def unregister_fleet_provider(provider) -> None:
    with _lock:
        try:
            _fleet_providers.remove(provider)
        except ValueError:
            pass


def fleet_status_data() -> dict:
    """The combined ``/status/fleet`` block ({} when no federator is
    registered — federation of telemetry is off by default)."""
    out = {}
    for p in list(_fleet_providers):
        try:
            out.update(p.fleet_block())
        except Exception:
            continue
    return out


def _admit_series_locked(name: str, labels: tuple) -> bool:
    """Cardinality guard (caller holds ``_lock``): may a NEW labeled
    series join ``name``'s family? Past ``config.obs_max_series`` the
    series is dropped and the drop counted — /metrics stays bounded and
    parseable no matter what label values a caller mints."""
    if not labels:
        return True
    if (name, labels) in _dropped_series:
        return False
    from ..config import get_config

    cap = int(get_config().obs_max_series)
    if cap > 0 and _family_series.get(name, 0) >= cap:
        from ._counters import record_telemetry_series_dropped

        _dropped_series.add((name, labels))
        record_telemetry_series_dropped()
        return False
    _family_series[name] = _family_series.get(name, 0) + 1
    return True


def gauge_set(name: str, value, labels: tuple = ()) -> None:
    try:
        value = float(value)
    except (TypeError, ValueError):
        return
    key = (name, labels)
    with _lock:
        if key not in _gauges and not _admit_series_locked(name, labels):
            return
        _gauges[key] = value


def drop_labeled_series(name_prefix: str, label_kvs: tuple) -> int:
    """Remove every labeled gauge series whose family name starts with
    ``name_prefix`` and whose label set contains all of ``label_kvs``,
    releasing their slots in the cardinality ledger. Drift's version
    eviction rides this: an evicted model version must not leave its
    ``drift_score{version=...}`` series latched at a stale value on
    /metrics — or pinning cap room the live versions need."""
    kvs = set(label_kvs)
    with _lock:
        doomed = [k for k in _gauges
                  if k[0].startswith(name_prefix) and kvs <= set(k[1])]
        for k in doomed:
            del _gauges[k]
            left = _family_series.get(k[0], 0) - 1
            if left > 0:
                _family_series[k[0]] = left
            else:
                _family_series.pop(k[0], None)
            _dropped_series.discard(k)
        return len(doomed)


def gauges_snapshot() -> dict:
    with _lock:
        return dict(_gauges)


def histogram(name: str, labels: tuple = (), bounds=None) -> Histogram:
    """Create-or-get the histogram keyed (name, labels). ``labels`` is
    a tuple of (key, value) string pairs; label sets under one name
    must share boundaries (the first creation wins)."""
    global _overflow_hist
    key = (name, labels)
    with _lock:
        h = _hists.get(key)
        if h is None:
            if not _admit_series_locked(name, labels):
                # callers observe into a shared sink that never renders
                # — the write contract survives the cap, the page stays
                # bounded
                if _overflow_hist is None:
                    _overflow_hist = Histogram(bounds)
                return _overflow_hist
            h = _hists[key] = Histogram(bounds)
        return h


def histograms_snapshot() -> dict:
    with _lock:
        return dict(_hists)


def metrics_reset() -> None:
    """Clear gauges/histograms/rings (counters have their own reset) —
    test isolation."""
    with _lock:
        _gauges.clear()
        _hists.clear()
        _family_series.clear()
        _dropped_series.clear()
        _recent_spans.clear()
        _recent_stalls.clear()
        del _fleet_providers[:]


# -- publishers --------------------------------------------------------------
# every publish path is gated on this module-global bool: with no
# telemetry server live the calls cost one load + one branch, and
# NOTHING is registered with the span layer (its disabled path stays
# the shared no-op).

_publishing = 0
_pub_lock = threading.Lock()


def live_publishing() -> bool:
    return _publishing > 0


def _publishing_arm(delta: int) -> None:
    global _publishing
    with _pub_lock:
        _publishing += delta


def publish_progress(**gauges) -> None:
    """Host-side fit progress (loss, grad_norm, pass, blocks...) as
    ``fit_<name>`` gauges. No-op unless a telemetry server is live;
    callers only ever pass values they already hold on host — this path
    must never force a device sync."""
    if not _publishing:
        return
    for k, v in gauges.items():
        if v is not None:
            gauge_set(f"fit_{k}", v)


def note_stall(rec: dict) -> None:
    """Watchdog stall dump -> the /status ring (the ``watchdog_stalls``
    counter itself is incremented by the watchdog, so /metrics and the
    report counters table see it with or without a live server)."""
    try:
        with _lock:  # /status iterates this ring from the HTTP thread
            _recent_stalls.append({
                k: v for k, v in rec.items() if k != "stacks"
            })
    except Exception:
        pass


def _on_span_record(rec: dict) -> None:
    """Span-close observer (registered only while a server is live):
    stream-pass records become progress gauges + the pass-time
    histogram; everything lands in the recent-span ring for /status."""
    try:
        if "stream_pass" in rec:
            p = int(rec["stream_pass"])
            wall = float(rec.get("pass_s") or rec.get("wall_s") or 0.0)
            gauge_set("fit_pass", p)
            if wall > 0:
                histogram("fit_pass_seconds").observe(wall)
                gauge_set("fit_last_pass_seconds", wall)
                n = float(rec.get("n_rows") or 0.0)
                if n > 0:
                    gauge_set("fit_rows_per_sec", n / wall)
            tot = rec.get("passes_total")
            if tot:
                gauge_set("fit_passes_total", int(tot))
                if wall > 0:
                    # ETA from the pass clock: remaining passes at the
                    # measured per-pass wall (host arithmetic only)
                    gauge_set("fit_eta_seconds",
                              max(int(tot) - p, 0) * wall)
        elif rec.get("span") == "fit":
            gauge_set("fit_wall_s", rec.get("wall_s", 0.0))
        with _lock:  # /status iterates this ring from the HTTP thread
            _recent_spans.append(rec)
    except Exception:
        pass  # telemetry must never raise into the span layer


# -- Prometheus text exposition v0.0.4 ---------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _san(name: str) -> str:
    name = _NAME_RE.sub("_", str(name))
    return name if name and not name[0].isdigit() else f"_{name}"


def _fmt(v) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_san(k)}="{str(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


def _merge_label(labels: tuple, key: str, value: str) -> str:
    return _labels_str(tuple(labels) + ((key, value),))


def render_prometheus() -> str:
    """The /metrics body: counters (``_total`` suffix), gauges, and
    histograms (cumulative ``le`` buckets + ``_sum``/``_count``), all
    under the ``dask_ml_tpu_`` namespace. Pure host dicts — no jax call
    anywhere on this path (scraping must never compile or sync)."""
    lines = []
    counters = counters_snapshot()
    for name in sorted(counters):
        v = counters[name]
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(float(v)):
            continue
        n = f"{_PREFIX}{_san(name)}_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")
    hist_by_name: dict[str, list] = {}
    for (name, labels), h in sorted(histograms_snapshot().items()):
        hist_by_name.setdefault(_san(name), []).append((labels, h))
    by_name: dict[str, list] = {}
    for (name, labels), v in sorted(gauges_snapshot().items()):
        # a gauge sharing a histogram's family name would emit a second
        # TYPE line for that family — invalid exposition; histogram wins
        if math.isfinite(v) and _san(name) not in hist_by_name:
            by_name.setdefault(_san(name), []).append((labels, v))
    for name, series in by_name.items():
        n = f"{_PREFIX}{name}"
        lines.append(f"# TYPE {n} gauge")
        for labels, v in series:
            lines.append(f"{n}{_labels_str(labels)} {_fmt(v)}")
    for name, series in hist_by_name.items():
        n = f"{_PREFIX}{name}"
        lines.append(f"# TYPE {n} histogram")
        for labels, h in series:
            snap = h.snapshot()
            cum = 0
            for i, bound in enumerate(snap["bounds"]):
                cum += snap["counts"][i]
                lines.append(
                    f"{n}_bucket"
                    f"{_merge_label(labels, 'le', _fmt(bound))} {cum}"
                )
            cum += snap["counts"][-1]
            lines.append(
                f"{n}_bucket{_merge_label(labels, 'le', '+Inf')} {cum}"
            )
            ls = _labels_str(labels)
            lines.append(f"{n}_sum{ls} {_fmt(snap['sum'])}")
            lines.append(f"{n}_count{ls} {snap['count']}")
    # fleet-merged families (dask_ml_tpu_fleet_*, a disjoint namespace
    # — one TYPE line per family holds across the whole page) from any
    # registered federator; a provider error must never 500 the scrape
    for p in list(_fleet_providers):
        try:
            lines.extend(p.render_lines())
        except Exception:
            continue
    up = f"{_PREFIX}uptime_seconds"
    lines.append(f"# TYPE {up} gauge")
    lines.append(f"{up} {_fmt(time.time() - _T0)}")
    return "\n".join(lines) + "\n"


# -- /status -----------------------------------------------------------------

def status_data() -> dict:
    """The live JSON the wedged-tunnel round needed: what the process
    believes it is doing RIGHT NOW (open-span stack), what it has done
    recently (report tables over the recent-span ring + the program
    registry), the serving windows, and any watchdog stalls."""
    from ._programs import programs_snapshot
    from .report import report_data

    now = time.time()
    open_spans = []
    for s in open_spans_snapshot():
        s = dict(s)
        s["age_s"] = round(now - s.pop("t_open_unix"), 3)
        open_spans.append(s)
    counters = counters_snapshot()
    # the recent-span ring + synthetic counters/programs records render
    # through the SAME aggregator as the post-hoc CLI — one shape for
    # live and recorded views
    with _lock:  # fit threads append concurrently; unlocked iteration
        records = list(_recent_spans)     # raises "deque mutated" -> 500
        stalls = list(_recent_stalls)
    records.append({"counters": True, **counters})
    progs = programs_snapshot()
    if progs:
        records.append({"programs": progs})
    # the plans table (ISSUE 15): which plan/ladder rung minted each
    # warmed specialization — rides the same report aggregator
    try:
        from ..plans import plans_snapshot

        plrows = plans_snapshot()
    except Exception:
        plrows = None
    if plrows:
        records.append({"plans": plrows})
    # the incident plane (ISSUE 20): alert rules + captured bundles —
    # blocks for operators, synthetic records for the shared report
    # aggregator (one serialization path for live and post-hoc views)
    try:
        from . import alerts as _alerts

        alerts_block = _alerts.alerts_data()
    except Exception:
        alerts_block = {}
    try:
        from . import incidents as _incidents

        incidents_block = _incidents.incidents_data()
    except Exception:
        incidents_block = {}
    if alerts_block.get("rules") or alerts_block.get("events"):
        records.append({"alerts": alerts_block})
    if incidents_block.get("captured"):
        records.append({"incidents": incidents_block["captured"]})
    hists = {}
    for (name, labels), h in histograms_snapshot().items():
        key = f"{name}{_labels_str(labels)}"
        snap = h.snapshot()
        hists[key] = {
            "count": snap["count"], "sum": round(snap["sum"], 6),
            **{k: (None if isinstance(v, float) and math.isnan(v)
                   else round(v, 6))
               for k, v in h.percentiles((50, 90, 99)).items()},
        }
    serving = []
    for srv in list(_server_set()):
        try:
            serving.append(srv.stats())
        except Exception:
            continue
    # the registry block: every live ModelRegistry's per-name view
    # (current version, archived versions, last publish, publisher) —
    # fleet operators see what is serving without instrumenting code
    registry = {}
    for reg in list(_registry_set()):
        try:
            registry.update(reg.status_snapshot())
        except Exception:
            continue
    # the drift block: last computed train-vs-serve / window scores,
    # recent hot-swap canaries, and the tracked sketch keys
    try:
        from . import drift as _drift

        drift_block = _drift.status_block()
    except Exception:
        drift_block = {}
    # the reliability block: armed fault plan + per-site fired counts,
    # retry/quarantine/resume/restart counters — "is chaos armed, what
    # has it hit, what did the hardening absorb"
    try:
        from ..reliability import status_block as _rel_status

        reliability_block = _rel_status()
    except Exception:
        reliability_block = {}
    # the structured telemetry block the fleet federator merges from:
    # gauges and RAW histogram buckets as [name, labels, payload]
    # triples (the display "gauges"/"histograms" blocks bake labels
    # into string keys — fine to read, lossy to re-parse). Bounds ride
    # each histogram so the bucket-for-bucket merge can refuse a
    # mismatched ladder instead of corrupting quantiles.
    telem_g = [[n, [list(kv) for kv in ls], v]
               for (n, ls), v in sorted(gauges_snapshot().items())]
    telem_h = []
    for (name, labels), h in sorted(histograms_snapshot().items()):
        snap = h.snapshot()
        telem_h.append([name, [list(kv) for kv in labels], {
            "bounds": list(snap["bounds"]), "counts": snap["counts"],
            "sum": snap["sum"], "count": snap["count"],
            "min": snap["min"], "max": snap["max"],
        }])
    out = {
        "pid": os.getpid(),
        "t_unix": round(now, 3),
        "uptime_s": round(now - _T0, 3),
        "open_spans": open_spans,
        "counters": counters,
        "gauges": {f"{n}{_labels_str(ls)}": v
                   for (n, ls), v in gauges_snapshot().items()},
        "histograms": hists,
        "telemetry": {"gauges": telem_g, "histograms": telem_h},
        "serving": serving,
        "registry": registry,
        "drift": drift_block,
        "reliability": reliability_block,
        "watchdog_stalls": stalls,
        "alerts": alerts_block,
        "incidents": incidents_block,
        "report": report_data(records),
    }
    try:
        from ._counters import device_memory_gauges

        out["device_memory"] = device_memory_gauges()
    except Exception:
        out["device_memory"] = {}
    fleet = fleet_status_data()
    if fleet:
        out["fleet"] = fleet
    return out


# -- HTTP server -------------------------------------------------------------

def _json_default(o):
    """Non-JSON leaves (numpy scalars riding span attrs) -> float/str."""
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "dask-ml-tpu-telemetry/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # silent: stderr belongs to the fit
        pass

    def _reply(self, code, body: bytes, ctype: str, headers=()):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in dict(headers).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        # the federation request/publish surface (serving/federation):
        # POST /fleet/<name>/<op> routes to the live-registered
        # FleetServer carrying <name> in this process. Kept out of
        # do_GET so scrapers stay read-only.
        path = self.path.split("?", 1)[0].rstrip("/")
        try:
            if path == "/profile":
                # on-demand deep profiling: a bounded jax.profiler
                # window into config.incident_dir (real device traces
                # on TPU; no-op-with-reason off-TPU). POST, not GET —
                # it changes on-disk state and blocks for the window.
                from urllib.parse import parse_qs, urlparse

                from . import incidents as _incidents

                q = parse_qs(urlparse(self.path).query)
                seconds = (q.get("seconds") or ["5"])[0]
                out = _incidents.deep_profile(seconds)
                self._reply(
                    200 if out.get("profiled") else 400,
                    (json.dumps(out, default=_json_default)
                     + "\n").encode(),
                    "application/json",
                )
            elif path.startswith("/fleet/"):
                from ..serving import federation

                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n > 0 else b""
                code, out, ctype, extra = federation.handle_http(
                    path, dict(self.headers.items()), body
                )
                self._reply(code, out, ctype, extra)
            else:
                self._reply(404, b"not found\n",
                            "text/plain; charset=utf-8")
        except Exception as exc:  # never take the server thread down
            try:
                self._reply(500, f"error: {exc}\n".encode(),
                            "text/plain; charset=utf-8")
            except Exception:
                pass

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/healthz":
                self._reply(200, b"ok\n", "text/plain; charset=utf-8")
            elif path == "/metrics":
                self._reply(
                    200, render_prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/traces":
                # the request trace plane's JSON surface: sampler
                # counters, retained sampled traces, per-stage exemplar
                # histograms. Exemplars live HERE, not in /metrics —
                # the Prometheus text exposition stays grammar-clean
                from ._requests import traces_data

                self._reply(
                    200,
                    (json.dumps(traces_data(), default=_json_default)
                     + "\n").encode(),
                    "application/json",
                )
            elif path == "/alerts":
                # the alert engine's view alone: rule rows with
                # firing/resolved state, the transition ring, and the
                # crossing ledger — what a pager/autoscaler polls
                from . import alerts as _alerts

                self._reply(
                    200,
                    (json.dumps(_alerts.alerts_data(),
                                default=_json_default) + "\n").encode(),
                    "application/json",
                )
            elif path == "/status/fleet":
                # the fleet-scope view alone ({} until a federating
                # router registers): merged counters/quantiles + the
                # SLO burn block, without the full /status payload
                self._reply(
                    200,
                    (json.dumps(fleet_status_data(),
                                default=_json_default) + "\n").encode(),
                    "application/json",
                )
            elif path == "/status":
                # default=: span attrs can carry numpy scalars (a fit's
                # n_iter etc.) — degrade them to floats/strings instead
                # of 500ing the whole status page
                self._reply(
                    200,
                    (json.dumps(status_data(), default=_json_default)
                     + "\n").encode(),
                    "application/json",
                )
            elif path == "/":
                self._reply(
                    200,
                    b"dask_ml_tpu live telemetry: "
                    b"/metrics /status /status/fleet /traces /alerts "
                    b"/healthz (POST /profile?seconds=N)\n",
                    "text/plain; charset=utf-8",
                )
            else:
                self._reply(404, b"not found\n",
                            "text/plain; charset=utf-8")
        except Exception as exc:  # never take the server thread down
            try:
                self._reply(500, f"error: {exc}\n".encode(),
                            "text/plain; charset=utf-8")
            except Exception:
                pass


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # a fit process restarted on the same port must not wait out
    # TIME_WAIT to expose telemetry again
    allow_reuse_address = True


class TelemetryServer:
    """The background exporter. ``port=0`` binds an ephemeral port
    (tests); production sets ``config.obs_http_port``. Use as a context
    manager or ``start()``/``stop()``. Starting registers the span
    observer that feeds fit-progress gauges; stopping removes it, so a
    stopped plane restores the exact pre-live overhead profile."""

    def __init__(self, port=None, host="127.0.0.1"):
        if port is None:
            from ..config import get_config

            port = int(get_config().obs_http_port)
        self.port = int(port)
        self.host = host
        self._httpd = None
        self._thread = None

    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._httpd is not None:
            return self
        httpd = _Server((self.host, self.port), _Handler)
        self.port = httpd.server_address[1]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="dask-ml-tpu-telemetry", daemon=True,
        )
        # arm publication BEFORE serving: a scrape racing start() must
        # not observe a half-armed plane
        add_span_observer(_on_span_record)
        _publishing_arm(+1)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        _publishing_arm(-1)
        remove_span_observer(_on_span_record)
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        finally:
            self._httpd = None
            if self._thread is not None:
                self._thread.join(5.0)
                self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


# -- process-wide singleton --------------------------------------------------

_singleton: TelemetryServer | None = None
_singleton_lock = threading.Lock()
# port -> last bind-failure time; retried after a backoff rather than
# blacklisted forever — the process that loses a port race (two bench
# children sharing one DASK_ML_TPU_OBS_HTTP_PORT) must regain its live
# endpoint once the winner exits and frees the port
_failed_ports: dict[int, float] = {}
_BIND_RETRY_S = 30.0


def telemetry_server() -> TelemetryServer | None:
    """The live singleton server, or None."""
    return _singleton


def ensure_telemetry() -> TelemetryServer | None:
    """Start the process-wide telemetry server if ``config.obs_http_port``
    asks for one and none is running (idempotent; first port wins for
    the process lifetime). Called from the hot-path entries (BlockStream
    construction, ModelServer.start, fit_logger, bench) — with the knob
    at its 0 default this is one config read. A bind failure (port
    already taken — e.g. two bench children racing) backs off for
    ``_BIND_RETRY_S`` before the next attempt, and NEVER raises into
    the fit."""
    global _singleton
    # the alert engine shares these entry points but arms on its OWN
    # knobs (obs_alert_rules / incident_dir) — rules work without an
    # HTTP port. One None check + one config read when disarmed; a bad
    # rule spec raises its typed error HERE, in the arming caller,
    # never silently inside a daemon.
    from . import alerts as _alerts

    try:
        _alerts.ensure_engine()
    except _alerts.AlertRuleError:
        raise
    except Exception:
        pass
    if _singleton is not None:
        return _singleton
    from ..config import get_config

    port = int(get_config().obs_http_port)
    if port <= 0:
        return None
    t_fail = _failed_ports.get(port)
    if t_fail is not None and time.time() - t_fail < _BIND_RETRY_S:
        return None
    with _singleton_lock:
        if _singleton is not None:
            return _singleton
        try:
            srv = TelemetryServer(port=port).start()
        except Exception:
            _failed_ports[port] = time.time()
            return None
        _failed_ports.pop(port, None)
        _singleton = srv
    return _singleton


def stop_telemetry() -> None:
    """Stop the singleton (tests / graceful shutdown)."""
    global _singleton
    with _singleton_lock:
        srv, _singleton = _singleton, None
    if srv is not None:
        srv.stop()
