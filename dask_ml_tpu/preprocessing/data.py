"""Preprocessing scalers & transforms over sharded arrays.

Reference: ``dask_ml/preprocessing/data.py`` (SURVEY.md §2a Preprocessing
row): StandardScaler / MinMaxScaler / RobustScaler / QuantileTransformer /
PolynomialFeatures as lazy dask reductions + per-block transforms. Here the
fit statistics are one jitted masked reduction each (psum under sharding)
and transforms are elementwise XLA programs that keep data on device.

Quantile-based fits (RobustScaler, QuantileTransformer) use a global
device-side sort (XLA gathers the column); the reference uses approximate
t-digest quantiles — exact is affordable at this stage and flagged for a
sketch-based upgrade.
"""

from __future__ import annotations

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..base import BaseEstimator, TransformerMixin, to_host
from ..ops import reductions
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_array, check_is_fitted


def _handle_zeros_in_scale(scale):
    """Ref: dask_ml/utils.py::handle_zeros_in_scale."""
    return np.where(scale == 0.0, 1.0, scale)


@functools.partial(jax.jit,
                   static_argnames=("shift_first", "do_clip"))
def _affine(data, mask, a, b, lo=0.0, hi=1.0, shift_first=True,
            do_clip=False):
    """One fused program for every scaler transform/inverse. A chain of
    eager ops would pay one dispatch round-trip EACH on a tunneled
    runtime; jitted, XLA fuses the whole transform into a single kernel
    launch.

    ``shift_first=True`` computes ``(data + b) * a`` — the
    subtract-then-scale form, which keeps the benign cancellation for
    features with |mean| >> std (``data * a + b`` would round at the
    magnitude of data before b cancels it). ``shift_first=False``
    computes ``data * a + b`` — the scale-then-shift form used by the
    inverse direction. ``mask=None`` skips padding re-zeroing (only
    valid when the shift term is zero)."""
    a = jnp.asarray(a, data.dtype)
    b = jnp.asarray(b, data.dtype)
    out = (data + b) * a if shift_first else data * a + b
    if do_clip:
        out = jnp.clip(out, lo, hi)
    if mask is not None:
        out = out * mask[:, None].astype(data.dtype)
    return out


def _frame_parts(X):
    """(partition list, kind) for frame inputs; (None, None) otherwise.

    The reference's scalers consume dd.DataFrames natively and return
    frames of the same type (ref: dask_ml/preprocessing/data.py — the
    dd path of StandardScaler etc.); here the frame types are pandas
    and :class:`~dask_ml_tpu.parallel.frames.PartitionedFrame`.
    """
    if isinstance(X, pd.DataFrame):
        return [X], "pandas"
    from ..parallel.frames import PartitionedFrame

    if isinstance(X, PartitionedFrame):
        return list(X.partitions), "partitioned"
    return None, None


def _frame_device(parts, cols):
    """Place frame partitions on the mesh, rejecting unencoded columns.
    Reuses PartitionedFrame.to_sharded as the single frame→device
    bridge."""
    from ..parallel.frames import PartitionedFrame

    bad = [
        c for c in cols
        if not (pd.api.types.is_numeric_dtype(parts[0].dtypes[c])
                or pd.api.types.is_bool_dtype(parts[0].dtypes[c]))
    ]
    if bad:
        raise ValueError(
            f"non-numeric columns {bad}: encode them first "
            "(Categorizer + DummyEncoder/OrdinalEncoder)"
        )
    return PartitionedFrame(parts).to_sharded(columns=cols)


def _frame_check_fitted_names(self, cols):
    fitted = getattr(self, "feature_names_in_", None)
    if fitted is not None and list(fitted) != list(cols):
        raise ValueError(
            f"feature names {list(cols)} do not match the names seen at "
            f"fit time {list(fitted)}"
        )


def _frame_rebuild(self, parts, kind, cols, out):
    """Rebuild the method result as the input's frame type with the
    original partition boundaries and index."""
    if not isinstance(out, ShardedArray):
        return out
    if out.shape[1] != len(cols):
        # width-changing transform (PolynomialFeatures): honor the
        # reference's preserve_dataframe switch
        if not getattr(self, "preserve_dataframe", True):
            return out
        names = list(self.get_feature_names_out(cols))
    else:
        names = cols
    arr = np.asarray(out.to_numpy())
    rebuilt, off = [], 0
    for p in parts:
        rebuilt.append(pd.DataFrame(
            arr[off:off + len(p)], index=p.index, columns=names
        ))
        off += len(p)
    if kind == "pandas":
        return rebuilt[0]
    from ..parallel.frames import PartitionedFrame

    return PartitionedFrame(rebuilt)


def _frame_aware(method, name):
    """Frame adapter for array-native transformer methods: frames are
    placed on device (all columns must already be numeric — categorical
    columns go through Categorizer/DummyEncoder/OrdinalEncoder first),
    the array method runs on the mesh, and the result is rebuilt as the
    SAME frame type with the original partition boundaries and index —
    the reference's frame-in/frame-out contract."""

    @functools.wraps(method)
    def wrapper(self, X, *args, **kwargs):
        parts, kind = _frame_parts(X)
        if kind is None:
            return method(self, X, *args, **kwargs)
        cols = list(parts[0].columns)
        if name != "fit":
            _frame_check_fitted_names(self, cols)
        out = method(self, _frame_device(parts, cols), *args, **kwargs)
        if out is self:  # fit
            self.feature_names_in_ = np.asarray(cols, dtype=object)
            return self
        return _frame_rebuild(self, parts, kind, cols, out)

    return wrapper


class _DeviceTransformer(TransformerMixin, BaseEstimator):
    def __init_subclass__(cls, **kw):
        # every subclass-defined fit/transform/inverse_transform gets the
        # frame adapter; array inputs pass straight through
        super().__init_subclass__(**kw)
        for name in ("fit", "transform", "inverse_transform"):
            if name in cls.__dict__:
                setattr(cls, name, _frame_aware(cls.__dict__[name], name))

    def fit_transform(self, X, y=None, **kw):
        parts, kind = _frame_parts(X)
        if kind is None:
            return self.fit(X, y, **kw).transform(X)
        # frame input: one host-concat + one device placement for the
        # whole fit+transform, then rebuild the frame once
        cols = list(parts[0].columns)
        Xs = _frame_device(parts, cols)
        out = self.fit(Xs, y, **kw).transform(Xs)
        self.feature_names_in_ = np.asarray(cols, dtype=object)
        return _frame_rebuild(self, parts, kind, cols, out)

    # quantile-based transformers compute NaN-skipping statistics
    # (nanquantile), so they accept NaN like sklearn's 'allow-nan' mode;
    # moment-based scalers keep strict rejection (their masked reductions
    # would silently propagate NaN into the fitted statistics)
    _allow_nan = False

    def _sharded(self, X) -> ShardedArray:
        return check_array(X, dtype=np.float32, allow_nan=self._allow_nan)


class StandardScaler(_DeviceTransformer):
    """Ref: dask_ml/preprocessing/data.py::StandardScaler."""

    def __init__(self, copy=True, with_mean=True, with_std=True):
        self.copy = copy
        self.with_mean = with_mean
        self.with_std = with_std

    def fit(self, X, y=None):
        X = self._sharded(X)
        mean, var = reductions.masked_mean_var(X.data, X.row_mask(), X.n_rows)
        self.mean_ = to_host(mean) if self.with_mean else None
        if self.with_std:
            self.var_ = to_host(var)
            self.scale_ = _handle_zeros_in_scale(np.sqrt(self.var_))
        else:
            self.var_ = self.scale_ = None
        self.n_samples_seen_ = X.n_rows
        self.n_features_in_ = X.shape[1]
        return self

    def transform(self, X):
        check_is_fitted(self, "n_samples_seen_")
        X = self._sharded(X)
        a = 1.0 / self.scale_ if self.with_std else np.float32(1.0)
        b = -self.mean_ if self.with_mean else np.float32(0.0)
        mask = X.row_mask() if self.with_mean else None
        out = _affine(X.data, mask, a, b)
        return ShardedArray(out, X.n_rows, X.mesh)

    def inverse_transform(self, X):
        check_is_fitted(self, "n_samples_seen_")
        X = self._sharded(X)
        a = self.scale_ if self.with_std else np.float32(1.0)
        b = self.mean_ if self.with_mean else np.float32(0.0)
        mask = X.row_mask() if self.with_mean else None
        out = _affine(X.data, mask, a, b, shift_first=False)
        return ShardedArray(out, X.n_rows, X.mesh)


class MinMaxScaler(_DeviceTransformer):
    """Ref: dask_ml/preprocessing/data.py::MinMaxScaler."""

    def __init__(self, feature_range=(0, 1), copy=True, clip=False):
        self.feature_range = feature_range
        self.copy = copy
        self.clip = clip

    def fit(self, X, y=None):
        X = self._sharded(X)
        mask = X.row_mask()
        dmin = to_host(reductions.masked_min(X.data, mask))
        dmax = to_host(reductions.masked_max(X.data, mask))
        lo, hi = self.feature_range
        self.data_min_, self.data_max_ = dmin, dmax
        self.data_range_ = dmax - dmin
        self.scale_ = (hi - lo) / _handle_zeros_in_scale(self.data_range_)
        self.min_ = lo - dmin * self.scale_
        self.n_features_in_ = X.shape[1]
        return self

    def transform(self, X):
        check_is_fitted(self, "scale_")
        X = self._sharded(X)
        out = _affine(X.data, X.row_mask(), self.scale_, self.min_,
                      self.feature_range[0], self.feature_range[1],
                      shift_first=False, do_clip=bool(self.clip))
        return ShardedArray(out, X.n_rows, X.mesh)

    def inverse_transform(self, X):
        check_is_fitted(self, "scale_")
        X = self._sharded(X)
        out = _affine(X.data, X.row_mask(), 1.0 / self.scale_, -self.min_)
        return ShardedArray(out, X.n_rows, X.mesh)


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("n_bins",))
def _sketch_quantiles(data, mask, qs, n_bins=4096):
    """Histogram-sketch per-column quantiles (the reference's approximate
    quantiles, ``dask_ml/preprocessing/data.py::RobustScaler`` — dask's
    t-digest/percentile sketch): one min/max pass + one bucketized
    segment_sum pass, then interpolation inside the hit bin. No global
    sort — O(n·d) work and O(d·n_bins) memory instead of gathering whole
    columns, which is what makes 1B-row scaling stats feasible. Error is
    bounded by one bin width: (max-min)/n_bins per column."""
    d = data.shape[1]
    valid = mask[:, None] > 0
    big = jnp.asarray(jnp.inf, jnp.float32)
    df = data.astype(jnp.float32)
    mn = jnp.min(jnp.where(valid, df, big), axis=0)
    mx = jnp.max(jnp.where(valid, df, -big), axis=0)
    span = jnp.maximum(mx - mn, 1e-12)
    idx = jnp.clip(((df - mn) / span * n_bins).astype(jnp.int32),
                   0, n_bins - 1)
    flat = idx + jnp.arange(d, dtype=jnp.int32)[None, :] * n_bins
    weights = jnp.broadcast_to(mask[:, None].astype(jnp.float32),
                               df.shape)
    hist = jax.ops.segment_sum(
        weights.reshape(-1), flat.reshape(-1), num_segments=d * n_bins
    ).reshape(d, n_bins)
    cum = jnp.cumsum(hist, axis=1)
    q_arr = jnp.asarray(qs, jnp.float32)

    def one_col(cum_c, mn_c, span_c):
        t = q_arr * cum_c[-1]
        b = jnp.clip(jnp.searchsorted(cum_c, t), 0, n_bins - 1)
        prev = jnp.where(b > 0, cum_c[jnp.maximum(b - 1, 0)], 0.0)
        in_bin = cum_c[b] - prev
        frac = jnp.where(in_bin > 0, (t - prev) / in_bin, 0.5)
        return mn_c + (b + frac) * span_c / n_bins

    return jax.vmap(one_col)(cum, mn, span).T  # (n_q, d)


# rows above which scaling stats switch to the sketch: an exact
# nanquantile gathers and sorts whole columns, which stops being
# affordable long before BASELINE scale
_SKETCH_THRESHOLD = 1_000_000


def _masked_quantiles(X: ShardedArray, qs, sketch=None, n_bins=4096):
    """Per-column quantiles. Small inputs: exact nanquantile (padding →
    NaN). Large inputs (or ``sketch=True``): histogram sketch, matching
    the reference's approximate-quantile behavior at scale."""
    if sketch is None:
        sketch = X.n_rows > _SKETCH_THRESHOLD
    if sketch:
        return _sketch_quantiles(
            X.data, X.row_mask(jnp.float32), jnp.asarray(qs, jnp.float32),
            n_bins=n_bins,
        )
    mask = X.row_mask(X.dtype)
    data = jnp.where(mask[:, None] > 0, X.data, jnp.nan)
    return jnp.nanquantile(
        data.astype(jnp.float32), jnp.asarray(qs, jnp.float32), axis=0
    )


class RobustScaler(_DeviceTransformer):
    """Ref: dask_ml/preprocessing/data.py::RobustScaler (approximate
    quantiles there; exact here)."""

    _allow_nan = True

    def __init__(self, with_centering=True, with_scaling=True,
                 quantile_range=(25.0, 75.0), copy=True):
        self.with_centering = with_centering
        self.with_scaling = with_scaling
        self.quantile_range = quantile_range
        self.copy = copy

    def fit(self, X, y=None):
        X = self._sharded(X)
        q_lo, q_hi = self.quantile_range
        qs = _masked_quantiles(X, [q_lo / 100.0, 0.5, q_hi / 100.0])
        qs = to_host(qs)
        self.center_ = qs[1] if self.with_centering else None
        if self.with_scaling:
            self.scale_ = _handle_zeros_in_scale(qs[2] - qs[0])
        else:
            self.scale_ = None
        self.n_features_in_ = X.shape[1]
        return self

    def transform(self, X):
        check_is_fitted(self, "n_features_in_")
        X = self._sharded(X)
        a = 1.0 / self.scale_ if self.with_scaling else np.float32(1.0)
        b = -self.center_ if self.with_centering else np.float32(0.0)
        out = _affine(X.data, X.row_mask(), a, b)
        return ShardedArray(out, X.n_rows, X.mesh)

    def inverse_transform(self, X):
        check_is_fitted(self, "n_features_in_")
        X = self._sharded(X)
        a = self.scale_ if self.with_scaling else np.float32(1.0)
        b = self.center_ if self.with_centering else np.float32(0.0)
        out = _affine(X.data, X.row_mask(), a, b, shift_first=False)
        return ShardedArray(out, X.n_rows, X.mesh)


class QuantileTransformer(_DeviceTransformer):
    """Ref: dask_ml/preprocessing/data.py::QuantileTransformer — maps each
    feature through its empirical CDF via interpolation."""

    _allow_nan = True

    def __init__(self, n_quantiles=1000, output_distribution="uniform",
                 ignore_implicit_zeros=False, subsample=int(1e5),
                 random_state=None, copy=True):
        self.n_quantiles = n_quantiles
        self.output_distribution = output_distribution
        self.ignore_implicit_zeros = ignore_implicit_zeros
        self.subsample = subsample
        self.random_state = random_state
        self.copy = copy

    def fit(self, X, y=None):
        if self.ignore_implicit_zeros:
            # sklearn: only meaningful for sparse input, which TPU dense
            # arrays never are — raise rather than silently no-op
            raise ValueError(
                "ignore_implicit_zeros applies to sparse matrices only; "
                "dense input does not support it"
            )
        X = self._sharded(X)
        sub_limit = int(self.subsample) if self.subsample else None
        if sub_limit is not None and self.n_quantiles > sub_limit:
            raise ValueError(
                f"The number of quantiles ({self.n_quantiles}) cannot be "
                f"greater than subsample ({sub_limit})"
            )
        n_q = min(self.n_quantiles, X.n_rows)
        self.n_quantiles_ = n_q
        self.references_ = np.linspace(0, 1, n_q)
        sub = sub_limit if sub_limit is not None else X.n_rows
        src = X
        if X.n_rows > sub:
            # sklearn semantics: quantiles of a seeded uniform subsample
            # of `subsample` rows. The pick is a device Gumbel top-l
            # (static shapes, no host index generation at 1B rows) and
            # the gather one all-to-all (take_rows). If the sample is
            # still past the sort-affordability threshold,
            # _masked_quantiles switches to the histogram sketch — the
            # reference's approximate-quantile behavior at scale.
            import jax as _jax

            from ..models.kmeans import _gumbel_top_l
            from ..parallel.sharded import take_rows

            key = _jax.random.PRNGKey(
                0 if self.random_state is None else int(self.random_state)
            )
            idx_d = _gumbel_top_l(X.row_mask(jnp.float32), key, sub)
            if not idx_d.is_fully_addressable:
                # multi-host mesh: replicate before the host read —
                # np.asarray on a cross-process array raises
                from ..parallel.sharded import _replicator

                idx_d = _replicator(X.mesh)(idx_d)
            src = take_rows(X, np.asarray(idx_d))
        self.quantiles_ = to_host(_masked_quantiles(src, self.references_))
        self.n_features_in_ = X.shape[1]
        return self

    def transform(self, X):
        check_is_fitted(self, "quantiles_")
        return self._map(X, inverse=False)

    def inverse_transform(self, X):
        check_is_fitted(self, "quantiles_")
        return self._map(X, inverse=True)

    def _map(self, X, inverse):
        from scipy import stats

        X = self._sharded(X)
        quantiles = jnp.asarray(self.quantiles_, jnp.float32)  # (n_q, d)
        refs = jnp.asarray(self.references_, jnp.float32)
        data = X.data.astype(jnp.float32)
        normal = self.output_distribution == "normal"

        if inverse and normal:
            data = jnp.asarray(
                stats.norm.cdf(np.asarray(data)), jnp.float32
            )

        def col(vals, qcol):
            if inverse:
                return jnp.interp(vals, refs, qcol)
            # average of forward and reverse interpolation: sklearn's tie
            # handling — on runs of equal values the one-sided interp is
            # biased to the run's edge, the average lands mid-run
            fwd = jnp.interp(vals, qcol, refs)
            rev = -jnp.interp(-vals, -qcol[::-1], -refs[::-1])
            out = 0.5 * (fwd + rev)
            # boundary override, also sklearn: at/above the fitted max →
            # exactly refs[-1], then at/below the fitted min → refs[0].
            # Lower bound LAST so a constant column maps to refs[0]
            out = jnp.where(vals >= qcol[-1], refs[-1], out)
            return jnp.where(vals <= qcol[0], refs[0], out)

        out = jax.vmap(col, in_axes=(1, 1), out_axes=1)(data, quantiles)
        if not inverse and normal:
            clipped = jnp.clip(out, 1e-7, 1 - 1e-7)
            out = jnp.asarray(
                stats.norm.ppf(np.asarray(clipped)), jnp.float32
            )
        out = out * X.row_mask(out.dtype)[:, None]
        return ShardedArray(out, X.n_rows, X.mesh)


class PolynomialFeatures(_DeviceTransformer):
    """Ref: dask_ml/preprocessing/data.py::PolynomialFeatures — the
    reference maps sklearn per block; here the monomials are one fused
    elementwise program (products of gathered columns)."""

    def __init__(self, degree=2, interaction_only=False, include_bias=True,
                 preserve_dataframe=False):
        self.degree = degree
        self.interaction_only = interaction_only
        self.include_bias = include_bias
        self.preserve_dataframe = preserve_dataframe

    def _combinations(self, d):
        comb = (itertools.combinations if self.interaction_only
                else itertools.combinations_with_replacement)
        start = 0 if self.include_bias else 1
        return [c for deg in range(start, self.degree + 1)
                for c in comb(range(d), deg)]

    def fit(self, X, y=None):
        X = self._sharded(X)
        self.n_features_in_ = d = X.shape[1]
        self._combos = self._combinations(d)
        self.n_output_features_ = len(self._combos)
        return self

    def transform(self, X):
        check_is_fitted(self, "n_output_features_")
        X = self._sharded(X)
        data = X.data
        mask = X.row_mask(data.dtype)
        cols = []
        for combo in self._combos:
            if len(combo) == 0:
                cols.append(mask)  # bias column, zeroed on padding
            else:
                c = data[:, combo[0]]
                for j in combo[1:]:
                    c = c * data[:, j]
                cols.append(c)
        out = jnp.stack(cols, axis=1)
        return ShardedArray(out, X.n_rows, X.mesh)

    def get_feature_names_out(self, input_features=None):
        if input_features is None:
            input_features = [f"x{i}" for i in range(self.n_features_in_)]
        names = []
        for combo in self._combos:
            if not combo:
                names.append("1")
            else:
                counts = {}
                for j in combo:
                    counts[j] = counts.get(j, 0) + 1
                names.append(" ".join(
                    f"{input_features[j]}^{c}" if c > 1 else input_features[j]
                    for j, c in sorted(counts.items())
                ))
        return np.asarray(names, dtype=object)
